#!/usr/bin/env python3
"""kubetpu benchmark: the BASELINE north-star metric.

Gang-schedules a 256-chip job (32 pods x 8 chips) onto a v5e-256 pod
(32 fake host-nodes, full fidelity through advertisement -> translation ->
geometric fill -> accounting -> rollback-capable gang placement) and reports
the p50 end-to-end gang schedule latency against the <100 ms BASELINE
target. Also verifies the placement is ICI-contiguous (score 1.0) — a fast
but wrong placement doesn't count.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}
vs_baseline = target_ms / p50_ms (>1.0 means faster than the 100 ms target).
"""

import json
import statistics
import sys
import time

sys.path.insert(0, ".")

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.core import Cluster  # noqa: E402
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager  # noqa: E402
from kubetpu.plugintypes import ResourceTPU  # noqa: E402

TARGET_MS = 100.0
NUM_HOSTS = 32  # v5e-256 = 32 hosts x 8 chips
ROUNDS = 20


def build_cluster() -> Cluster:
    cluster = Cluster()
    for host in range(NUM_HOSTS):
        mgr = new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-256", host_index=host))
        cluster.register_node(f"v5e256-h{host:02d}", device=mgr)
    return cluster


def gang():
    return [
        PodInfo(
            name=f"w{i:02d}",
            running_containers={"main": ContainerInfo(requests={ResourceTPU: 8})},
        )
        for i in range(NUM_HOSTS)
    ]


def _fragmented_scenario() -> dict:
    """Adversarial leg (p50 alone hides tail behavior): hold a random ~30%
    of chips as 1-chip pods, then measure 8-chip placements on what's left.
    Full suite of adversarial configs: ``schedsim --config 8 9 10``."""
    import random

    rng = random.Random(42)
    cluster = build_cluster()
    singles = []
    for h in range(NUM_HOSTS):
        for i in range(8):
            p = PodInfo(
                name=f"hold-{h}-{i}",
                running_containers={"main": ContainerInfo(requests={ResourceTPU: 1})},
            )
            cluster.schedule(p, lambda n, hh=f"v5e256-h{h:02d}": n == hh)
            singles.append(p.name)
    rng.shuffle(singles)
    for name in singles[int(len(singles) * 0.30):]:
        cluster.release(name)
    lat = []
    for r in range(2 * ROUNDS):
        p = PodInfo(
            name=f"q{r}",
            running_containers={"main": ContainerInfo(requests={ResourceTPU: 8})},
        )
        t0 = time.perf_counter()
        cluster.schedule(p)
        lat.append((time.perf_counter() - t0) * 1e3)
        cluster.release(p.name)
    lat.sort()
    return {
        "fragmented_pod_p50_ms": round(statistics.median(lat), 3),
        "fragmented_pod_p99_ms": round(lat[min(len(lat) - 1, int(0.99 * len(lat)))], 3),
    }


def main() -> int:
    cluster = build_cluster()
    latencies_ms = []
    for round_idx in range(ROUNDS):
        pods = gang()
        t0 = time.perf_counter()
        placed = cluster.schedule_gang(pods)
        dt_ms = (time.perf_counter() - t0) * 1e3
        contiguity = cluster.gang_contiguity(placed)
        if contiguity != 1.0:
            print(
                json.dumps(
                    {
                        "metric": "256-chip gang schedule p50 latency",
                        "value": -1.0,
                        "unit": "ms",
                        "vs_baseline": 0.0,
                        "error": f"non-contiguous placement (score {contiguity})",
                    }
                )
            )
            return 1
        latencies_ms.append(dt_ms)
        for p in placed:
            cluster.release(p.name)

    p50 = statistics.median(latencies_ms)
    p99 = sorted(latencies_ms)[min(ROUNDS - 1, int(0.99 * ROUNDS))]
    print(
        json.dumps(
            {
                "metric": "256-chip gang schedule p50 latency",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(TARGET_MS / p50, 3),
                "p99_ms": round(p99, 3),
                **_fragmented_scenario(),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
