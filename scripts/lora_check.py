#!/usr/bin/env python3
"""``make lora-check`` — the multi-tenant adapter-serving oracle.

Boots a router + 2 PACKED multi-LoRA paged replicas IN-PROCESS on the
CPU backend, injects >=10% wire faults (drop / injected 503 / truncated
response) on the adapter hot-load leg (``/adapters``) plus a lighter
mix on ``/generate``, drives a per-tenant storm through keyed,
retrying client POSTs — including hot-loads past the replica HBM
budget so LRU eviction fires under pressure — and fails (exit 1) on:

- PARITY: any tenant's routed greedy tokens differing from a quiet
  single-tenant run on ``merge_lora(base, adapter)`` — the packed
  stack, per-slot retargeting, adapter-salted prefix keys, retries
  and hot-load churn must all be invisible in the token stream;
- DOUBLE RESIDENCY: a replayed / retried push occupying two stack
  indices, or directory bookkeeping skewing from the stack
  (``check_invariants``' adapter-directory oracle, run per drain);
- STALE SERVING: a request naming an evicted adapter being served at
  all (it must refuse — names resolve through the directory at
  enqueue, never through a cached index);
- the accounting identity ``resident == initial + loads - evicts`` on
  every replica (a double-load breaks it without an extra evict);
- faults that never actually fired (a chaos run that injected nothing
  proves nothing).

Runs in well under a minute with no accelerator; wired into
``make chaos`` so every fault-injection run also proves thousand-tenant
packing serves each tenant exactly.
"""

import os
import sys
import urllib.error

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 — backend already initialized
    pass

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.lora import (  # noqa: E402
    LoraConfig, init_lora_params, merge_lora)
from kubetpu.jobs.multi_lora import (  # noqa: E402
    PagedMultiLoraDecodeServer, adapter_fingerprint)
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402
from kubetpu.router import ReplicaServer, RouterServer  # noqa: E402
from kubetpu.router.adapters import AdapterRegistry  # noqa: E402
from kubetpu.wire.faults import FaultInjector, RoutePolicy  # noqa: E402
from kubetpu.wire.httpcommon import request_json  # noqa: E402

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
LCFG = LoraConfig(rank=4, alpha=8.0)
PS = 8
MAX_NEW = 4
N_ADAPTERS = 6          # tenants in the registry...
CAPACITY = 4            # ...over a 4-deep replica stack: pressure
# >=10% total injection on the adapter hot-load leg (the round's new
# wire surface), plus a lighter mix on generate to keep the data plane
# honest while adapters churn
ADAPTER_FAULTS = RoutePolicy(drop=0.05, error=0.04, partial=0.04)
GEN_FAULTS = RoutePolicy(drop=0.03, error=0.03, partial=0.03)


def fail(msg: str) -> None:
    print(f"lora-check: FAIL: {msg}")
    sys.exit(1)


def _adapter(seed: int):
    a = init_lora_params(jax.random.PRNGKey(seed), CFG, LCFG)
    keys = jax.random.split(jax.random.PRNGKey(seed + 100),
                            len(a["blocks"]))
    for i, (k, v) in enumerate(sorted(a["blocks"].items())):
        if k.endswith("_b"):
            a["blocks"][k] = jax.random.normal(
                keys[i], v.shape, v.dtype) * 0.05
    return a


def make_server(base, adapters):
    return PagedMultiLoraDecodeServer(
        CFG, base, LCFG, adapters, max_adapters=CAPACITY, n_slots=2,
        max_seq=64, max_new_tokens=MAX_NEW, page_size=PS,
        prefill_budget=PS, prefix_cache_pages=16)


def tenant_prompts(tenant: int):
    """Two prompts per tenant sharing a one-page prefix (so the salted
    prefix tree engages) plus a short loner."""
    fam = [(i * (tenant + 3)) % 60 + 1 for i in range(PS)]
    return [fam + [tenant + 1], fam + [tenant + 11], [tenant + 20, 2, 3]]


def main() -> int:
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(s) for s in range(1, N_ADAPTERS + 1)]
    names = [adapter_fingerprint(a) for a in adapters]

    # the quiet oracle: each tenant alone on the merged model
    expected = {}
    for t, a in enumerate(adapters):
        ref = PagedDecodeServer(
            CFG, merge_lora(base, a, LCFG), n_slots=1, max_seq=64,
            max_new_tokens=MAX_NEW, page_size=PS, prefill_budget=PS,
            prefix_cache_pages=16)
        for p in tenant_prompts(t):
            rid = ref.enqueue(p)
            ref.drain()
            expected[(t, tuple(p))] = ref.pop_result(rid)

    registry = AdapterRegistry()
    for a in adapters:
        registry.register(a)

    injector = FaultInjector(seed=23, routes={
        "/adapters": ADAPTER_FAULTS, "/generate": GEN_FAULTS})
    replicas = []
    for i in range(2):
        # both replicas boot with the first two tenants resident
        rep = ReplicaServer(make_server(base, adapters[:2]), f"ml{i}",
                            faults=injector, idle_wait=0.002)
        rep.start()
        replicas.append(rep)
    router = RouterServer(load_refresh_s=0.05, adapters=registry)
    router.start()
    try:
        for rep in replicas:
            router.register_replica(rep.address)

        def audit():
            for rep in replicas:
                rep.server.check_invariants()
                res = rep.server.resident_adapters()
                if len(set(res)) != len(res):
                    fail(f"{rep.name}: duplicate residency {res}")

        def generate(t: int, prompt, key: str):
            body = request_json(
                router.address + "/generate",
                {"prompt": prompt, "adapter": names[t], "timeout": 30.0},
                idempotency_key=key, timeout=30.0)
            want = expected[(t, tuple(prompt))]
            if body["tokens"] != want:
                fail(f"tenant {t} prompt {prompt[:3]}...: routed "
                     f"{body['tokens']} != merged oracle {want} "
                     f"(replica {body['replica']})")
            return body

        # phase 1 — hot-load tenants 2..3 everywhere (stack now full),
        # then a per-tenant storm across all four resident tenants
        for name in names[2:CAPACITY]:
            for rep in replicas:
                registry.push_adapter(rep.address, name, timeout=30.0)
        audit()
        n_gen = 0
        for t in range(CAPACITY):
            for j, p in enumerate(tenant_prompts(t)):
                generate(t, p, f"lora-check-p1-{t}-{j}")
                n_gen += 1
        audit()

        # replayed pushes are no-ops: same content, fresh keys
        before = [tuple(rep.server.resident_adapters())
                  for rep in replicas]
        for name in names[:CAPACITY]:
            registry.push_adapter(replicas[0].address, name, timeout=30.0)
        if tuple(replicas[0].server.resident_adapters()) != before[0]:
            fail("replayed pushes changed residency: "
                 f"{before[0]} -> {replicas[0].server.resident_adapters()}")
        audit()

        # phase 2 — pressure: tenants 4..5 displace LRU residents
        evicted = set()
        for name in names[CAPACITY:]:
            for rep in replicas:
                was = set(rep.server.resident_adapters())
                registry.push_adapter(rep.address, name, timeout=30.0)
                now = set(rep.server.resident_adapters())
                evicted |= was - now
                if name not in now:
                    fail(f"{rep.name}: pushed {name} not resident")
        if not evicted:
            fail("no LRU eviction under pressure — capacity not binding")
        audit()
        for t in range(CAPACITY, N_ADAPTERS):
            for j, p in enumerate(tenant_prompts(t)):
                generate(t, p, f"lora-check-p2-{t}-{j}")
                n_gen += 1
        audit()

        # an evicted tenant must REFUSE, never serve stale factors
        gone = sorted(evicted)[0]
        t_gone = names.index(gone)
        stale_served = 0
        try:
            request_json(
                router.address + "/generate",
                {"prompt": [1, 2, 3], "adapter": gone, "timeout": 10.0},
                idempotency_key="lora-check-stale", timeout=10.0)
            stale_served = 1
        except urllib.error.HTTPError:
            pass
        except Exception:  # noqa: BLE001 — drop/partial surface as URLError
            pass
        if stale_served:
            fail(f"evicted adapter {gone} was served")

        # ...and hot-loading it back restores exact parity
        for rep in replicas:
            registry.push_adapter(rep.address, gone, timeout=30.0)
        audit()
        for j, p in enumerate(tenant_prompts(t_gone)):
            generate(t_gone, p, f"lora-check-p3-{t_gone}-{j}")
            n_gen += 1
        audit()

        # accounting identity per replica: a replay that double-loaded
        # would bump loads without a matching evict
        for rep in replicas:
            srv = rep.server
            loads = int(srv.obs.counter(
                "kubetpu_adapter_loads_total").value)
            evicts = int(srv.obs.counter(
                "kubetpu_adapter_evicts_total").value)
            res = len(srv.resident_adapters())
            if res != 2 + loads - evicts:
                fail(f"{rep.name}: residency identity broken — "
                     f"{res} resident != 2 initial + {loads} loads "
                     f"- {evicts} evicts")

        fired = dict(injector.counts)
        if sum(fired.values()) == 0:
            fail("no faults fired — the soak proved nothing; raise rates")
    finally:
        router.shutdown()
        for rep in replicas:
            rep.shutdown(graceful=False)

    print(f"lora-check OK: {n_gen} routed per-tenant generations "
          f"token-exact vs merged, {len(evicted)} LRU evictions under "
          f"pressure, stale names refused, faults fired {fired}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
