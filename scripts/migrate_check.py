#!/usr/bin/env python3
"""``make migrate-check`` — the live-KV-migration oracle.

Boots a router + 2 paged serving replicas (prefix cache on)
IN-PROCESS on the CPU backend, injects >=10% wire faults
(drop / injected 503 / truncated response) on the ``/migrate_in``
transfer leg, drives waves of long decode streams through keyed router
POSTs while ROLLING ``/migrate_out`` sweeps ping-pong the in-flight
streams between the replicas, and fails (exit 1) on:

- PARITY: any migrated stream's tokens differing byte-for-byte from a
  quiet unmigrated run (token-exact resume is the whole point —
  retries, replays, prefix-remaps and mid-stream handoffs
  notwithstanding);
- DOUBLE RESTORE / DOUBLE ADMISSION: the epoch-fence + idempotency
  counters must balance — source-side committed handoffs == target-side
  committed restores, zero ambiguous outcomes under the generous retry
  budget, fresh admissions == logical requests (a restore is a
  ``migrate_in``, never an ``admit``), and a deliberately forged stale
  commit must be FENCED 409 (the counter asserts exactly one, from the
  probe);
- an UNSTITCHED handoff trace: one traced migration must render
  source-replica and target-replica spans under a single trace id;
- the POOL ORACLE (``check_invariants``) on BOTH replicas after every
  wave, and faults that never actually fired.

Runs in well under a minute with no accelerator; wired into
``make chaos`` so every fault-injection run also proves a slot handoff
is exact and at-most-once.
"""

import os
import sys
import threading
import time

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 — backend already initialized
    pass

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402
from kubetpu.obs import span  # noqa: E402
from kubetpu.router import ReplicaServer, RouterServer  # noqa: E402
from kubetpu.router.migration import chunk_b64, encode_snapshot  # noqa: E402
from kubetpu.wire.faults import FaultInjector, RoutePolicy  # noqa: E402
from kubetpu.wire.httpcommon import RetryPolicy, request_json  # noqa: E402

# the storm clients chase streams that keep hopping: give them a wider
# retry budget than the default so an unluckily-timed 502 retries into
# the post-ping-pong calm instead of surfacing
STORM_RETRY = RetryPolicy(attempts=6, deadline=55.0)

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8
MAX_NEW = 96
WAVES = 3           # always-run waves
EXTRA_WAVES = 2     # top-up waves, run only until faults have fired
WAVE_STREAMS = 3
# >=10% total injection on the migrate leg (25% here — the leg is only
# a few dozen POSTs per run, and a chaos run that fires nothing proves
# nothing; the top-up waves keep even an unlucky seed honest): drop +
# injected 503 + truncated response (the latter manufactures the
# lost-commit-ack replay window)
MIG_FAULTS = RoutePolicy(drop=0.10, error=0.08, partial=0.07)


def fail(msg: str) -> None:
    print(f"migrate-check: FAIL: {msg}")
    sys.exit(1)


def make_server(params):
    return PagedDecodeServer(
        CFG, params, n_slots=4, max_seq=128, max_new_tokens=MAX_NEW,
        page_size=PS, prefix_cache_pages=24)


def storm_prompts():
    """One shared-prefix family + loners, (WAVES + EXTRA_WAVES) x
    WAVE_STREAMS total — the family exercises the
    restore-remaps-cached-pages path."""
    fam = [(i * 5) % 60 + 1 for i in range(2 * PS)]
    prompts = []
    for i in range((WAVES + EXTRA_WAVES) * WAVE_STREAMS):
        if i % 3 == 2:
            prompts.append([(i * 11) % 60 + 1 for j in range(12)])
        else:
            prompts.append(fam + [i + 1])
    return prompts


def mig_counter(rep, result):
    total = 0
    for name, labels, kind, inst in rep.server.obs.snapshot():
        if (name == "kubetpu_migrations_total"
                and dict(labels).get("result") == result):
            total += int(inst.value)
    return total


def main() -> int:
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = storm_prompts()

    # the quiet oracle: one replica, serial, no wire, no faults
    direct = make_server(params)
    expected = []
    for p in prompts:
        rid = direct.enqueue(p)
        direct.drain()
        expected.append(direct.pop_result(rid))

    injector = FaultInjector(seed=13, routes={"/migrate_in": MIG_FAULTS})
    replicas = []
    for i in range(2):
        rep = ReplicaServer(make_server(params), f"mchk{i}",
                            faults=injector, idle_wait=0.002)
        rep.start()
        replicas.append(rep)
    router = RouterServer(load_refresh_s=0.1)
    router.start()
    results = [None] * len(prompts)
    try:
        for rep in replicas:
            router.register_replica(rep.address)

        def one(i):
            results[i] = request_json(
                router.address + "/generate",
                {"prompt": prompts[i], "timeout": 60.0},
                idempotency_key=f"migrate-check-{i}", timeout=60.0,
                retry=STORM_RETRY)

        def sweep(src, dst, trace=False):
            """One /migrate_out sweep src -> dst; returns committed."""
            if trace:
                with span("migrate-check.handoff") as root:
                    res = request_json(
                        src.address + "/migrate_out",
                        {"target": dst.address, "reason": "check",
                         "wait": True},
                        idempotency_key=f"mc-sweep-{time.monotonic()}",
                        timeout=60.0)
                    return res.get("migrated", 0), root.trace_id
            res = request_json(
                src.address + "/migrate_out",
                {"target": dst.address, "reason": "check", "wait": True},
                idempotency_key=f"mc-sweep-{time.monotonic()}",
                timeout=60.0)
            return res.get("migrated", 0), None

        committed_sweeps = 0
        trace_id = None
        ran = 0
        for wave in range(WAVES + EXTRA_WAVES):
            if (wave >= WAVES
                    and sum(injector.counts.values()) > 0
                    and committed_sweeps >= 2):
                break        # top-up waves only run until faults fired
            threads = []
            for j in range(WAVE_STREAMS):
                i = wave * WAVE_STREAMS + j
                t = threading.Thread(target=one, args=(i,), daemon=True)
                t.start()
                threads.append(t)
            ran += WAVE_STREAMS
            # ping-pong the wave's in-flight streams between the
            # replicas (up to 4 hops) so the migrate leg sees real
            # traffic; the first committing sweep is traced so the
            # stitching oracle has a handoff to render
            for _hop in range(4):
                deadline = time.monotonic() + 20.0
                src = None
                while src is None and time.monotonic() < deadline:
                    for rep in replicas:
                        with rep._cv:
                            if rep.server.migratable_rids():
                                src = rep
                                break
                    if src is None and not any(
                            t.is_alive() for t in threads):
                        break
                    time.sleep(0.003)
                if src is None:
                    break
                dst = replicas[1] if src is replicas[0] else replicas[0]
                n, tid = sweep(src, dst, trace=(trace_id is None))
                committed_sweeps += n
                if n and tid:
                    trace_id = tid
                # a breather between hops: the routed requests' re-pin
                # chase must be able to catch a stream between handoffs
                time.sleep(0.05)
            for t in threads:
                t.join(90.0)
                if t.is_alive():
                    fail("a routed stream never completed")
            for rep in replicas:
                rep.server.check_invariants()

        # 1) parity: every stream's tokens == the quiet direct run
        for i, (body, want) in enumerate(zip(results[:ran],
                                             expected[:ran])):
            if body is None or body.get("tokens") != want:
                fail(f"request {i}: routed tokens != quiet direct run "
                     f"(got {body and body.get('tokens')}, want {want})")

        # 2) the at-most-once ledger: committed out == committed in,
        # nothing ambiguous, zero fenced (before the probe), and fresh
        # admissions == logical requests (restores are migrate_in
        # events, never admits)
        out_committed = sum(mig_counter(rep, "committed")
                            for rep in replicas)
        ambiguous = sum(mig_counter(rep, "ambiguous") for rep in replicas)
        in_committed = sum(
            int(rep.server.obs.counter(
                "kubetpu_migrations_in_total",
                result="committed").value) for rep in replicas)
        fenced = sum(
            int(rep.server.obs.counter(
                "kubetpu_migrations_fenced_total").value)
            for rep in replicas)
        if out_committed < 2:
            fail(f"only {out_committed} committed handoffs — the storm "
                 f"exercised nothing; raise stream length")
        if out_committed != in_committed:
            fail(f"{out_committed} committed handoffs at sources vs "
                 f"{in_committed} committed restores at targets — a "
                 f"lost ack double-restored or a restore went missing")
        if ambiguous:
            fail(f"{ambiguous} ambiguous handoffs under a generous "
                 f"retry budget — the transfer leg is flakier than the "
                 f"injected faults explain")
        if fenced:
            fail(f"{fenced} fence hits before the probe — a duplicate "
                 f"handoff generation reached commit")
        admits = sum(len(rep.server.events.events(kind="admit"))
                     for rep in replicas)
        migrate_ins = sum(len(rep.server.events.events(kind="migrate_in"))
                          for rep in replicas)
        if admits != ran:
            fail(f"{admits} fresh admissions for {ran} logical "
                 f"requests — a handoff double-admitted")
        if migrate_ins != in_committed:
            fail(f"{migrate_ins} migrate_in events vs {in_committed} "
                 f"committed restores")

        # 3) the epoch fence catches a forged stale handoff: replay the
        # ledger's highest committed epoch for an already-handled stream
        # under FRESH idempotency keys — only the fence can refuse it
        probe_rep = next(rep for rep in replicas if rep._mig_epochs)
        okey, epoch = next(iter(probe_rep._mig_epochs.items()))
        victim = make_server(params)
        vrid = victim.enqueue(prompts[0])
        while len(victim._emitted.get(vrid, [])) < 2:
            victim.step()
        snap = victim.snapshot_slot(vrid)
        snap["origin"] = [okey[0], okey[1]]
        snap["epoch"] = epoch
        meta, blob = encode_snapshot(snap)
        tok = {"origin": [okey[0], okey[1]], "epoch": epoch}
        import urllib.error
        request_json(probe_rep.address + "/migrate_in",
                     {"phase": "begin", "token": tok, "meta": meta},
                     idempotency_key="mc-forge-begin", timeout=30.0)
        request_json(probe_rep.address + "/migrate_in",
                     {"phase": "chunk", "token": tok, "seq": 0,
                      "data": chunk_b64(blob)},
                     idempotency_key="mc-forge-c0", timeout=30.0)
        try:
            request_json(probe_rep.address + "/migrate_in",
                         {"phase": "commit", "token": tok, "n_chunks": 1,
                          "arrays": meta["arrays"],
                          "ship_from_page": 0},
                         idempotency_key="mc-forge-commit", timeout=30.0)
            fail("forged stale-epoch commit was ACCEPTED — the fence "
                 "is not fencing")
        except urllib.error.HTTPError as e:
            if e.code != 409:
                fail(f"forged stale commit got HTTP {e.code}, want 409")
        fenced = sum(
            int(rep.server.obs.counter(
                "kubetpu_migrations_fenced_total").value)
            for rep in replicas)
        # >= 1, not == 1: the probe's own commit rides the faulted
        # /migrate_in leg, and a truncated 409 response makes the keyed
        # retry re-execute the (side-effect-free) fence check — a
        # second counter tick with no second restore
        if fenced < 1:
            fail(f"fence counter reads {fenced} after the probe, "
                 f"want >= 1")

        # 4) the faults actually fired (a chaos run that injected
        # nothing proves nothing), and replays were observed somewhere
        fired = dict(injector.counts)
        if sum(fired.values()) == 0:
            fail("no faults fired on the migrate leg; raise rates")

        # 5) one handoff renders source AND target replica spans under
        # one trace id
        if trace_id is None:
            fail("no traced handoff was captured")
        trace = router.trace(trace_id)
        comps = {s.get("component", "") for s in trace["spans"]}
        rep_comps = {c for c in comps if c.startswith("replica:")}
        if len(rep_comps) < 2:
            fail(f"handoff trace {trace_id} did not stitch source and "
                 f"target replica spans (components: {sorted(comps)})")

        # 6) both pools honest after the whole storm
        for rep in replicas:
            rep.server.check_invariants()
        repins = int(router._c_repin.value)
    finally:
        router.shutdown()
        for rep in replicas:
            rep.shutdown(graceful=False)

    print(f"migrate-check OK: {out_committed} token-exact handoffs under "
          f"injected faults ({dict(injector.counts)}), "
          f"{in_committed} restores / 0 double, {repins} router re-pins, "
          f"fence probe refused, pools clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
