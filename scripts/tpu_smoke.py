#!/usr/bin/env python3
"""Real-TPU smoke: compiled Pallas flash attention vs XLA dense attention —
numerics and wall-clock on the local chip. Run directly on a TPU VM:

    python scripts/tpu_smoke.py [--seq 2048] [--dtype bf16]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs.model import dense_causal_attention
from kubetpu.ops import flash_attention


def bench(fn, *args, iters=20):
    out = fn(*args)
    jax.block_until_ready(out)  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3, out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    args = ap.parse_args()

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    print(f"device: {jax.devices()[0]}")
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (
        jax.random.normal(kk, (args.batch, args.seq, args.heads, args.dim), dtype)
        for kk in keys
    )

    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, 128, 128, False))
    dense = jax.jit(dense_causal_attention)

    t_flash, out_flash = bench(flash, q, k, v)
    print(f"shape (B,S,H,D)=({args.batch},{args.seq},{args.heads},{args.dim}) {args.dtype}")
    print(f"flash  : {t_flash:8.3f} ms/iter")

    try:
        t_dense, out_dense = bench(dense, q, k, v)
        print(f"dense  : {t_dense:8.3f} ms/iter   speedup x{t_dense / t_flash:.2f}")
        diff = np.max(
            np.abs(np.asarray(out_flash, np.float32) - np.asarray(out_dense, np.float32))
        )
    except Exception as e:  # noqa: BLE001 — dense OOMs where flash doesn't
        print(f"dense  : OOM/failed ({type(e).__name__}) — the O(S^2) score matrix "
              "doesn't fit; flash's O(S*D) does. Verifying numerics on a slice.")
        small = slice(0, min(args.seq, 1024))
        qs, ks, vs = q[:, small], k[:, small], v[:, small]
        out_small = jax.jit(lambda q, k, v: flash_attention(q, k, v, 128, 128, False))(qs, ks, vs)
        ref_small = dense(qs, ks, vs)
        diff = np.max(
            np.abs(np.asarray(out_small, np.float32) - np.asarray(ref_small, np.float32))
        )

    print(f"max |diff| = {diff:.4g}")
    tol = 3e-2 if dtype == jnp.bfloat16 else 3e-4
    assert diff < tol, f"numerics mismatch: {diff} >= {tol}"

    # fused backward: grad through the kernel at a size the dense path can
    # still check (small slice), then a full-size fwd+bwd smoke
    small = slice(0, min(args.seq, 512))
    qs, ks, vs = (x[:, small].astype(jnp.float32) for x in (q, k, v))
    gf = jax.jit(jax.grad(lambda a, b, c: jnp.sum(flash_attention(a, b, c, 128, 128, False) ** 2)))(qs, ks, vs)
    gd = jax.jit(jax.grad(lambda a, b, c: jnp.sum(dense_causal_attention(a, b, c) ** 2)))(qs, ks, vs)
    rel = float(jnp.max(jnp.abs(gf - gd)) / (jnp.max(jnp.abs(gd)) + 1e-9))
    print(f"fused bwd dq rel diff (S=512) = {rel:.3e}")
    assert rel < 2e-2
    g = jax.jit(jax.grad(lambda a, b, c: jnp.sum(flash_attention(a, b, c, 128, 128, False).astype(jnp.float32) ** 2)))(q, k, v)
    jax.block_until_ready(g)
    print(f"fused fwd+bwd at S={args.seq}: OK")

    # paged attention: the COMPILED kernel must match the XLA gather
    # reference (interpret-mode parity is pinned in tests/test_paged.py;
    # this is the real-silicon leg VERDICT r2 asked for)
    from kubetpu.jobs.paged import _attend_paged
    from kubetpu.ops.paged_attention import paged_attention

    bq, hq, hkv, dq, ps, n_pool, max_pages = 4, 8, 4, 64, 128, 16, 4
    keys = jax.random.split(jax.random.PRNGKey(9), 3)
    qq = jax.random.normal(keys[0], (bq, hq, dq), jnp.bfloat16)
    kp = jax.random.normal(keys[1], (n_pool, ps, hkv, dq), jnp.bfloat16)
    vp = jax.random.normal(keys[2], (n_pool, ps, hkv, dq), jnp.bfloat16)
    table = jnp.asarray(
        [[5, 2, 7, -1], [0, 3, -1, -1], [9, 8, 1, 11], [15, -1, -1, -1]],
        jnp.int32,
    )
    pos = jnp.asarray([300, 140, 511, 60], jnp.int32)
    out_k = jax.jit(lambda *a: paged_attention(*a))(qq, kp, vp, table, pos)
    ref_k = jax.jit(_attend_paged)(qq, kp, vp, table, pos)
    pdiff = np.max(np.abs(np.asarray(out_k, np.float32) - np.asarray(ref_k, np.float32)))
    print(f"paged attention (compiled) max |diff| = {pdiff:.4g}")
    assert pdiff < 3e-2

    # Round-15 variants on real silicon: in-kernel int8 dequant, the
    # banded decode mask, a wider pages_per_block tile, and the
    # multi-token chunk kernel (interpret parity rides tier-1; this is
    # the compiled leg)
    from functools import partial as _partial

    from kubetpu.jobs.paged import _attend_paged_chunk
    from kubetpu.jobs.quant import quantize_kv_chunk
    from kubetpu.ops.paged_attention import paged_attention_chunk

    k8 = quantize_kv_chunk(kp.astype(jnp.float32))
    v8 = quantize_kv_chunk(vp.astype(jnp.float32))
    qf = qq.astype(jnp.float32)
    out8 = jax.jit(lambda *a: paged_attention(*a))(qf, k8, v8, table, pos)
    ref8 = jax.jit(_attend_paged)(qf, k8, v8, table, pos)
    d8 = np.max(np.abs(np.asarray(out8) - np.asarray(ref8)))
    print(f"paged attention int8 (compiled) max |diff| = {d8:.4g}")
    assert d8 < 3e-2
    out_w2 = jax.jit(_partial(paged_attention, window=200))(
        qq, kp, vp, table, pos)
    ref_w2 = jax.jit(_partial(_attend_paged, window=200))(
        qq, kp, vp, table, pos)
    dw = np.max(np.abs(np.asarray(out_w2, np.float32)
                       - np.asarray(ref_w2, np.float32)))
    print(f"paged attention banded (compiled) max |diff| = {dw:.4g}")
    assert dw < 3e-2
    out_p2 = jax.jit(_partial(paged_attention, pages_per_block=2))(
        qq, kp, vp, table, pos)
    dp2 = np.max(np.abs(np.asarray(out_p2, np.float32)
                        - np.asarray(ref_k, np.float32)))
    print(f"paged attention ppb=2 (compiled) max |diff| = {dp2:.4g}")
    assert dp2 < 3e-2
    qc = jax.random.normal(jax.random.PRNGKey(13), (bq, 5, hq, dq),
                           jnp.bfloat16)
    pos_c = jnp.asarray([296, 136, 500, 56], jnp.int32)
    out_c = jax.jit(lambda *a: paged_attention_chunk(*a))(
        qc, kp, vp, table, pos_c)
    ref_c = jax.jit(_attend_paged_chunk)(qc, kp, vp, table, pos_c)
    dc = np.max(np.abs(np.asarray(out_c, np.float32)
                       - np.asarray(ref_c, np.float32)))
    print(f"paged chunk kernel (compiled) max |diff| = {dc:.4g}")
    assert dc < 3e-2

    # sliding-window flash (round 4): compiled block-skip bounds vs the
    # dense band reference, forward AND gradient (interpret parity is
    # pinned in tests/test_ops.py; this is the real-silicon leg)
    from kubetpu.jobs.model import dense_attention

    W = 1024
    kw = jax.random.split(jax.random.PRNGKey(11), 3)
    qw, kw_, vw = (jax.random.normal(kk, (2, 4096, 8, 64), jnp.bfloat16)
                   for kk in kw)
    out_w = jax.jit(
        lambda a, b, c: flash_attention(a, b, c, 128, 128, False, True, W)
    )(qw, kw_, vw)
    jax.block_until_ready(out_w)  # 4096 exercises the block-skip bounds
    try:
        ref_w = jax.jit(
            lambda a, b, c: dense_attention(a, b, c, causal=True, window=W)
        )(qw, kw_, vw)
        wdiff = np.max(np.abs(np.asarray(out_w, np.float32)
                              - np.asarray(ref_w, np.float32)))
    except Exception:  # noqa: BLE001 — dense band OOMs first on small HBM
        # parity on a dense-feasible slice; the full-size compiled run
        # above already proved the kernel executes
        qs_, ks_, vs_ = (x[:, :1024] for x in (qw, kw_, vw))
        out_s = jax.jit(
            lambda a, b, c: flash_attention(a, b, c, 128, 128, False, True, W)
        )(qs_, ks_, vs_)
        ref_s = dense_attention(qs_, ks_, vs_, causal=True, window=W)
        wdiff = np.max(np.abs(np.asarray(out_s, np.float32)
                              - np.asarray(ref_s, np.float32)))
    print(f"windowed flash (compiled) max |diff| = {wdiff:.4g}")
    assert wdiff < 3e-2
    # all three cotangents: argnums=(0,1,2) keeps BOTH backward kernels
    # (dQ and dK/dV) live in the compiled graph — grad of q alone would
    # let XLA dead-code the dK/dV pallas_call
    gq_w, gk_w, gv_w = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(
            flash_attention(a, b, c, 128, 128, False, True, W
                            ).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2),
    ))(qw, kw_, vw)
    for name, g_ in (("dq", gq_w), ("dk", gk_w), ("dv", gv_w)):
        assert bool(jnp.isfinite(g_.astype(jnp.float32)).all()), name
    print("windowed flash backward finite (dq, dk, dv)")

    # round 5: int8 KV cache and windowed paged serving on real silicon —
    # greedy token parity against their bf16/dense counterparts, compiled
    # on the chip (the CPU tests prove the math; this proves the XLA TPU
    # lowering of int8 scatter/gather and the ring page table)
    import dataclasses

    from kubetpu.jobs import ModelConfig, init_params
    from kubetpu.jobs.decode import make_generate
    from kubetpu.jobs.paged import PagedDecodeServer
    from kubetpu.jobs.serving import DecodeServer

    scfg = ModelConfig(vocab=256, d_model=128, n_layers=2, n_heads=8,
                       n_kv_heads=4, d_ff=256, max_seq=256,
                       dtype=jnp.bfloat16)
    sparams = init_params(jax.random.PRNGKey(0), scfg)
    sprompt = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                 scfg.vocab, jnp.int32)
    t_ref = make_generate(scfg)(sparams, sprompt, jax.random.PRNGKey(2), 24)
    t_q8 = make_generate(scfg, kv_int8=True)(sparams, sprompt,
                                             jax.random.PRNGKey(2), 24)
    jax.block_until_ready((t_ref, t_q8))
    q8_agree = float(jnp.mean((t_ref == t_q8).astype(jnp.float32)))
    print(f"int8 KV cache greedy agreement on-chip: {q8_agree:.3f}")
    assert q8_agree > 0.9  # untrained bf16 model: near-ties may flip

    wscfg = dataclasses.replace(scfg, window=32)
    dense_srv = DecodeServer(wscfg, sparams, n_slots=2, max_seq=256,
                             max_new_tokens=16)
    paged_srv = PagedDecodeServer(wscfg, sparams, n_slots=2, max_seq=256,
                                  max_new_tokens=16, page_size=8)
    pr = [3, 14, 15, 9, 2, 6, 5, 3, 5]
    rd, rp = dense_srv.submit(pr), paged_srv.submit(pr)
    dense_srv.drain(); paged_srv.drain()
    assert dense_srv.result(rd) == paged_srv.result(rp), (
        "windowed paged diverged from dense banded on-chip")
    print("windowed paged serving == dense banded (on-chip)")
    print("OK")


if __name__ == "__main__":
    main()
