#!/usr/bin/env python3
"""``make sched-check`` — the Round-21 fit-index equivalence oracle.

Drives a LARGE fake fleet (128 v5e-8 hosts, 1024 chips) through mixed
scheduling churn — whole-chip pods, vChip (fractional) pods, gang
launches, random releases, priority preemption, cordon/uncordon,
drain, node refresh and node removal — with the cluster's
``index_cross_check`` oracle armed: every index-pruned sweep is
shadowed by the reference full O(fleet) sweep, and the run fails
(exit 1) on:

- DECISION DIVERGENCE: the index path trying a different (node, score)
  than the full sweep would — the equivalence guarantee, enforced live
  (``Cluster._schedule_inner`` raises, this script turns it into a
  failure);
- INVARIANT VIOLATION: ``Cluster.check_invariants()`` non-empty at the
  phase boundaries (the index/accounting audit rides it: every clean
  index entry must equal a fresh recompute from the node's books, every
  bucket must mirror its entry, and the pod->node map must match
  placements both directions);
- INDEX/ACCOUNTING DRIFT after a DELIBERATE DESYNC: the script corrupts
  an index entry behind the cluster's back, proves the audit CATCHES it
  and that scheduling remains CORRECT anyway (twin-cluster comparison
  against an index-disabled cluster fed the identical op stream), then
  repairs the index and proves the audit goes quiet;
- FALLBACK-SWEEP correctness: with the index kill switch engaged
  (``use_fit_index=False``) the same op stream must produce identical
  placements — the pruned path and the pure sweep are the same
  scheduler.

Runs in seconds with no accelerator; wired into ``make chaos`` so every
fault-injection run also proves the fit index never changes a placement
decision.
"""

import random
import sys

sys.path.insert(0, ".")

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.core import Cluster, SchedulingError  # noqa: E402
from kubetpu.core.cluster import PriorityKey  # noqa: E402
from kubetpu.device import (  # noqa: E402
    make_fake_tpus_info,
    new_fake_tpu_dev_manager,
)
from kubetpu.plugintypes import ResourceTPU  # noqa: E402
from kubetpu.scheduler.meshstate import FracKey  # noqa: E402

N_NODES = 128
OPS = 1200
SEED = 20260807


def fail(msg: str) -> None:
    print(f"sched-check: FAIL: {msg}")
    sys.exit(1)


def oracle(cluster: Cluster, phase: str) -> None:
    problems = cluster.check_invariants()
    if problems:
        fail(f"invariants violated after {phase}: {problems[:3]}")


def fleet(use_fit_index: bool) -> Cluster:
    c = Cluster(use_fit_index=use_fit_index)
    for i in range(N_NODES):
        c.register_node(
            f"n{i:04d}",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-8", slice_uid=f"s{i}")
            ),
        )
    return c


def whole_pod(name: str, chips: int) -> PodInfo:
    return PodInfo(
        name=name,
        requests={},
        running_containers={
            "main": ContainerInfo(requests={ResourceTPU: chips})
        },
    )


def frac_pod(name: str, milli: int) -> PodInfo:
    return PodInfo(
        name=name,
        requests={FracKey: milli},
        running_containers={"main": ContainerInfo()},
    )


def churn(cluster: Cluster, record=None, replay=None):
    """One deterministic mixed-op stream. With *record* (a list), every
    placement lands in it as (pod, node) for later comparison; *replay*
    asserts placements equal a recorded stream op by op — the
    twin-cluster equivalence check (index on vs off)."""
    rng = random.Random(SEED)
    placed = []  # pod names alive, swap-pop victim picks
    k = [0]

    def note(pod_name: str, node_name: str) -> None:
        if record is not None:
            record.append((pod_name, node_name))
        if replay is not None:
            want = replay[k[0]]
            if want != (pod_name, node_name):
                fail(
                    "twin-cluster divergence at op "
                    f"{k[0]}: index path placed {want}, pure sweep "
                    f"placed {(pod_name, node_name)}"
                )
            k[0] += 1

    for op in range(OPS):
        r = rng.random()
        if r < 0.28 and placed:
            j = rng.randrange(len(placed))
            placed[j], placed[-1] = placed[-1], placed[j]
            cluster.release(placed.pop())
        elif r < 0.33:
            # maintenance churn: cordon a random node for a while
            name = f"n{rng.randrange(N_NODES):04d}"
            if name in cluster.nodes:
                cluster.cordon(name, on=name not in cluster.cordoned)
        elif r < 0.36:
            # gang launch across one slice's worth of hosts
            gang = [whole_pod(f"g{op}-{m}", 4) for m in range(2)]
            try:
                for p in cluster.schedule_gang(gang):
                    placed.append(p.name)
                    note(p.name, p.node_name)
            except SchedulingError:
                pass
        elif r < 0.38:
            pod = whole_pod(f"hi{op}", 8)
            pod.requests[PriorityKey] = 10
            try:
                got, evicted = cluster.schedule_preempting(pod)
            except SchedulingError:
                pass
            else:
                for v in evicted:
                    if v.name in placed:
                        placed.remove(v.name)
                placed.append(got.name)
                note(got.name, got.node_name)
        elif r < 0.7:
            pod = whole_pod(f"c{op}", rng.choice([1, 1, 2, 2, 4, 8]))
            try:
                got = cluster.schedule(pod)
            except SchedulingError:
                pass
            else:
                placed.append(got.name)
                note(got.name, got.node_name)
        else:
            pod = frac_pod(f"v{op}", rng.choice([125, 250, 500]))
            try:
                got = cluster.schedule(pod)
            except SchedulingError:
                pass
            else:
                placed.append(got.name)
                note(got.name, got.node_name)
        if op % 300 == 299:
            oracle(cluster, f"churn op {op}")
    # lifecycle tail: drain one loaded node, refresh another, remove a
    # third — the paths that REPLACE allocatable dicts must re-hook the
    # index's dirty notifications
    for name, action in (("n0003", "drain"), ("n0005", "refresh"),
                         ("n0007", "remove")):
        if name not in cluster.nodes:
            continue
        if action == "drain":
            migrated, unplaced = cluster.drain(name)
            for pod in unplaced:
                if pod.name in placed:
                    placed.remove(pod.name)
            cluster.cordon(name, on=False)
        elif action == "refresh":
            cluster.refresh_node(name)
        else:
            for pod_name in list(cluster.nodes[name].pods):
                if pod_name in placed:
                    placed.remove(pod_name)
            cluster.remove_node(name)
        oracle(cluster, action)
    return placed


def main() -> int:
    # Phase 1: cross-checked churn — every pruned sweep shadowed by the
    # reference full sweep; any divergence raises inside the cluster.
    c = fleet(use_fit_index=True)
    c.index_cross_check = True
    record: list = []
    try:
        churn(c, record=record)
    except RuntimeError as e:
        fail(f"cross-check divergence: {e}")
    oracle(c, "cross-checked churn")
    stats = c.index_stats
    if not stats["pruned_sweeps"]:
        fail("the index never pruned a sweep — the fast path is dead")
    if not stats["cross_checks"]:
        fail("the oracle never fired — cross-checking is miswired")
    print(
        f"sched-check: phase 1 OK — {len(record)} placements, "
        f"{stats['pruned_sweeps']} pruned sweeps, "
        f"{stats['cross_checks']} cross-checked, "
        f"{stats['fallback_sweeps']} fallbacks"
    )

    # Phase 2: twin cluster with the kill switch engaged replays the
    # identical op stream — placements must match (pod, node) exactly.
    plain = fleet(use_fit_index=False)
    churn(plain, replay=record)
    oracle(plain, "pure-sweep twin churn")
    if plain.index_stats["pruned_sweeps"]:
        fail("the disabled index pruned a sweep — kill switch broken")
    print(f"sched-check: phase 2 OK — pure-sweep twin matched all "
          f"{len(record)} placements")

    # Phase 3: deliberate desync. Corrupt one live index entry behind
    # the cluster's back: the audit must CATCH it, and repairing (mark
    # dirty -> lazy recompute) must make it go quiet again.
    victim = next(iter(sorted(c.nodes)))
    entry = c.fit_index.entries.get(victim)
    if entry is None:
        fail(f"no index entry for {victim} after churn")
    entry.free_tpu += 3  # books now disagree with the index
    problems = c.check_invariants()
    if not any("fit index" in p for p in problems):
        fail("check_invariants missed a deliberately desynced entry")
    c.fit_index.mark_dirty(victim)  # the repair path: lazy recompute
    pod = whole_pod("post-desync", 1)
    try:
        got = c.schedule(pod)  # forces ensure_fresh before the query
        c.release(got.name)
    except SchedulingError:
        pass
    oracle(c, "desync repair")
    print("sched-check: phase 3 OK — audit caught the desync, "
          "dirty-repair cleared it")

    print("sched-check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
