#!/usr/bin/env python3
"""``make tier-check`` — the tiered-KV-cache oracle (Round-19).

Proves the three tiers move KV WITHOUT moving tokens, under faults:

- HOST arm: a 3-family storm whose working set overflows a tiny HBM
  tree budget, so LRU victims SPILL to host buffers and returning
  families FILL them back — greedy tokens must equal the cold
  (reuse-off) server on every request, spills/fills/savings must all
  actually engage, and the pool + tree oracles must hold throughout;
- PEER arm: two ReplicaServers; the cold one pulls each family's span
  from the warm one over ``/prefix_fetch`` with >=10% injected
  drop/503/partial on that leg — parity on every request, and the
  fetch ledger (hit + miss + degraded) must account for every attempt;
- degrade probes: a DARK peer (nothing listening), a scripted 503
  absorbed by the retry budget, and a scripted double-drop that must
  fall back to cold prefill — each token-exact.

Runs in about a minute on the CPU backend; wired into ``make chaos`` so
every fault-injection run also proves tiering can only remove work.
"""

import os
import sys

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 — backend already initialized
    pass

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402
from kubetpu.router import ReplicaServer  # noqa: E402
from kubetpu.wire.faults import FaultInjector, RoutePolicy  # noqa: E402
from kubetpu.wire.httpcommon import request_json  # noqa: E402

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8
BUDGET = 4          # HBM tree pages: two 2-page families fill it


def fail(msg: str) -> None:
    print(f"tier-check: FAIL: {msg}")
    sys.exit(1)


def fam(seed):
    return [(i * seed) % 60 + 1 for i in range(2 * PS)]


def make(params, host=1 << 22, budget=BUDGET):
    return PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=6, page_size=PS,
                             prefill_budget=PS,
                             prefix_cache_pages=budget,
                             host_tier_bytes=host)


def run(server, prompts, check=False):
    rids = [server.enqueue(p) for p in prompts]
    server.drain()
    outs = [server.pop_result(r) for r in rids]
    if check:
        server.check_invariants()
    return outs


def main() -> int:
    params = init_params(jax.random.PRNGKey(0), CFG)
    cold = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=6, page_size=PS,
                             prefill_budget=PS)

    def ref(prompts):
        return run(cold, prompts)

    # -- HOST arm: 3 families cycling through a 2-family HBM budget ----------
    fams = [fam(s) for s in (5, 7, 11)]
    waves = []
    for tail in range(3):
        for f, head in enumerate(fams):
            waves.append([head + [f * 10 + tail + 1]])
    prompts = [p for w in waves for p in w]
    want = ref(prompts)

    warm = make(params)
    got = []
    try:
        for wave in waves:
            got.extend(run(warm, wave, check=True))
    except AssertionError as e:
        fail(f"HOST arm: pool oracle violated mid-storm: {e}")
    if got != want:
        bad = [i for i, (g, r) in enumerate(zip(got, want)) if g != r]
        fail(f"HOST arm parity: requests {bad} diverged through the "
             f"host tier")
    ts = warm.tier_stats()
    if ts["spills"]["host"] == 0:
        fail(f"HOST arm never spilled: {ts}")
    if ts["fills"]["host"] == 0:
        fail(f"HOST arm never filled back: {ts}")
    if ts["tokens_saved"]["host"] == 0:
        fail(f"host tier saved no prefill tokens: {ts}")
    if warm._prefix_cache.host_bytes > warm.host_tier_bytes:
        fail("host tier past its byte budget")
    try:
        warm._prefix_cache.check()
    except AssertionError as e:
        fail(f"HOST arm tree oracle: {e}")

    # -- PEER arm: cold replica pulls spans under injected faults ------------
    inj = FaultInjector(seed=7, routes={
        "/prefix_fetch": RoutePolicy(drop=0.05, error=0.05, partial=0.05),
    })
    ra = ReplicaServer(make(params), "tier-a", faults=inj, idle_wait=0.002)
    rb = ReplicaServer(make(params), "tier-b", idle_wait=0.002)
    ua = ra.start()
    rb.start()
    peer_fams = [fam(s) for s in (5, 7, 11, 13, 17, 19, 23, 29)]
    try:
        for i, head in enumerate(peer_fams):
            body = request_json(ra.address + "/generate",
                                {"prompt": head + [1]},
                                idempotency_key=f"tc-warm-{i}", timeout=30)
            if body["tokens"] != ref([head + [1]])[0]:
                fail(f"PEER arm: warm-side family {i} diverged")
        attempts = 0
        for i, head in enumerate(peer_fams):
            p = head + [2]
            body = request_json(rb.address + "/generate",
                                {"prompt": p, "prefix_peer": ua},
                                idempotency_key=f"tc-peer-{i}", timeout=30)
            attempts += 1
            if body["tokens"] != ref([p])[0]:
                fail(f"PEER arm parity: family {i} diverged through the "
                     f"peer fetch (injected faults must degrade to cold, "
                     f"never corrupt)")

        def fetch_counts():
            out = {"hit": 0, "miss": 0, "degraded": 0}
            for line in rb.server.metrics_text().splitlines():
                if line.startswith("kubetpu_peer_prefix_fetch_total"):
                    for k in out:
                        if f'result="{k}"' in line:
                            out[k] = int(float(line.rsplit(" ", 1)[1]))
            return out

        counts = fetch_counts()
        if sum(counts.values()) != attempts:
            fail(f"fetch ledger leaks: {counts} over {attempts} attempts")
        if counts["hit"] == 0:
            fail(f"PEER arm never landed a fetch: {counts}")
        if rb.server.tier_stats()["tokens_saved"]["peer"] == 0:
            fail("peer tier saved no prefill tokens")

        # dark peer: nothing listening — degrade within the retry
        # deadline, cold-prefill token-exactly
        p = fam(31) + [1]
        body = request_json(rb.address + "/generate",
                            {"prompt": p,
                             "prefix_peer": "http://127.0.0.1:9"},
                            idempotency_key="tc-dark", timeout=30)
        if body["tokens"] != ref([p])[0]:
            fail("dark-peer probe diverged")
        if fetch_counts()["degraded"] <= counts["degraded"]:
            fail("dark peer did not count as degraded")

        # scripted single 503: the retry budget (2 attempts) absorbs it
        request_json(ra.address + "/generate", {"prompt": fam(37) + [1]},
                     idempotency_key="tc-warm-503", timeout=30)
        inj.set_route("/prefix_fetch", RoutePolicy(error=1.0, times=1))
        body = request_json(rb.address + "/generate",
                            {"prompt": fam(37) + [2], "prefix_peer": ua},
                            idempotency_key="tc-503", timeout=30)
        if body["tokens"] != ref([fam(37) + [2]])[0]:
            fail("retry-through-503 probe diverged")
        if fetch_counts()["hit"] <= counts["hit"]:
            fail("a single injected 503 defeated the retry budget")

        # scripted double drop: past the retry budget — must fall back
        # to cold prefill, not error the generate
        request_json(ra.address + "/generate", {"prompt": fam(41) + [1]},
                     idempotency_key="tc-warm-drop", timeout=30)
        inj.set_route("/prefix_fetch", RoutePolicy(drop=1.0, times=2))
        before = fetch_counts()["degraded"]
        body = request_json(rb.address + "/generate",
                            {"prompt": fam(41) + [2], "prefix_peer": ua},
                            idempotency_key="tc-drop", timeout=30)
        if body["tokens"] != ref([fam(41) + [2]])[0]:
            fail("double-drop probe diverged")
        if fetch_counts()["degraded"] <= before:
            fail("a dropped fetch did not count as degraded")

        injected = sum(inj.counts.values())
        total_fetches = sum(fetch_counts().values())
        try:
            ra.server.check_invariants()
            rb.server.check_invariants()
        except AssertionError as e:
            fail(f"PEER arm pool oracle: {e}")
    finally:
        ra.shutdown(graceful=False)
        rb.shutdown(graceful=False)

    print(f"tier-check: OK — host arm {len(prompts)} requests "
          f"(spills {ts['spills']['host']}, fills {ts['fills']['host']}, "
          f"saved {ts['tokens_saved']['host']} tokens); "
          f"peer arm {total_fetches} fetches "
          f"(hit {fetch_counts()['hit']}, "
          f"degraded {fetch_counts()['degraded']}, "
          f"{injected} injected faults), oracles clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
