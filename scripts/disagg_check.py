#!/usr/bin/env python3
"""``make disagg-check`` — the disaggregated prefill/decode oracle.

Boots a router + 1 PREFILL replica + 2 DECODE replicas (paged servers,
prefix cache on, chunked prefill) IN-PROCESS on the CPU backend,
injects >=10% wire faults (drop / injected 503 / truncated response) on
the KV-stream leg (``/migrate_in`` — begin, every streamed span chunk,
and the commit all ride it), drives waves of mixed long-prompt/
short-prompt requests through keyed router POSTs, and fails (exit 1)
on:

- PARITY: any routed stream's tokens differing byte-for-byte from a
  quiet colocated run (the decode replica must emit exactly what a
  single server would have — prefix remaps, streamed spans, replays
  and the handoff notwithstanding);
- the HANDOFF LEDGER: committed handoffs == logical requests (every
  stream moved, none silently degraded to colocated under the retry
  budget), committed == decode-side committed restores, zero
  ambiguous/aborted/refused outcomes, and fresh admissions == requests
  fleet-wide (a restore is a ``migrate_in``, never an ``admit`` — the
  zero-double-admission guarantee under lost acks);
- NO PIPELINING: zero pages streamed before prefill finished would
  mean the spans all shipped at commit — the overlap is the point;
- an UNSTITCHED handoff trace: one handoff must render prefill-replica
  and decode-replica spans under a single trace id;
- the POOL ORACLE (``check_invariants``) on ALL THREE pools after the
  storm, and faults that never actually fired.

Runs in well under a minute with no accelerator; wired into
``make chaos`` so every fault-injection run also proves the
disaggregated topology is exact and at-most-once.
"""

import os
import sys
import threading

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 — backend already initialized
    pass

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402
from kubetpu.router import ReplicaServer, RouterServer  # noqa: E402
from kubetpu.wire.faults import FaultInjector, RoutePolicy  # noqa: E402
from kubetpu.wire.httpcommon import RetryPolicy, request_json  # noqa: E402

STORM_RETRY = RetryPolicy(attempts=6, deadline=55.0)

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8
MAX_NEW = 24
WAVES = 3
WAVE_STREAMS = 3
# >=10% total injection on the KV-stream leg: the streamed spans give
# this leg dozens of POSTs per run, so moderate per-POST rates still
# fire plenty while the 4-attempt keyed retries keep every handoff
# committing (an abort would silently degrade the topology — the
# ledger assert below is exactly that guard)
MIG_FAULTS = RoutePolicy(drop=0.05, error=0.04, partial=0.05)


def fail(msg: str) -> None:
    print(f"disagg-check: FAIL: {msg}")
    sys.exit(1)


def make_server(params):
    return PagedDecodeServer(
        CFG, params, n_slots=4, max_seq=128, max_new_tokens=MAX_NEW,
        page_size=PS, n_pages=64, prefill_budget=8,
        prefix_cache_pages=24)


def storm_prompts():
    """Mixed long-prompt/short-prompt traffic: one shared-prefix long
    family (exercises the begin-phase hint — warm decode-side pages
    never cross the wire; both caches cold, its FIRST member streams
    spans) plus medium cold loners whose multi-chunk prefills are the
    reliable early-streaming window (budget 8 -> ~6+ chunk steps per
    loner, far wider than a fault-retry backoff)."""
    fam = [(i * 5) % 60 + 1 for i in range(10 * PS)]
    prompts = []
    for i in range(WAVES * WAVE_STREAMS):
        if i % 3 == 2:
            prompts.append([(i * 11 + j) % 60 + 1 for j in range(48)])
        else:
            prompts.append(fam + [i + 1])
    return prompts


def handoff_counter(rep, result):
    return int(rep.server.obs.counter(
        "kubetpu_handoffs_total", result=result).value)


def main() -> int:
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = storm_prompts()

    # the quiet oracle: one colocated replica, serial, no wire
    direct = make_server(params)
    expected = []
    for p in prompts:
        rid = direct.enqueue(p)
        direct.drain()
        expected.append(direct.pop_result(rid))

    injector = FaultInjector(seed=7, routes={"/migrate_in": MIG_FAULTS})
    prefill = ReplicaServer(make_server(params), "dchk-pre", faults=None,
                            role="prefill", idle_wait=0.002)
    decodes = [ReplicaServer(make_server(params), f"dchk-dec{i}",
                             faults=injector, role="decode",
                             idle_wait=0.002)
               for i in range(2)]
    replicas = [prefill] + decodes
    for rep in replicas:
        rep.start()
    router = RouterServer(load_refresh_s=0.1)
    router.start()
    results = [None] * len(prompts)
    try:
        for rep in replicas:
            router.register_replica(rep.address)

        def one(i):
            results[i] = request_json(
                router.address + "/generate",
                {"prompt": prompts[i], "timeout": 60.0},
                idempotency_key=f"disagg-check-{i}", timeout=60.0,
                retry=STORM_RETRY)

        for wave in range(WAVES):
            threads = []
            for j in range(WAVE_STREAMS):
                t = threading.Thread(
                    target=one, args=(wave * WAVE_STREAMS + j,),
                    daemon=True)
                t.start()
                threads.append(t)
            for t in threads:
                t.join(90.0)
                if t.is_alive():
                    fail("a routed stream never completed")
            for rep in replicas:
                rep.server.check_invariants()

        # 1) parity: every stream's tokens == the quiet colocated run,
        # and every stream was EMITTED by a decode replica
        for i, (body, want) in enumerate(zip(results, expected)):
            if body is None or body.get("tokens") != want:
                fail(f"request {i}: routed tokens != quiet colocated "
                     f"run (got {body and body.get('tokens')}, "
                     f"want {want})")
            if body.get("replica") == prefill.name:
                fail(f"request {i} was emitted by the PREFILL replica "
                     f"— its handoff silently degraded to colocated")

        # 2) the handoff ledger: every logical request handed off
        # exactly once, restores == commits, nothing ambiguous, and
        # fleet-wide fresh admissions == requests (a restore is a
        # migrate_in, never an admit — zero double-admissions under
        # lost acks)
        committed = handoff_counter(prefill, "committed")
        bad = {r: handoff_counter(prefill, r)
               for r in ("aborted", "refused", "ambiguous", "fenced",
                         "skipped")}
        if committed != len(prompts):
            fail(f"{committed} committed handoffs for {len(prompts)} "
                 f"requests (other outcomes: {bad})")
        if any(bad.values()):
            fail(f"non-committed handoff outcomes under a generous "
                 f"retry budget: {bad}")
        restores = sum(
            int(rep.server.obs.counter(
                "kubetpu_migrations_in_total",
                result="committed").value) for rep in replicas)
        if restores != committed:
            fail(f"{committed} committed handoffs at the source vs "
                 f"{restores} committed restores at targets — a lost "
                 f"ack double-restored or a restore went missing")
        admits = sum(len(rep.server.events.events(kind="admit"))
                     for rep in replicas)
        if admits != len(prompts):
            fail(f"{admits} fresh admissions for {len(prompts)} "
                 f"logical requests — a handoff double-admitted")
        migrate_ins = sum(
            len(rep.server.events.events(kind="migrate_in"))
            for rep in replicas)
        if migrate_ins != restores:
            fail(f"{migrate_ins} migrate_in events vs {restores} "
                 f"committed restores")

        # 3) pipelining actually happened: pages shipped BEFORE their
        # prefill finished, on the faulted leg
        streamed = int(prefill.server.obs.counter(
            "kubetpu_handoff_pages_streamed_total").value)
        if streamed <= 0:
            fail("zero pages streamed before prefill finished — the "
                 "transfer degenerated to a commit-time blob")
        if prefill._handoff_bytes <= 0 or prefill._handoff_early_bytes <= 0:
            fail("handoff byte accounting is empty")
        overlap = prefill._handoff_early_bytes / prefill._handoff_bytes

        # 4) warm decode-side prefix pages never crossed the wire: the
        # shared family re-lands where its prefix is published, so
        # some restores MUST have mapped cached pages read-only — a
        # broken begin-phase hint would read 0 here while every byte
        # silently ships
        remapped = sum(
            int(rep.server.obs.counter(
                "kubetpu_migration_pages_remapped_total").value)
            for rep in decodes)
        if remapped <= 0:
            fail("zero pages satisfied by the decode-side prefix "
                 "cache — the begin-phase hint shipped warm pages")

        # 5) the faults actually fired on the KV-stream leg
        fired = dict(injector.counts)
        if sum(fired.values()) == 0:
            fail("no faults fired on the KV-stream leg; raise rates")

        # 6) one handoff renders prefill-replica AND decode-replica
        # spans under a single trace id
        commits = prefill.events.events(kind="handoff_commit")
        tid = next((e.get("trace_id") for e in commits
                    if e.get("trace_id")), None)
        if tid is None:
            fail("no handoff_commit event carries a trace id")
        trace = router.trace(tid)
        comps = {s.get("component", "") for s in trace["spans"]}
        rep_comps = {c for c in comps if c.startswith("replica:")}
        if len(rep_comps) < 2:
            fail(f"handoff trace {tid} did not stitch prefill and "
                 f"decode replica spans (components: {sorted(comps)})")

        # 7) all three pools honest after the whole storm
        for rep in replicas:
            rep.server.check_invariants()
    finally:
        router.shutdown()
        for rep in replicas:
            rep.shutdown(graceful=False)

    print(f"disagg-check OK: {committed} token-exact prefill->decode "
          f"handoffs under injected faults ({dict(injector.counts)}), "
          f"{streamed} pages streamed mid-prefill "
          f"(overlap {overlap:.2f}), {remapped} warm pages never "
          f"shipped, admissions == requests, pools clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
