#!/usr/bin/env python3
"""``make router-check`` — the data-plane routing oracle.

Boots a router + 2 paged serving replicas (prefix cache on) IN-PROCESS
on the CPU backend, injects >=10% wire faults (drop / injected 5xx /
truncated response) on BOTH the router surface and every replica's
``/generate``, drives a 3-family shared-prefix storm through keyed,
retrying client POSTs, and fails (exit 1) on:

- PARITY: any routed request's greedy tokens differing from a quiet
  direct serial run on one replica (routing must be semantics-free —
  affinity placement, prefix-cache hits, retries and replays
  notwithstanding);
- DOUBLE ALLOCATION: total generate EXECUTIONS (and serving ``admit``
  events) across the fleet differing from the number of logical
  requests — a retried POST whose first response was lost must be
  REPLAYED by the idempotency window, never re-admitted;
- an UNSTITCHED trace: the storm's traced request must render router
  and replica spans under one trace id (the router hop
  ``kubetpu.cli.obs --trace`` draws);
- the POOL ORACLE (``check_invariants``) on any replica after the
  storm, and faults that never actually fired (a chaos run that
  injected nothing proves nothing).

Runs in well under a minute with no accelerator; wired into
``make chaos`` so every fault-injection run also proves the data plane
routes exactly and never double-admits.
"""

import os
import sys

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 — backend already initialized
    pass

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402
from kubetpu.obs import span  # noqa: E402
from kubetpu.router import ReplicaServer, RouterServer  # noqa: E402
from kubetpu.wire.faults import FaultInjector, RoutePolicy  # noqa: E402
from kubetpu.wire.httpcommon import request_json  # noqa: E402

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8
MAX_NEW = 5
# >=10% total injection on the generate legs: 4% drop + 4% injected 503
# + 4% truncated response (the double-allocation manufacturing fault)
GEN_FAULTS = RoutePolicy(drop=0.04, error=0.04, partial=0.04)


def fail(msg: str) -> None:
    print(f"router-check: FAIL: {msg}")
    sys.exit(1)


def make_server(params):
    return PagedDecodeServer(
        CFG, params, n_slots=2, max_seq=64, max_new_tokens=MAX_NEW,
        page_size=PS, prefill_budget=PS, prefix_cache_pages=16)


def storm_prompts():
    """Three shared-prefix families x tails + a sub-page loner."""
    prompts = []
    for f, seed in enumerate((5, 7, 11)):
        fam = [(i * seed) % 60 + 1 for i in range(2 * PS)]
        for tail in range(3):
            prompts.append(fam + [f * 10 + tail + 1])
    prompts.append([63] * 3)
    return prompts


def main() -> int:
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = storm_prompts()

    # the quiet oracle: one replica, serial, no wire, no faults
    direct = make_server(params)
    expected = []
    for p in prompts:
        rid = direct.enqueue(p)
        direct.drain()
        expected.append(direct.pop_result(rid))

    injector = FaultInjector(seed=11, routes={"/generate": GEN_FAULTS})
    replicas = []
    for i in range(2):
        rep = ReplicaServer(make_server(params), f"chk{i}",
                            faults=injector, idle_wait=0.002)
        rep.start()
        replicas.append(rep)
    router = RouterServer(load_refresh_s=0.1, faults=injector)
    router.start()
    try:
        for rep in replicas:
            router.register_replica(rep.address)

        results = []
        trace_id = None
        for i, p in enumerate(prompts):
            if i == len(prompts) // 2 and trace_id is None:
                with span("router-check.generate") as root:
                    body = request_json(
                        router.address + "/generate",
                        {"prompt": p, "timeout": 30.0},
                        idempotency_key=f"router-check-{i}", timeout=30.0)
                    trace_id = root.trace_id
            else:
                body = request_json(
                    router.address + "/generate",
                    {"prompt": p, "timeout": 30.0},
                    idempotency_key=f"router-check-{i}", timeout=30.0)
            results.append(body)

        # 1) parity: routed greedy tokens == the quiet direct run
        for i, (body, want) in enumerate(zip(results, expected)):
            if body["tokens"] != want:
                fail(f"request {i}: routed tokens {body['tokens']} != "
                     f"direct {want} (replica {body['replica']})")

        # 2) no double allocation: executions + admits == logical requests
        execs = sum(
            int(rep.server.obs.counter(
                "kubetpu_replica_generate_requests_total").value)
            for rep in replicas)
        admits = sum(len(rep.server.events.events(kind="admit"))
                     for rep in replicas)
        if execs != len(prompts):
            fail(f"{execs} generate executions for {len(prompts)} logical "
                 f"requests — an idempotency-keyed retry re-executed")
        if admits != len(prompts):
            fail(f"{admits} admit events for {len(prompts)} requests — "
                 f"a lost response double-admitted")

        # 3) the faults actually fired, and a replay actually happened
        # when a partial fault hit a generate leg
        fired = dict(injector.counts)
        if sum(fired.values()) == 0:
            fail("no faults fired — the soak proved nothing; raise rates")
        replays = sum(
            int(rep.server.obs.counter(
                "kubetpu_replica_generate_replays_total").value)
            for rep in replicas)
        print(f"router-check: faults fired {fired}, {replays} replays, "
              f"{execs} executions / {len(prompts)} requests")

        # 4) stitched router -> replica trace
        trace = router.trace(trace_id)
        comps = {s.get("component", "") for s in trace["spans"]}
        if "router" not in comps or not any(
                c.startswith("replica:") for c in comps):
            fail(f"trace {trace_id} did not stitch router and replica "
                 f"spans (components: {sorted(comps)})")

        # 5) the routed storm left every pool honest
        for rep in replicas:
            rep.server.check_invariants()
        hits = sum(rep.server.prefix_cache_stats()["requests_hit"]
                   for rep in replicas)
        if hits == 0:
            fail("zero prefix-cache hits through the router — affinity "
                 "routing is not engaging the radix trees")
    finally:
        router.shutdown()
        for rep in replicas:
            rep.shutdown(graceful=False)

    print("router-check OK: token-exact routing under injected faults, "
          f"no double allocation ({execs}/{len(prompts)}), "
          f"{hits} prefix hits, trace stitched")
    return 0


if __name__ == "__main__":
    sys.exit(main())
