#!/usr/bin/env python3
"""``make pack-check`` — the Round-18 fractional-packing oracle.

Schedules a mixed fractional (vChip) + whole-chip workload through the
real ``Cluster`` on fake devices and fails (exit 1) on:

- the PACKING ORACLE (``Cluster.check_invariants``) after every phase:
  Σ(fractions on a chip) must stay <= 1.0, free milli must balance
  against holds, a chip must never be whole-held AND fractionally
  occupied, and releases must restore EXACT capacity;
- ANTI-FRAGMENTATION / NO-STARVATION: after a storm of fractional
  replicas lands, a whole-chip GANG must still place — the best-fit
  policy must have concentrated the confetti on few chips instead of
  smearing it across the slice;
- fractional-preemption capacity: evicting the fractional pods of a
  chip must restore it to the whole-chip pool exactly;
- token PARITY of a packed replica: a ``PagedDecodeServer`` running on
  a quarter vChip (``pool_frac=0.25``) must emit byte-identical greedy
  tokens to an unpacked full-pool replica — a share changes capacity,
  never results.

Runs in under a minute with no accelerator; wired into ``make chaos``
so every fault-injection run also proves fractional packing doesn't
corrupt the scheduler's books.
"""

import os
import sys

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 — backend already initialized
    pass

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.core import Cluster, SchedulingError  # noqa: E402
from kubetpu.device import (  # noqa: E402
    make_fake_tpus_info,
    new_fake_tpu_dev_manager,
)
from kubetpu.plugintypes import ResourceTPU  # noqa: E402
from kubetpu.scheduler.meshstate import (  # noqa: E402
    MILLI_PER_CHIP,
    FracKey,
    parse_milli,
)


def fail(msg: str) -> None:
    print(f"pack-check: FAIL: {msg}")
    sys.exit(1)


def oracle(cluster: Cluster, phase: str) -> None:
    problems = cluster.check_invariants()
    if problems:
        fail(f"{phase}: invariants violated: {problems}")


def frac_pod(name, qty, **extra):
    return PodInfo(name=name, requests={FracKey: parse_milli(qty), **extra},
                   running_containers={"main": ContainerInfo()})


def tpu_pod(name, chips):
    return PodInfo(
        name=name,
        running_containers={
            "main": ContainerInfo(requests={ResourceTPU: chips})})


def snapshot_free(cluster: Cluster):
    """(scalar free, every /cards + /milli allocatable value) — the
    exact-restoration fingerprint."""
    out = {}
    for name, node in sorted(cluster.nodes.items()):
        for key, val in sorted(node.info.allocatable.items()):
            if key.endswith(("/cards", "/milli")) or key == ResourceTPU:
                out[(name, key)] = val
    return out


def main() -> int:
    cluster = Cluster()
    for i in range(2):
        cluster.register_node(
            f"pack-n{i}",
            device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")))
    pristine = snapshot_free(cluster)
    oracle(cluster, "registration")

    # -- phase 1: fractional workload mix ---------------------------------
    placed = []
    mix = [("250m", 6), ("500m", 3), ("0.125", 4)]
    k = 0
    for qty, count in mix:
        for _ in range(count):
            placed.append(cluster.schedule(frac_pod(f"vc{k}", qty)))
            k += 1
    # whole-chip pods ride along: the two grammars must coexist
    placed.append(cluster.schedule(tpu_pod("whole2", 2)))
    oracle(cluster, "fractional mix")
    # 6*250 + 3*500 + 4*125 = 3500 milli -> best-fit packs <= 4 chips
    occ = cluster.chip_occupancy()
    partial = sum(1 for per in occ.values()
                  for f in per.values() if 0.0 < f < 1.0)
    if partial > 4:
        fail(f"anti-fragmentation: {partial} partially-occupied chips "
             f"for 3500 milli of confetti (best-fit should need <= 4)")

    # -- phase 2: no whole-chip gang starvation ---------------------------
    try:
        gang = cluster.schedule_gang(
            [tpu_pod(f"gang{i}", 4) for i in range(2)])
    except SchedulingError as e:
        fail(f"whole-chip gang starved behind fractional confetti: {e}")
    oracle(cluster, "gang placement")
    for p in gang:
        cluster.release(p.name)

    # -- phase 3: fractional release restores exact capacity --------------
    for p in placed:
        cluster.release(p.name)
    oracle(cluster, "release")
    if snapshot_free(cluster) != pristine:
        fail("release did not restore exact capacity")

    # -- phase 4: preemption restores a fractionally-held chip ------------
    lows = [cluster.schedule(frac_pod(f"low{i}", "500m"))
            for i in range(16 * 2)]          # saturate both nodes
    oracle(cluster, "preemption setup")
    high = tpu_pod("high8", 8)
    high.requests["kubetpu/priority"] = 10
    placed_high, evicted = cluster.schedule_preempting(high)
    if len(evicted) == 0:
        fail("preemption evicted nothing for a whole-node pod")
    oracle(cluster, "preemption")
    cluster.release(placed_high.name)
    for p in lows:
        if p.name not in {e.name for e in evicted}:
            cluster.release(p.name)
    oracle(cluster, "preemption cleanup")
    if snapshot_free(cluster) != pristine:
        fail("preemption + release did not restore exact capacity")

    # -- phase 5: packed-replica token parity (pool_frac) -----------------
    import dataclasses
    import random

    from kubetpu.jobs import ModelConfig, init_params
    from kubetpu.jobs.paged import PagedDecodeServer

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    cfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = random.Random(0)
    prompts = [[rng.randrange(1, cfg.vocab) for _ in range(12)]
               for _ in range(4)]

    def serve(pool_frac):
        srv = PagedDecodeServer(
            cfg, params, n_slots=2, max_seq=32, max_new_tokens=8,
            page_size=8, n_pages=64, pool_frac=pool_frac)
        out = []
        for p in prompts:
            rid = srv.enqueue(p)
            srv.drain()
            out.append(srv.pop_result(rid))
        srv.check_invariants()
        return srv, out

    full_srv, full = serve(1.0)
    packed_srv, packed = serve(0.25)
    if full != packed:
        bad = [i for i, (a, b) in enumerate(zip(full, packed)) if a != b]
        fail(f"pool_frac parity: requests {bad} diverged")
    if packed_srv.pool_pages != full_srv.pool_pages // 4:
        fail(f"pool_frac=0.25 pool is {packed_srv.pool_pages} pages, "
             f"want {full_srv.pool_pages // 4} (honest partition)")

    print(f"pack-check: OK — {k} fractional + whole mix placed "
          f"({partial} partial chips), gang unstarved, capacity "
          f"restored exactly twice, preemption evicted "
          f"{len(evicted)} fractional pods, packed-vs-full parity on "
          f"{len(prompts)} requests (pool {packed_srv.pool_pages} vs "
          f"{full_srv.pool_pages} pages)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
