#!/usr/bin/env python3
"""``make prefix-check`` — the shared-prefix KV reuse oracle.

Runs a short shared-system-prompt storm through the paged server on the
CPU backend and fails (exit 1) on:

- PARITY: greedy tokens through prefix-cache HITS differing from the
  cold (reuse-off) server on any request — the bit-exactness contract
  the device path promises (the table is just a jit input);
- the POOL ACCOUNTING ORACLE (``PagedDecodeServer.check_invariants``)
  after every drain: free + slot-owned + tree-owned pages must equal the
  pool, shared mappings must point at tree-owned pages, refcounts must
  match live pins;
- REUSE not actually engaging (zero hits / zero tokens saved would make
  the parity check vacuous);
- leftover pins or a tree past its budget after the storm retires.

Runs in under a minute with no accelerator; wired into ``make chaos`` so
every fault-injection run also proves prefix sharing doesn't corrupt the
pool.
"""

import os
import sys

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 — backend already initialized
    pass

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8
BUDGET = 8


def fail(msg: str) -> None:
    print(f"prefix-check: FAIL: {msg}")
    sys.exit(1)


def storm_prompts():
    """Three shared-prefix families x tails + one loner: exercises hits,
    misses, branch splits and (with BUDGET=8 pages) LRU eviction."""
    fams = []
    for seed in (5, 7, 11):
        fams.append([(i * seed) % 60 + 1 for i in range(2 * PS)])
    prompts = []
    for f, fam in enumerate(fams):
        for tail in range(3):
            prompts.append(fam + [f * 10 + tail + 1])
    prompts.append([63] * 3)   # sub-page loner: never cacheable
    return prompts


def run(server, prompts, check=False):
    outs = []
    for wave in (prompts[: len(prompts) // 2], prompts[len(prompts) // 2:]):
        rids = [server.enqueue(p) for p in wave]
        server.drain()
        outs.extend(server.pop_result(r) for r in rids)
        if check:
            server.check_invariants()
    return outs


def main() -> int:
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = storm_prompts()

    cold = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=6, page_size=PS,
                             prefill_budget=PS)
    ref = run(cold, prompts)

    warm = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=6, page_size=PS,
                             prefill_budget=PS,
                             prefix_cache_pages=BUDGET)
    try:
        got = run(warm, prompts, check=True)
    except AssertionError as e:
        fail(f"pool oracle violated mid-storm: {e}")

    if got != ref:
        bad = [i for i, (g, r) in enumerate(zip(got, ref)) if g != r]
        fail(f"parity: requests {bad} diverged through prefix-cache hits")

    stats = warm.prefix_cache_stats()
    if stats["requests_hit"] == 0 or stats["prefill_tokens_saved"] == 0:
        fail(f"reuse never engaged: {stats}")
    if warm._prefix_cache.total_pages > BUDGET:
        fail(f"tree past its budget: {warm._prefix_cache.total_pages}")
    if any(n.refcount for n in warm._prefix_cache.nodes()):
        fail("leaked pins after the storm retired")
    try:
        warm.check_invariants()
    except AssertionError as e:
        fail(f"pool oracle violated after the storm: {e}")

    # Round-15 KERNEL arm (interpret): the same chunked + prefix-hit
    # storm through the fused paged-attention kernel — chunked prefill
    # AND the decode step walk the page table in the kernel, and the
    # tokens must still match the cold gather-core reference exactly
    warm_k = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                               max_new_tokens=6, page_size=PS,
                               prefill_budget=PS,
                               prefix_cache_pages=BUDGET,
                               use_kernel=True, interpret=True)
    try:
        got_k = run(warm_k, prompts, check=True)
    except AssertionError as e:
        fail(f"KERNEL arm: pool oracle violated mid-storm: {e}")
    if got_k != ref:
        bad = [i for i, (g, r) in enumerate(zip(got_k, ref)) if g != r]
        fail(f"KERNEL arm parity: requests {bad} diverged")
    stats_k = warm_k.prefix_cache_stats()
    if stats_k["requests_hit"] == 0:
        fail(f"KERNEL arm reuse never engaged: {stats_k}")
    if warm_k._c_kernel_steps.value <= 0:
        fail("KERNEL arm never ran a kernel step — parity was vacuous")

    print(f"prefix-check: OK — {len(prompts)} requests, "
          f"hits {stats['requests_hit']}, "
          f"saved {stats['prefill_tokens_saved']} prefill tokens, "
          f"evicted {stats['evicted_pages']} pages, oracle clean; "
          f"kernel arm hits {stats_k['requests_hit']}, "
          f"{int(warm_k._c_kernel_steps.value)} kernel steps")
    return 0


if __name__ == "__main__":
    sys.exit(main())
