"""Generate tests/fixtures/tiny_tokenizer.json + tiny_tokenizer_vectors.json.

Run once (committed outputs are the source of truth for CI): trains a tiny
byte-level BPE with the llama-3 pretokenizer layout via the Rust
``tokenizers`` package, then records encode vectors for a battery of
tricky strings. ``tests/test_tokenizer.py`` pins kubetpu's pure-Python
loader against these vectors WITHOUT needing ``tokenizers`` at test time
(and additionally cross-checks live when the package is present).
"""

import json
import os
import random

from tokenizers import Regex, Tokenizer, decoders, models, pre_tokenizers, trainers

# the llama-3 tiktoken-style pattern (meta-llama/Meta-Llama-3-8B tokenizer.json)
LLAMA3_PATTERN = (
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}|"
    r" ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
)

STRINGS = [
    "Hello, world!",
    "  leading and trailing  ",
    "The 1234 quick 56789 brown foxes' tails; they're odd.",
    "tabs\tand\nnewlines\r\n\r\nmixed   runs",
    "emoji \U0001f680\U0001f9e0 and accents: café naïve über",
    "CJK: 今日は世界 你好吗",
    "mixed1234numbers99and100words",
    "I'll I'd I've it's we're you'll THEY'RE",
    "punct!!! ??? ... ---- ###(nested [brackets] {braces})",
    " nbsp and zero​width",
    "",
    " ",
    "\n\n\n",
    "a",
    "<|begin_of_text|>framed<|end_of_text|>",
]


def main() -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    fixdir = os.path.join(here, "..", "tests", "fixtures")
    os.makedirs(fixdir, exist_ok=True)

    tok = Tokenizer(models.BPE(ignore_merges=True))
    tok.pre_tokenizer = pre_tokenizers.Sequence(
        [
            pre_tokenizers.Split(
                pattern=Regex(LLAMA3_PATTERN), behavior="isolated", invert=False
            ),
            pre_tokenizers.ByteLevel(add_prefix_space=False, use_regex=False),
        ]
    )
    tok.decoder = decoders.ByteLevel()

    rng = random.Random(0)
    words = [
        "the", "quick", "brown", "fox", "jumps", "over", "lazy", "dog",
        "tpu", "mesh", "slice", "kernel", "attention", "token", "batch",
        "1234", "42", "café", "über", "naïve", "hello", "world",
    ]
    corpus = [
        " ".join(rng.choice(words) for _ in range(rng.randint(3, 12)))
        + rng.choice([".", "!", "?", "...", "\n"])
        for _ in range(4000
        )
    ]
    trainer = trainers.BpeTrainer(
        vocab_size=600,
        special_tokens=["<|begin_of_text|>", "<|end_of_text|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(corpus, trainer)
    path = os.path.join(fixdir, "tiny_tokenizer.json")
    tok.save(path, pretty=True)

    vectors = {}
    for s in STRINGS:
        vectors[s] = tok.encode(s).ids
    with open(os.path.join(fixdir, "tiny_tokenizer_vectors.json"), "w") as f:
        json.dump(vectors, f, ensure_ascii=True, indent=1)
    print(f"wrote {path} (vocab {tok.get_vocab_size()}) + "
          f"{len(vectors)} vectors")


if __name__ == "__main__":
    main()
