#!/usr/bin/env python3
"""``make bench-gate`` — the serving-bench regression gate.

The ``BENCH_r0N.json`` trajectory files record one round each. Through
round 5 they carried only the scheduler bench (``parsed``); from round 6
they also carry a ``storms`` dict of serving storm metrics:

    decode_tok_s    tokens emitted per second of storm wall (higher good)
    ttft_p50_ms     chunked mixed-load TTFT p50          (lower good)
    itl_p99_ms      chunked mixed-load ITL p99           (lower good)
    router_hit_rate / router_ttft_p50_ms   Round-14 data-plane rows
    paged_kernel_decode_toks_s  Round-15: decode tok/s through the fused
                    paged-attention kernel (interpret)   (higher good)
    migration_drain_s  Round-16: drain-complete latency of a loaded
                    replica via live KV migration        (lower good)
    disagg_itl_p99_ms / disagg_decode_toks_s  Round-17: the
                    disaggregated arm of the mixed long-prompt/
                    short-decode storm (ITL lower good, tok/s higher
                    good; the colocated arm rides along un-gated as
                    colocated_* for the topology comparison)
    packing_fleet_toks_s / replicas_per_chip  Round-18: the packed
                    (vChip) arm of the multi-tenant packing storm
                    (both higher good; replicas_per_chip is the
                    scheduler's own density count, not normalized; the
                    whole-chip arm rides un-gated as packing_cmp_* at
                    --record, where the strictly-higher acceptance is
                    enforced)
    tiering_ttft_p50_ms / tiering_hit_rate  Round-19: the host-tier arm
                    of the tiered-KV-cache storm (working set 4x the
                    HBM tree budget; TTFT lower good, hit rate higher
                    good and NOT normalized); at --record the no-tier
                    and host+peer arms ride un-gated as tiering_cmp_*
                    and the Round-19 acceptance is enforced strictly:
                    host-tier TTFT p50 strictly better than no-tier,
                    host AND peer tiers each saving prefill tokens
    crash_recovery_s  Round-20: SIGKILL-to-routable latency of a
                    same-name replacement replica (boot-nonce
                    takeover) killed mid-storm    (lower good; streams
                    preserved and a takeover firing are hard guards;
                    values under the 0.25s ABS_FLOOR pass outright —
                    at the ~10ms healthy scale a relative threshold
                    would gate scheduler jitter, not regressions)
    multilora_fleet_toks_s / adapters_per_replica  Round-22: the
                    packed arm of the multi-LoRA tenancy storm — ONE
                    PagedMultiLoraDecodeServer serving every tenant's
                    closed-loop stream from shared slots (both higher
                    good; adapters_per_replica is the replica's own
                    resident count, not normalized); at --record the
                    per-tenant-replica arm rides un-gated as
                    multilora_cmp_* and the Round-22 acceptance is
                    enforced strictly: packed fleet tok/s per chip
                    strictly above per-tenant replicas at equal
                    hardware, with >=64 resident adapters, parity
                    intact
    sched_p99_ms    Round-21: per-pod schedule p99 under sustained
                    submit/release/preempt churn on a 4096-chip fleet
                    (512 v5e-8 hosts, schedsim config 15) — the
                    control-plane tail the incremental fit index
                    flattens (lower good); at --record the full
                    256-vs-4096 comparison runs and the Round-21
                    acceptance (4096-chip p99 within 3x the 256-chip
                    p99) is enforced, with the comparison rows
                    recorded un-gated as sched_cmp_*

Modes:

    bench_gate.py            gate the NEWEST round file against its
                             predecessor: >15% regression in any storm
                             metric both rounds measured -> exit 1.
                             Metrics only one side has are reported as
                             "new baseline", never gated (round 5 and
                             earlier have no storms — the first gated
                             round passes by construction and seeds the
                             trajectory).
    bench_gate.py --smoke    re-measure a tiny storm IN-PROCESS (best of
                             --repeats, noise-suppressed) and gate it
                             against the newest persisted round — fast
                             enough to ride ``make chaos``.
    bench_gate.py --record   measure (storm + scheduler bench) and write
                             the next ``BENCH_r0N.json`` so the
                             trajectory file set stays continuous.

``--threshold`` (or ``KUBETPU_BENCH_GATE_THRESHOLD``) moves the 15%
bar; wall-clock noise on shared machines is real, which is why the
smoke measurement is best-of-N per metric, not a single draw.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, ".")

HIGHER_IS_BETTER = {"decode_tok_s", "router_hit_rate",
                    "paged_kernel_decode_toks_s",
                    "disagg_decode_toks_s",
                    "packing_fleet_toks_s", "replicas_per_chip",
                    "tiering_hit_rate",
                    "multilora_fleet_toks_s", "adapters_per_replica"}
GATED = ("decode_tok_s", "ttft_p50_ms", "itl_p99_ms",
         "router_hit_rate", "router_ttft_p50_ms",
         "paged_kernel_decode_toks_s", "migration_drain_s",
         "disagg_itl_p99_ms", "disagg_decode_toks_s",
         "packing_fleet_toks_s", "replicas_per_chip",
         "tiering_ttft_p50_ms", "tiering_hit_rate",
         "crash_recovery_s", "sched_p99_ms",
         "multilora_fleet_toks_s", "adapters_per_replica")
# ratios/counters are load-independent: the host-speed calibration must
# only rescale wall-clock metrics, never a hit rate — nor the
# scheduler's replica-density count (Round-18) or the tier hit rate
# (Round-19)
NOT_NORMALIZED = {"router_hit_rate", "replicas_per_chip",
                  "tiering_hit_rate", "adapters_per_replica"}
# lower-is-better metrics whose healthy value sits at the scheduler-
# jitter scale: a relative threshold on a ~10ms measurement gates OS
# noise, not regressions. A current value at or under the floor passes
# outright; the relative gate re-engages the moment the metric drifts
# into territory a real regression (a blocking probe, a serialized
# replay) would push it to.
ABS_FLOOR = {"crash_recovery_s": 0.25}


def _round_files(root: str):
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def _calibrate(iters: int = 30, reps: int = 3) -> float:
    """Host-speed probe: best-of-*reps* wall time of a fixed numpy
    workload. Wall-clock storm metrics on shared/throttled machines
    swing uniformly with co-tenant load and cgroup CFS quota (3x+
    observed right after a jax-heavy target); recording the probe next
    to the storm lets the smoke gate normalize a uniformly-slower (or
    faster) machine out of the comparison instead of failing honest
    code."""
    import numpy as np

    a = np.random.default_rng(0).standard_normal((192, 192)).astype(
        np.float32)
    best = float("inf")
    for _ in range(reps):
        b = a
        t0 = time.perf_counter()
        for _ in range(iters):
            b = b @ a
            b /= np.abs(b).max() + 1e-9
        best = min(best, time.perf_counter() - t0)
    return best


def measure_storm(repeats: int = 3, rounds: int = 2,
                  strict: bool = False) -> dict:
    """The gate's own chunked mixed-load storm (tiny flagship config,
    DecodeServer, token-budget admission): per-metric best of *repeats*
    full runs — max tok/s, min latencies — so one co-tenant stall
    doesn't fail an honest round."""
    import dataclasses
    import random

    import jax

    from bench_model import flagship_cfg
    from kubetpu.jobs import init_params
    from kubetpu.jobs.serving import DecodeServer

    cfg = dataclasses.replace(flagship_cfg(smoke=True), remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = random.Random(0)
    # calibrate at BOTH ends of the measurement and keep the fastest
    # probe: the storm metrics are best-of-N across several minutes, so
    # they latch the quietest moment — a single-moment probe on a
    # bursty co-tenant box can sample a slow spike the storms dodged,
    # and the mismatched ratio then fails honest code in the smoke gate
    calib = _calibrate()
    longs = [[rng.randrange(1, cfg.vocab) for _ in range(56)]
             for _ in range(rounds)]
    shorts = [[rng.randrange(1, cfg.vocab) for _ in range(8)]
              for _ in range(rounds * 3)]
    best: dict = {}
    for _ in range(repeats):
        server = DecodeServer(cfg, params, n_slots=4, max_seq=64,
                              max_new_tokens=4, prefill_budget=24)
        server.warmup()
        emitted = 0
        t0 = time.perf_counter()
        for r in range(rounds):
            server.enqueue(longs[r])
            for s in range(3):
                server.enqueue(shorts[r * 3 + s])
            while not server._idle():
                for toks in server.step().values():
                    emitted += len(toks)
        wall = time.perf_counter() - t0
        stats = server.metrics_summary()
        run = {
            "decode_tok_s": round(emitted / wall, 1) if wall else 0.0,
            "ttft_p50_ms": round(stats["ttft"]["p50_ms"], 3),
            "itl_p99_ms": round(stats["itl"]["p99_ms"], 3),
        }
        for k, v in run.items():
            if k not in best:
                best[k] = v
            elif k in HIGHER_IS_BETTER:
                best[k] = max(best[k], v)
            else:
                best[k] = min(best[k], v)
    best["requests"] = rounds * 4
    best["repeats"] = repeats
    # Round-14 data plane rows: DETERMINISTIC affinity storms (serial
    # driving -> the cluster hit rate is a pure function of the
    # routing, so the ratcheted metric can't flap on thread timing);
    # the wall-clock TTFT is best-of-2 like every other storm metric —
    # a one-off scheduler stall in a single draw must not fail chaos
    from bench_model import router_storm

    router_cfg = dataclasses.replace(flagship_cfg(smoke=True), remat=False)
    for _ in range(3):   # best-of-3: the TTFT draw is jittery on 1-core hosts
        (affinity,) = router_storm(
            router_cfg,
            n_replicas=2, n_families=3, sys_len=64, tail_len=8,
            requests_per_family=3, max_new=4, page_size=16,
            prefill_budget=32, cache_pages=32, concurrency=1,
            policies=("affinity",))
        best["router_hit_rate"] = affinity["value"]
        best["router_ttft_p50_ms"] = min(
            best.get("router_ttft_p50_ms", float("inf")),
            affinity["ttft_p50_ms"])
    # Round-15 row: decode tok/s THROUGH the fused paged-attention
    # kernel (interpret mode on CPU) on a real PagedDecodeServer —
    # parity is tier-1's job; the gate watches the kernel path's
    # dispatch cost (best-of-2 like every other storm metric)
    from kubetpu.jobs.paged import PagedDecodeServer

    kcfg = dataclasses.replace(flagship_cfg(smoke=True), remat=False)
    kparams = init_params(jax.random.PRNGKey(1), kcfg)
    kprompts = [[rng.randrange(1, kcfg.vocab) for _ in range(8)]
                for _ in range(4)]
    for _ in range(2):
        server = PagedDecodeServer(kcfg, kparams, n_slots=2, max_seq=32,
                                   max_new_tokens=8, page_size=8,
                                   use_kernel=True, interpret=True)
        server.warmup()
        emitted = 0
        t0 = time.perf_counter()
        for p in kprompts:
            server.enqueue(p)
        while not server._idle():
            for toks in server.step().values():
                emitted += len(toks)
        wall = time.perf_counter() - t0
        best["paged_kernel_decode_toks_s"] = max(
            best.get("paged_kernel_decode_toks_s", 0.0),
            round(emitted / wall, 1) if wall else 0.0)
    # Round-16 row: drain-complete latency of a loaded replica through
    # LIVE MIGRATION (the elastic scale-down path) — best-of-2 VALID
    # samples: a run where the stream finished before the drain landed
    # (migrations == 0) measured an EMPTY drain and must not seed the
    # ratchet with a vacuous number no real handoff can match.
    from bench_model import migration_storm

    mig_cfg = dataclasses.replace(flagship_cfg(smoke=True), remat=False)
    valid = 0
    for _attempt in range(6):
        if valid >= 2:
            break
        (mig,) = migration_storm(
            mig_cfg, n_replicas=2, n_streams=2, prompt_len=16,
            max_new=48, page_size=16, n_slots=2, arms=("migrate",))
        if mig["streams_preserved"] != mig["requests"]:
            raise SystemExit(
                "bench-gate: migration storm dropped a stream — "
                f"{mig['streams_preserved']}/{mig['requests']} preserved")
        if mig["migrations"] < 1:
            continue            # vacuous draw: nothing actually moved
        valid += 1
        best["migration_drain_s"] = min(
            best.get("migration_drain_s", float("inf")), mig["value"])
    if valid == 0:
        raise SystemExit(
            "bench-gate: migration storm never migrated a stream — "
            "lengthen the streams")
    # Round-17 rows. The GATE keys measure the disaggregated arm alone
    # on the tiny flagship config (fast, ratchet-stable, best-of-2;
    # streams-preserved and handoffs-committed are hard correctness
    # guards). The topology COMPARISON needs a scale where serving
    # compute dominates dispatch overhead — on the tiny config the two
    # arms sit within host noise of each other — so *strict* (the
    # --record path) additionally runs both arms once at a 4-layer
    # d256 config and enforces the Round-17 acceptance: decode ITL p99
    # strictly better disaggregated, decode tok/s no worse. Its
    # numbers are recorded un-gated as *_cmp_* so the trajectory file
    # documents the comparison each round.
    from bench_model import disagg_storm

    disagg_cfg = dataclasses.replace(flagship_cfg(smoke=True), remat=False)
    for _ in range(3):   # best-of-3, same jitter argument as the router row
        (disagg,) = disagg_storm(
            disagg_cfg, n_long=3, long_len=192, n_short=5, short_len=8,
            max_new=24, page_size=16, prefill_budget=16, n_slots=8,
            n_prefill=2, n_decode=1, arms=("disagg",))
        if disagg["streams_preserved"] != disagg["requests"]:
            raise SystemExit(
                "bench-gate: disagg storm dropped a stream — "
                f"{disagg['streams_preserved']}/{disagg['requests']} "
                f"preserved")
        if disagg["handoffs_committed"] != disagg["requests"]:
            raise SystemExit(
                "bench-gate: disagg handoffs committed != requests "
                f"({disagg['handoffs_committed']} for "
                f"{disagg['requests']}) — a handoff silently degraded "
                f"or double-shipped")
        best["disagg_itl_p99_ms"] = min(
            best.get("disagg_itl_p99_ms", float("inf")),
            disagg["value"])
        best["disagg_decode_toks_s"] = max(
            best.get("disagg_decode_toks_s", 0.0),
            disagg["decode_tok_s"])
    # Round-18 rows: multi-tenant replica PACKING under fractional chip
    # virtualization. The gate keys measure the PACKED arm alone
    # (best-of-2 tok/s; replicas-per-chip is the scheduler's own count —
    # deterministic, NOT_NORMALIZED); at --record the whole-chip arm
    # rides along un-gated as packing_cmp_* and the Round-18 acceptance
    # is enforced strictly: packed fleet tok/s per chip strictly higher
    # than whole-chip granularity at equal hardware, parity intact.
    from bench_model import packing_storm

    pk_cfg = dataclasses.replace(flagship_cfg(smoke=True), remat=False)
    for _ in range(2):
        (packed,) = packing_storm(
            pk_cfg, n_tenants=4, prompt_len=8, max_new=12,
            window_s=1.0, n_slots=2, pack=4, arms=("packed",))
        if not packed["parity"]:
            raise SystemExit(
                "bench-gate: packing storm broke greedy parity — a "
                "vChip share must never change tokens")
        best["packing_fleet_toks_s"] = max(
            best.get("packing_fleet_toks_s", 0.0), packed["value"])
        best["replicas_per_chip"] = packed["replicas_per_chip"]
    # Round-22 rows: multi-LoRA tenancy — ONE packed replica holding
    # every tenant's adapter, serving all closed-loop streams from
    # shared slots through one compiled paged leg. The gate keys
    # measure the PACKED arm alone (best-of-2 tok/s; resident adapters
    # per replica is the replica's own directory count —
    # deterministic, NOT_NORMALIZED); the within-path parity rider is
    # a hard guard. The per-tenant-replica comparison arm runs at
    # --record (strict), where the Round-22 acceptance is enforced.
    from bench_model import multilora_storm

    ml_cfg = dataclasses.replace(flagship_cfg(smoke=True), remat=False)
    for _ in range(2):
        (ml,) = multilora_storm(
            ml_cfg, n_tenants=4, n_resident=16, prompt_len=8,
            max_new=12, window_s=1.0, n_slots=4, pack=4,
            arms=("packed",))
        if not ml["parity"]:
            raise SystemExit(
                "bench-gate: multilora storm broke greedy parity — "
                "cross-tenant batching must never change tokens")
        best["multilora_fleet_toks_s"] = max(
            best.get("multilora_fleet_toks_s", 0.0), ml["value"])
        best["adapters_per_replica"] = ml["adapters_per_replica"]
    # Round-19 rows: the tiered KV cache. The gate keys measure the
    # HOST-TIER arm alone on a working set 4x the HBM tree budget
    # (best-of-2 TTFT; the hit rate is deterministic under serial
    # driving — NOT_NORMALIZED); spills/fills actually engaging is a
    # hard correctness guard. The no-tier and host+peer comparison
    # arms run at --record (strict) where the Round-19 acceptance is
    # enforced.
    from bench_model import tiering_storm

    tier_cfg = dataclasses.replace(flagship_cfg(smoke=True), remat=False)
    for _ in range(2):
        (host_arm,) = tiering_storm(
            tier_cfg, n_families=4, sys_len=96, tail_len=8, rounds=3,
            max_new=4, page_size=16, prefill_budget=32, n_slots=2,
            arms=("host",))
        if host_arm["tier_spills"]["host"] == 0:
            raise SystemExit(
                "bench-gate: tiering storm never spilled — the working "
                "set must overflow the HBM budget")
        if host_arm["tier_fills"]["host"] == 0:
            raise SystemExit(
                "bench-gate: tiering storm never filled from host — "
                "returning families must find their spilled KV")
        best["tiering_ttft_p50_ms"] = min(
            best.get("tiering_ttft_p50_ms", float("inf")),
            host_arm["value"])
        best["tiering_hit_rate"] = host_arm["hit_rate"]
    # Round-20 row: hard-kill recovery — SIGKILL a loaded replica
    # mid-storm, boot a same-name replacement (boot-nonce takeover) and
    # measure kill-to-routable latency. Best-of-2 VALID samples, same
    # rule as the migration row: a draw where the streams finished
    # before the kill landed measured an UNLOADED recovery and must not
    # seed the ratchet. Streams preserved and a takeover actually
    # firing are hard correctness guards.
    from bench_model import crash_storm

    cr_cfg = dataclasses.replace(flagship_cfg(smoke=True), remat=False)
    valid = 0
    for _attempt in range(6):
        if valid >= 2:
            break
        (cr,) = crash_storm(
            cr_cfg, n_replicas=2, n_streams=2, prompt_len=16,
            max_new=48, page_size=16, n_slots=2)
        if cr["streams_preserved"] != cr["requests"]:
            raise SystemExit(
                "bench-gate: crash storm lost a keyed stream — "
                f"{cr['streams_preserved']}/{cr['requests']} preserved")
        if cr["takeovers"] < 1:
            raise SystemExit(
                "bench-gate: crash storm replacement did not take the "
                "dead handle over — the boot-nonce path regressed")
        if not cr["loaded"]:
            continue            # vacuous draw: the victim died idle
        valid += 1
        best["crash_recovery_s"] = min(
            best.get("crash_recovery_s", float("inf")), cr["value"])
    if valid == 0:
        raise SystemExit(
            "bench-gate: crash storm never killed a loaded replica — "
            "lengthen the streams")
    # Round-21 row: per-pod schedule p99 under sustained churn at fleet
    # scale — pure-CPU control-plane wall clock (normalized like the
    # other latency rows, best-of-2). The smoke runs the 4096-chip arm
    # alone; at --record (strict) the full schedsim config15 comparison
    # runs instead and the Round-21 acceptance is enforced (the config
    # asserts 4096-chip p99 < 3x the 256-chip p99), with the comparison
    # rows riding un-gated as sched_cmp_* for the trajectory. A p99
    # over 600 ops is jitter-sensitive on a loaded host, so a failed
    # draw retries (same valid-sample idiom as the storms above) — the
    # acceptance must hold on at least one draw.
    from kubetpu.cli.schedsim import churn_fleet, config15, sched_churn

    if strict:
        last_err, valid = None, 0
        for _attempt in range(4):
            if valid >= 2:
                break
            try:
                r21 = config15()
            except AssertionError as e:
                last_err = str(e)
                continue
            valid += 1
            if r21["sched_p99_ms"] < best.get("sched_p99_ms",
                                              float("inf")):
                best["sched_p99_ms"] = r21["sched_p99_ms"]
                best["sched_cmp_256_p99_ms"] = (
                    r21["chips256"]["p99_ms"])
                best["sched_cmp_p99_ratio_4096_vs_256"] = (
                    r21["p99_ratio_4096_vs_256"])
        if valid == 0:
            raise SystemExit(
                "bench-gate: the Round-21 acceptance did not hold on "
                f"any draw — {last_err}")
    else:
        for _ in range(2):
            churn = sched_churn(churn_fleet(512), 600)
            best["sched_p99_ms"] = min(
                best.get("sched_p99_ms", float("inf")), churn["p99_ms"])
    if strict:
        last_err = None
        for _attempt in range(2):
            whole, packed = packing_storm(
                pk_cfg, n_tenants=4, prompt_len=8, max_new=12,
                window_s=1.5, n_slots=2, pack=4)
            if not (whole["parity"] and packed["parity"]):
                raise SystemExit(
                    "bench-gate: packing comparison broke greedy parity")
            best["packing_cmp_whole_toks_s"] = whole["value"]
            best["packing_cmp_packed_toks_s"] = packed["value"]
            best["packing_cmp_whole_replicas_per_chip"] = (
                whole["replicas_per_chip"])
            if packed["value"] > whole["value"]:
                last_err = None
                break
            last_err = (f"packed {packed['value']} vs whole "
                        f"{whole['value']} tok/s per chip")
        if last_err is not None:
            raise SystemExit(
                "bench-gate: the Round-18 acceptance did not hold — "
                "packed fractional replicas must beat whole-chip "
                f"granularity at equal hardware ({last_err})")
    if strict:
        # Round-22 acceptance: one packed replica with >= 64 resident
        # adapters must beat per-tenant replicas (each on its own
        # Round-18 vChip) on fleet tok/s per chip at equal hardware,
        # parity intact on both compute paths.
        last_err = None
        for _attempt in range(2):
            per_tenant, ml_packed = multilora_storm(
                ml_cfg, n_tenants=8, n_resident=64, prompt_len=8,
                max_new=12, window_s=1.5, n_slots=4, pack=4)
            if not (per_tenant["parity"] and ml_packed["parity"]):
                raise SystemExit(
                    "bench-gate: multilora comparison broke greedy "
                    "parity")
            if ml_packed["adapters_per_replica"] < 64:
                raise SystemExit(
                    "bench-gate: the packed replica holds "
                    f"{ml_packed['adapters_per_replica']} adapters — "
                    "the Round-22 acceptance needs >= 64 resident")
            best["multilora_cmp_per_tenant_toks_s"] = per_tenant["value"]
            best["multilora_cmp_packed_toks_s"] = ml_packed["value"]
            best["multilora_cmp_tenants_served"] = (
                per_tenant["tenants_served"])
            if ml_packed["value"] > per_tenant["value"]:
                last_err = None
                break
            last_err = (f"packed {ml_packed['value']} vs per-tenant "
                        f"{per_tenant['value']} tok/s per chip")
        if last_err is not None:
            raise SystemExit(
                "bench-gate: the Round-22 acceptance did not hold — "
                "one packed multi-LoRA replica must beat per-tenant "
                f"replicas at equal hardware ({last_err})")
    if strict:
        import jax.numpy as jnp

        from kubetpu.jobs import ModelConfig

        cmp_cfg = ModelConfig(vocab=256, d_model=256, n_layers=4,
                              n_heads=8, d_ff=512, max_seq=512,
                              dtype=jnp.bfloat16)
        last_err = None
        for _attempt in range(2):
            coloc, disagg = disagg_storm(
                cmp_cfg, n_long=4, long_len=256, n_short=6,
                short_len=8, max_new=48, page_size=16,
                prefill_budget=16, n_slots=10, n_prefill=2, n_decode=1)
            for row in (coloc, disagg):
                if row["streams_preserved"] != row["requests"]:
                    raise SystemExit(
                        "bench-gate: disagg comparison dropped a "
                        f"stream ({row['arm']})")
            best["disagg_cmp_itl_p99_ms"] = disagg["value"]
            best["disagg_cmp_decode_toks_s"] = disagg["decode_tok_s"]
            best["colocated_cmp_itl_p99_ms"] = coloc["value"]
            best["colocated_cmp_decode_toks_s"] = coloc["decode_tok_s"]
            if (disagg["value"] < coloc["value"]
                    and disagg["decode_tok_s"] >= coloc["decode_tok_s"]):
                last_err = None
                break
            last_err = (
                f"ITL {disagg['value']} vs {coloc['value']} ms, tok/s "
                f"{disagg['decode_tok_s']} vs {coloc['decode_tok_s']}")
        if last_err is not None:
            raise SystemExit(
                "bench-gate: the Round-17 acceptance did not hold — "
                "disaggregated must beat colocated ITL p99 with tok/s "
                f"no worse ({last_err})")
    if strict:
        # Round-19 acceptance: at a working set 4x the HBM budget the
        # host tier must strictly beat dropping (no_tier), and BOTH
        # off-HBM tiers must actually save prefill tokens — the saved
        # counts are hard (deterministic); the TTFT comparison gets a
        # second attempt against co-tenant noise. The comparison arms
        # are recorded un-gated as tiering_cmp_* for the trajectory.
        last_err = None
        for _attempt in range(2):
            no_tier, host_t, peer_t = tiering_storm(
                tier_cfg, n_families=4, sys_len=96, tail_len=8,
                rounds=3, max_new=4, page_size=16, prefill_budget=32,
                n_slots=2)
            best["tiering_cmp_no_tier_ttft_p50_ms"] = no_tier["value"]
            best["tiering_cmp_host_ttft_p50_ms"] = host_t["value"]
            best["tiering_cmp_peer_ttft_p50_ms"] = peer_t["value"]
            if host_t["tier_tokens_saved"]["host"] <= 0:
                raise SystemExit(
                    "bench-gate: the host tier saved no prefill tokens")
            if peer_t["tier_tokens_saved"]["peer"] <= 0:
                raise SystemExit(
                    "bench-gate: the peer tier saved no prefill tokens")
            if host_t["value"] < no_tier["value"]:
                last_err = None
                break
            last_err = (f"host {host_t['value']} vs no-tier "
                        f"{no_tier['value']} ms TTFT p50")
        if last_err is not None:
            raise SystemExit(
                "bench-gate: the Round-19 acceptance did not hold — "
                "the host tier must strictly beat dropping at a 4x "
                f"working set ({last_err})")
    best["calib_s"] = round(min(calib, _calibrate()), 5)
    return best


def gate(cur: dict, prev: dict, threshold: float,
         cur_name: str, prev_name: str):
    """(failures, report lines) comparing the GATED metrics both sides
    measured; regression = worse than *prev* by more than *threshold*."""
    failures, report = [], []
    for key in GATED:
        c, p = cur.get(key), prev.get(key)
        if not isinstance(c, (int, float)) or not isinstance(p, (int, float)):
            report.append(f"  {key}: {c} (new baseline — "
                          f"{prev_name} did not measure it)")
            continue
        if p <= 0:
            report.append(f"  {key}: previous value {p} not gateable")
            continue
        floor = ABS_FLOOR.get(key)
        if (floor is not None and key not in HIGHER_IS_BETTER
                and c <= floor):
            report.append(f"  {key}: {p} ({prev_name}) -> {c} "
                          f"({cur_name})  [ok, under {floor}s floor]")
            continue
        reg = (p - c) / p if key in HIGHER_IS_BETTER else (c - p) / p
        verdict = "REGRESSED" if reg > threshold else "ok"
        report.append(f"  {key}: {p} ({prev_name}) -> {c} ({cur_name})  "
                      f"[{reg:+.1%} {verdict}]")
        if reg > threshold:
            failures.append(
                f"{key} regressed {reg:.1%} (> {threshold:.0%}): "
                f"{p} -> {c}")
    return failures, report


def record(root: str, repeats: int) -> str:
    """Measure this round and write the next ``BENCH_r0N.json`` —
    the legacy scheduler-bench shape (n/cmd/rc/tail/parsed) plus the
    Round-6+ ``storms`` dict the gate compares."""
    storms = measure_storm(repeats=repeats, strict=True)
    cmd = "if [ -f bench.py ]; then python bench.py; else exit 0; fi"
    proc = subprocess.run(["sh", "-c", cmd], capture_output=True,
                          text=True, cwd=root)
    tail = "\n".join((proc.stdout or "").splitlines()[-20:]) + "\n"
    parsed = {}
    for line in reversed((proc.stdout or "").splitlines()):
        try:
            parsed = json.loads(line)
            break
        except ValueError:
            continue
    rounds = _round_files(root)
    n = (rounds[-1][0] + 1) if rounds else 1
    path = os.path.join(root, f"BENCH_r{n:02d}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"n": n, "cmd": cmd, "rc": proc.returncode,
                   "tail": tail, "parsed": parsed, "storms": storms},
                  f, indent=1)
        f.write("\n")
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench-gate", description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="measure a live storm and gate it against the "
                         "newest persisted round")
    ap.add_argument("--record", action="store_true",
                    help="measure and persist the next BENCH_r0N.json")
    ap.add_argument("--threshold", type=float, default=float(
        os.environ.get("KUBETPU_BENCH_GATE_THRESHOLD", 0.15)))
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--dir", default=".")
    args = ap.parse_args(argv)

    if args.record:
        path = record(args.dir, args.repeats)
        print(f"bench-gate: recorded {path}")
        with open(path, encoding="utf-8") as f:
            print(json.dumps(json.load(f).get("storms", {}), indent=1))
        return 0

    rounds = _round_files(args.dir)
    if not rounds:
        print("bench-gate: no BENCH_r0N.json files — nothing to gate")
        return 0

    def load(path):
        with open(path, encoding="utf-8") as f:
            return json.load(f)

    if args.smoke:
        n, newest = rounds[-1]
        prev = load(newest).get("storms", {})
        if not prev:
            print(f"bench-gate --smoke: BENCH_r{n:02d}.json has no storms "
                  f"(pre-round-6 file) — run --record first; passing")
            return 0
        # best-of-3 minimum: on bursty co-tenant hosts a 2-draw smoke
        # can land entirely inside one slow burst and flap a legacy
        # metric the calibration probe dodged — one more draw buys the
        # quiet moment the record's best-of-3 already enjoys
        cur = measure_storm(repeats=max(3, args.repeats - 1))
        # load-normalize: the calibration probes bracket both runs, so a
        # machine uniformly K-times slower than at record time reads as
        # no regression (a real code regression moves the storm metrics
        # WITHOUT moving the probe)
        ref_calib = prev.get("calib_s")
        if ref_calib and cur.get("calib_s"):
            ratio = cur["calib_s"] / ref_calib
            print(f"bench-gate --smoke: load calibration x{ratio:.2f} "
                  f"(live {cur['calib_s']}s vs recorded {ref_calib}s)")
            cur = dict(cur)
            for key in GATED:
                if key in NOT_NORMALIZED:
                    continue
                if isinstance(cur.get(key), (int, float)):
                    cur[key] = round(
                        cur[key] * ratio if key in HIGHER_IS_BETTER
                        else cur[key] / ratio, 3)
        failures, report = gate(cur, prev, args.threshold,
                                "live", f"r{n:02d}")
    else:
        if len(rounds) < 2:
            print("bench-gate: only one round file — nothing to compare")
            return 0
        (pn, ppath), (cn, cpath) = rounds[-2], rounds[-1]
        prev = load(ppath).get("storms", {})
        cur = load(cpath).get("storms", {})
        # same normalization round-to-round: both files carry the probe
        # taken next to their storm, so machine-speed drift between
        # recording days divides out
        if prev.get("calib_s") and cur.get("calib_s"):
            ratio = cur["calib_s"] / prev["calib_s"]
            print(f"bench-gate: load calibration x{ratio:.2f} "
                  f"(r{cn:02d} {cur['calib_s']}s vs "
                  f"r{pn:02d} {prev['calib_s']}s)")
            cur = dict(cur)
            for key in GATED:
                if key in NOT_NORMALIZED:
                    continue
                if isinstance(cur.get(key), (int, float)):
                    cur[key] = round(
                        cur[key] * ratio if key in HIGHER_IS_BETTER
                        else cur[key] / ratio, 3)
        failures, report = gate(cur, prev, args.threshold,
                                f"r{cn:02d}", f"r{pn:02d}")

    print("bench-gate report:")
    for line in report:
        print(line)
    if failures:
        print("bench-gate FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"bench-gate OK (threshold {args.threshold:.0%})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
