#!/usr/bin/env python3
"""``make spec-check`` — the paged speculative-decoding oracle.

Runs short storms through ``PagedSpeculativeDecodeServer`` on the CPU
backend and fails (exit 1) on:

- PARITY: greedy tokens through speculative rounds differing from the
  plain ``PagedDecodeServer`` on any request — across monolithic AND
  chunked+prefix-cache admission, f32 AND kv_int8 pools (the
  rounds-are-invisible contract every serving path promises);
- the POOL ACCOUNTING ORACLE (``check_invariants``) after every drain:
  speculative overshoot writes must never perturb page ownership;
- SPECULATION not actually engaging (zero rounds, or a self-draft arm
  below the gamma+1 tokens/round ceiling, would make parity vacuous);
- the ADAPTIVE-GAMMA controller failing to converge: a random
  (disagreeing) draft must end at gamma 1, a self-draft at gamma_max,
  and the acceptance counters must satisfy 0 <= accepted <= proposed.

Runs in under a minute with no accelerator; wired into ``make chaos`` so
every fault-injection run also proves speculation doesn't corrupt the
pool.
"""

import os
import sys

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 — backend already initialized
    pass

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402
from kubetpu.jobs.spec_serving import PagedSpeculativeDecodeServer  # noqa: E402

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
DCFG = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=32)
PS = 8


def fail(msg: str) -> None:
    print(f"spec-check: FAIL: {msg}")
    sys.exit(1)


def run(server, prompts, check=False):
    outs = []
    for wave in (prompts[: len(prompts) // 2], prompts[len(prompts) // 2:]):
        rids = [server.enqueue(p) for p in wave]
        server.drain()
        outs.extend(server.pop_result(r) for r in rids)
        if check:
            server.check_invariants()
    return outs


def storm_prompts():
    fam = [(i * 5) % 60 + 1 for i in range(2 * PS)]
    return ([fam + [t] for t in (1, 2, 3)]
            + [[35, 8, 9, 7, 9, 3, 2, 1, 4], [26, 5], [63] * 3])


def main() -> int:
    t_params = init_params(jax.random.PRNGKey(0), CFG)
    d_params = init_params(jax.random.PRNGKey(7), DCFG)
    prompts = storm_prompts()

    for kv_int8 in (False, True):
        tag = "kv_int8" if kv_int8 else "f32"
        plain = PagedDecodeServer(
            CFG, t_params, n_slots=2, max_seq=64, max_new_tokens=8,
            page_size=PS, kv_int8=kv_int8)
        ref = run(plain, prompts)
        # monolithic admission
        spec = PagedSpeculativeDecodeServer(
            CFG, DCFG, t_params, d_params, n_slots=2, max_seq=64,
            max_new_tokens=8, page_size=PS, kv_int8=kv_int8, gamma_max=3)
        got = run(spec, prompts, check=True)
        if got != ref:
            fail(f"{tag} monolithic speculative tokens != plain paged")
        if spec._c_spec_rounds.value <= 0:
            fail(f"{tag}: no speculative rounds ran — parity was vacuous")
        acc, prop = spec._c_spec_accepted.value, spec._c_spec_proposed.value
        if not 0 <= acc <= prop:
            fail(f"{tag}: acceptance counters inconsistent ({acc}/{prop})")
        # chunked + prefix-cache admission (shared-family storm hits)
        spec2 = PagedSpeculativeDecodeServer(
            CFG, DCFG, t_params, d_params, n_slots=2, max_seq=64,
            max_new_tokens=8, page_size=PS, kv_int8=kv_int8,
            prefill_budget=PS, prefix_cache_pages=8, gamma_max=3)
        got2 = run(spec2, prompts, check=True)
        if got2 != ref:
            fail(f"{tag} chunked+prefix speculative tokens != plain paged")
        if spec2.prefix_cache_stats()["requests_hit"] < 1:
            fail(f"{tag}: prefix cache never hit — hit parity was vacuous")
        if any(g != 1 for g in spec2.slot_gammas()):
            fail(f"{tag}: disagreeing draft did not converge to gamma 1 "
                 f"({spec2.slot_gammas()})")
        # Round-15 KERNEL arms (interpret): the fused paged-attention
        # chunk kernel replaces the verify leg's gather core — parity
        # must hold monolithic AND chunked+prefix-hit, per pool dtype
        spec_k = PagedSpeculativeDecodeServer(
            CFG, DCFG, t_params, d_params, n_slots=2, max_seq=64,
            max_new_tokens=8, page_size=PS, kv_int8=kv_int8, gamma_max=3,
            use_kernel=True, interpret=True)
        if run(spec_k, prompts, check=True) != ref:
            fail(f"{tag} KERNEL monolithic speculative tokens != plain paged")
        if spec_k._c_kernel_steps.value <= 0:
            fail(f"{tag}: kernel arm never ran a kernel round — parity "
                 f"was vacuous")
        spec_k2 = PagedSpeculativeDecodeServer(
            CFG, DCFG, t_params, d_params, n_slots=2, max_seq=64,
            max_new_tokens=8, page_size=PS, kv_int8=kv_int8,
            prefill_budget=PS, prefix_cache_pages=8, gamma_max=3,
            use_kernel=True, interpret=True)
        if run(spec_k2, prompts, check=True) != ref:
            fail(f"{tag} KERNEL chunked+prefix speculative tokens != "
                 f"plain paged")
        if spec_k2.prefix_cache_stats()["requests_hit"] < 1:
            fail(f"{tag}: kernel arm prefix cache never hit — hit parity "
                 f"was vacuous")
        print(f"spec-check: {tag}: parity ok over {len(ref)} requests, "
              f"{int(spec2._c_spec_rounds.value)} rounds, "
              f"{spec2.prefix_cache_stats()['requests_hit']} prefix hits, "
              f"gammas {spec2.slot_gammas()}, kernel rounds "
              f"{int(spec_k._c_kernel_steps.value)}"
              f"+{int(spec_k2._c_kernel_steps.value)}")

    # self-draft ceiling: full agreement must pin gamma at gamma_max and
    # tokens/round at the gamma+1 ceiling (the rounds-not-tokens win)
    ceiling = PagedSpeculativeDecodeServer(
        CFG, CFG, t_params, t_params, n_slots=1, max_seq=64,
        max_new_tokens=31, page_size=PS, n_pages=8, gamma_max=2)
    rid = ceiling.submit([3, 14, 15, 9])
    ceiling.drain()
    ceiling.check_invariants()
    if ceiling.mean_tokens_per_round() != 3.0:
        fail(f"self-draft tokens/round {ceiling.mean_tokens_per_round()} "
             f"!= gamma_max+1 ceiling")
    if ceiling.slot_gammas() != [2]:
        fail(f"self-draft walked gamma off gamma_max: {ceiling.slot_gammas()}")
    plain = PagedDecodeServer(CFG, t_params, n_slots=1, max_seq=64,
                              max_new_tokens=31, page_size=PS, n_pages=8)
    rp = plain.submit([3, 14, 15, 9])
    plain.drain()
    if ceiling.result(rid) != plain.result(rp):
        fail("self-draft output != plain paged greedy")
    print("spec-check: self-draft ceiling ok (tokens/round == gamma_max+1)")
    print("spec-check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
