#!/usr/bin/env python3
"""``make crash-check`` — the crash-tolerance oracle (Round-20).

Three hard-kill scenarios, all in-process on the CPU backend, each with
an exact oracle; any miss fails (exit 1):

1. **Controller SIGKILL + cold restart.** A journaled controller places
   pods across 2 fake agents, an out-of-band allocation is planted on
   one agent (the orphan), and the controller dies abruptly
   (``shutdown(graceful=False)`` — no final snapshot, no goodbye). A
   torn partial record is appended to the WAL to simulate the kill
   landing mid-write. The restarted controller (same ``journal_path``)
   must replay to the EXACT pre-crash placement/pending state, free the
   orphan, drop (and count) the torn tail, pass ``check_invariants``
   before the wire reports ``recovering: false``, and surface the diff
   as ``kubetpu_recovery_*`` series. A SECOND restart must converge to
   the same state (replay is idempotent), and a fresh submit must place
   — the fleet is live, not just restored.

2. **Replica SIGKILL mid-storm + same-name takeover.** A router + 2
   paged replicas serve a keyed shared-prefix storm; halfway through,
   one replica is hard-killed and a NEW process re-registers under the
   SAME name at a new URL. The boot nonce exposes it as cache-wiped:
   the pool takes the handle over (``replica_takeover``), mid-stream
   pins naming it are dropped (``restart_unpin``), and the storm
   finishes with greedy-token PARITY against a quiet serial run and
   admissions == logical requests — the crash re-drives keyed work, it
   never re-admits or corrupts it.

3. **Autoscaler crash-replace.** The breaker confirms the killed
   replica DEAD; the reap pass must immediately boot a replacement
   through the launcher (``crash_replace`` event), bypassing cooldown
   — a crash is not load noise.

Runs in well under a minute with no accelerator; wired into
``make chaos``.
"""

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001 — backend already initialized
    pass

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.device import (  # noqa: E402
    make_fake_tpus_info,
    new_fake_tpu_dev_manager,
)
from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402
from kubetpu.obs import validate_prometheus_text  # noqa: E402
from kubetpu.plugintypes import ResourceTPU  # noqa: E402
from kubetpu.router import ReplicaServer, RouterServer  # noqa: E402
from kubetpu.router.autoscaler import ReplicaAutoscaler, ScalePolicy  # noqa: E402
from kubetpu.wire import ControllerServer, NodeAgentServer  # noqa: E402
from kubetpu.wire.controller import pod_to_json  # noqa: E402
from kubetpu.wire.httpcommon import request_json  # noqa: E402

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8
MAX_NEW = 5


def fail(msg: str) -> None:
    print(f"crash-check: FAIL: {msg}")
    sys.exit(1)


# -- scenario 1: controller SIGKILL + cold restart ---------------------------


def placements(ctrl: ControllerServer) -> dict:
    out = {}
    for nname, node in ctrl.cluster.nodes.items():
        for pname in node.pods:
            out[pname] = nname
    return out


def submit(ctrl_addr: str, name: str, key: str) -> None:
    request_json(
        ctrl_addr + "/pods",
        {"pod": pod_to_json(PodInfo(
            name=name,
            running_containers={"main": ContainerInfo(
                requests={ResourceTPU: 4})},
        ))},
        idempotency_key=key,
    )


def controller_scenario(tmp: str) -> float:
    journal_path = os.path.join(tmp, "controller.journal")
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h)),
            f"crash-h{h}",
        )
        for h in range(2)
    ]
    for a in agents:
        a.start()
    c1 = ControllerServer(poll_interval=3600, journal_path=journal_path)
    c1.start()
    for a in agents:
        request_json(c1.address + "/nodes", {"url": a.address},
                     idempotency_key=f"crash-check-reg-{a.node_name}")
    for i in range(3):
        submit(c1.address, f"crash-p{i}", f"crash-check-p{i}")
    c1.poll_once()
    pre_place = placements(c1)
    pre_pending = sorted(c1.pending_pods)
    if len(pre_place) != 3:
        fail(f"seed run placed {len(pre_place)}/3 pods: {pre_place}")

    # the allocation the control plane never knew about: an orphan the
    # reconcile diff must free
    agents[0].allocations["crash-orphan"] = {"main"}

    # SIGKILL: no drain, no final snapshot — and the kill lands
    # mid-write, leaving a torn partial record at the WAL tail
    c1.shutdown(graceful=False)
    with open(journal_path, "ab") as f:
        f.write(b'{"seq": 9999, "kind": "pod_place", "da')

    t0 = time.monotonic()
    c2 = ControllerServer(poll_interval=3600, journal_path=journal_path)
    c2.start()
    recovery_s = time.monotonic() - t0
    try:
        hz = request_json(c2.address + "/healthz", None, timeout=10)
        if hz.get("recovering"):
            fail("healthz still 'recovering' after start() returned")
        if c2.journal.stats()["torn_tail_dropped"] < 1:
            fail("torn WAL tail was not detected/dropped")
        got_place = placements(c2)
        if got_place != pre_place:
            fail(f"replayed placements {got_place} != pre-crash "
                 f"{pre_place}")
        if sorted(c2.pending_pods) != pre_pending:
            fail(f"replayed pending {sorted(c2.pending_pods)} != "
                 f"pre-crash {pre_pending}")
        if "crash-orphan" in agents[0].allocations:
            fail("orphaned agent allocation survived reconciliation")
        problems = c2.cluster.check_invariants()
        if problems:
            fail("post-recovery invariants dirty: " + "; ".join(problems))
        text = c2._metrics_text()
        mproblems = validate_prometheus_text(text)
        if mproblems:
            fail("post-recovery /metrics malformed: " + mproblems[0])
        for needle in ("kubetpu_recovery_replays_total 1",
                       "kubetpu_recovery_orphans_freed_total 1",
                       "kubetpu_recovery_placements_restored_total 3",
                       "kubetpu_controller_recovering 0"):
            if needle not in text:
                fail(f"missing recovery series: {needle!r}")
        # the recovered fleet is LIVE, not just restored
        submit(c2.address, "crash-p3", "crash-check-p3")
        if "crash-p3" not in placements(c2):
            fail("post-recovery submit did not place")
    finally:
        c2.shutdown(graceful=False)

    # replay is idempotent: a second cold restart (after the first
    # recovery trued-up the snapshot) converges to the same state
    c3 = ControllerServer(poll_interval=3600, journal_path=journal_path)
    c3.start()
    try:
        want = dict(pre_place, **{"crash-p3": placements(c3)["crash-p3"]}) \
            if "crash-p3" in placements(c3) else pre_place
        got = placements(c3)
        if sorted(got) != sorted(want):
            fail(f"second replay diverged: {sorted(got)} != "
                 f"{sorted(want)}")
        if c3.cluster.check_invariants():
            fail("second replay left dirty invariants")
    finally:
        c3.shutdown()
        for a in agents:
            a.shutdown()
    print(f"crash-check: controller recovered in {recovery_s * 1e3:.0f}ms "
          f"(3 placements + 1 orphan freed + torn tail dropped), "
          f"second replay converged")
    return recovery_s


# -- scenario 2: replica SIGKILL mid-storm + takeover ------------------------


def make_server(params):
    return PagedDecodeServer(
        CFG, params, n_slots=2, max_seq=64, max_new_tokens=MAX_NEW,
        page_size=PS, prefill_budget=PS, prefix_cache_pages=16)


def storm_prompts():
    prompts = []
    for f, seed in enumerate((5, 7, 11)):
        fam = [(i * seed) % 60 + 1 for i in range(2 * PS)]
        for tail in range(3):
            prompts.append(fam + [f * 10 + tail + 1])
    prompts.append([63] * 3)
    return prompts


def replica_scenario(params, prompts, expected) -> None:
    replicas = [ReplicaServer(make_server(params), f"crash-r{i}",
                              idle_wait=0.002) for i in range(2)]
    for rep in replicas:
        rep.start()
    router = RouterServer(load_refresh_s=0.05)
    router.start()
    replacement = None
    try:
        for rep in replicas:
            router.register_replica(rep.address)
        half = len(prompts) // 2
        results = []
        for i, p in enumerate(prompts[:half]):
            results.append(request_json(
                router.address + "/generate",
                {"prompt": p, "timeout": 30.0},
                idempotency_key=f"crash-check-gen-{i}", timeout=30.0))

        # plant a mid-stream pin naming the doomed replica: the restart
        # hook must drop it so the keyed re-drive re-picks fresh
        with router._lock:
            router._pins["crash-check-pin"] = ("crash-r0", 1)

        # SIGKILL the first replica, then re-register the SAME name at
        # a NEW url — a fresh boot nonce proves the cache is gone
        replicas[0].shutdown(graceful=False)
        replacement = ReplicaServer(make_server(params), "crash-r0",
                                    idle_wait=0.002)
        replacement.start()
        taken = router.register_replica(replacement.address)
        if taken != "crash-r0":
            fail(f"takeover registered as {taken!r}, not 'crash-r0'")
        if not router.events.events(kind="replica_takeover"):
            fail("no replica_takeover event for the same-name restart")
        with router._lock:
            pin = router._pins.get("crash-check-pin")
        if pin is not None:
            fail(f"stale pin to the killed replica survived: {pin}")
        if not router.events.events(kind="restart_unpin"):
            fail("no restart_unpin event when the pinned owner restarted")

        # the replacement must walk probation back to routable
        deadline = time.monotonic() + 10
        while "crash-r0" not in router.pool.routable():
            if time.monotonic() > deadline:
                fail("takeover replica never became routable "
                     f"(state {router.pool.state('crash-r0')!r})")
            router.pool.refresh(0.0)
            time.sleep(0.02)

        for i, p in enumerate(prompts[half:], start=half):
            results.append(request_json(
                router.address + "/generate",
                {"prompt": p, "timeout": 30.0},
                idempotency_key=f"crash-check-gen-{i}", timeout=30.0))

        for i, (body, want) in enumerate(zip(results, expected)):
            if body["tokens"] != want:
                fail(f"request {i}: tokens {body['tokens']} != quiet-run "
                     f"{want} (replica {body.get('replica')}) — the "
                     f"crash changed generation semantics")
        execs = sum(
            int(rep.server.obs.counter(
                "kubetpu_replica_generate_requests_total").value)
            for rep in (replicas[0], replicas[1], replacement))
        if execs != len(prompts):
            fail(f"{execs} generate executions for {len(prompts)} "
                 f"logical requests — the crash double-admitted or "
                 f"dropped keyed work")
        for rep in (replicas[1], replacement):
            rep.server.check_invariants()
    finally:
        router.shutdown()
        replicas[1].shutdown(graceful=False)
        if replacement is not None:
            replacement.shutdown(graceful=False)
    print(f"crash-check: replica takeover kept token parity "
          f"({len(prompts)} requests, {execs} executions), stale pin "
          f"dropped")


# -- scenario 3: autoscaler crash-replace ------------------------------------


def autoscaler_scenario(params) -> None:
    live = []

    def launcher(role):
        rep = ReplicaServer(make_server(params), f"crash-a{len(live)}",
                            idle_wait=0.002)
        rep.start()
        live.append(rep)
        return rep.address

    for _ in range(2):
        launcher("both")
    router = RouterServer(load_refresh_s=0.05, suspect_after=1,
                          dead_after=2)
    router.start()
    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=3, up_after=99,
                           down_after=99, cooldown_s=3600.0))
    try:
        for rep in live:
            router.register_replica(rep.address)
        victim = live[0]
        victim.shutdown(graceful=False)
        deadline = time.monotonic() + 10
        while router.pool.state(victim.name) != "dead":
            if time.monotonic() > deadline:
                fail("killed replica never reached DEAD "
                     f"(state {router.pool.state(victim.name)!r})")
            router.pool.refresh(0.0)
            time.sleep(0.02)
        scaler.poll_once()
        if not router.events.events(kind="reap"):
            fail("DEAD replica was not reaped")
        if not router.events.events(kind="crash_replace"):
            fail("reap did not crash-replace (cooldown_s=3600 would "
                 "otherwise block any scale-up — the bypass is the "
                 "point)")
        alive = router.pool.alive()
        if victim.name in alive or len(alive) != 2:
            fail(f"fleet after crash-replace is {alive}, want 2 alive "
                 f"without {victim.name!r}")
    finally:
        router.shutdown()
        for rep in live[1:]:
            rep.shutdown(graceful=False)
    print(f"crash-check: crash_replace rebooted the pool to "
          f"{len(alive)} replicas despite an hour of cooldown")


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="kubetpu-crash-check-")
    try:
        controller_scenario(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = storm_prompts()
    direct = make_server(params)
    expected = []
    for p in prompts:
        rid = direct.enqueue(p)
        direct.drain()
        expected.append(direct.pop_result(rid))

    replica_scenario(params, prompts, expected)
    autoscaler_scenario(params)
    print("crash-check OK: journal replay + reconcile exact, takeover "
          "kept parity with no double admission, crash_replace healed "
          "the pool")
    return 0


if __name__ == "__main__":
    sys.exit(main())
