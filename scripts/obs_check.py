#!/usr/bin/env python3
"""``make obs-check`` — the observability smoke oracle.

Starts a controller + 2 fake agents in-process, submits a pod so every
layer records something, scrapes the controller's FEDERATED ``/metrics``,
and fails (exit 1) on:

- malformed Prometheus text (``obs.validate_prometheus_text``);
- any missing REQUIRED series: scheduler latency summary, per-node agent
  allocate counters, the breaker-state node gauge, chips/pending gauges,
  and (Round-11) the standard process gauges (``kubetpu_build_info`` /
  uptime / RSS) plus the fleet ``kubetpu_slo_*`` judgment surface;
- a submit whose trace does not stitch (no shared trace_id across
  controller and agent spans);
- (Round-11) a ``GET /events`` body on the controller, any agent, or a
  serving-style exporter that is not schema-valid event JSONL, a
  controller event log missing its registration events, or a profiler-
  carrying exporter scrape missing the ``kubetpu_profile_*`` series.

Runs in a few seconds with no accelerator; wired into the chaos target so
every fault-injection run also proves the fleet is observable.
"""

import sys
import time

sys.path.insert(0, ".")

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.device import (  # noqa: E402
    make_fake_tpus_info,
    new_fake_tpu_dev_manager,
)
from kubetpu.obs import (  # noqa: E402
    EventLog,
    Registry,
    ServingProfiler,
    install_process_gauges,
    span,
    validate_events_jsonl,
    validate_prometheus_text,
)
from kubetpu.obs.exporter import MetricsServer  # noqa: E402
from kubetpu.obs.slo import fleet_slos  # noqa: E402
from kubetpu.plugintypes import ResourceTPU  # noqa: E402
from kubetpu.wire import ControllerServer, NodeAgentServer  # noqa: E402
from kubetpu.wire.controller import pod_to_json  # noqa: E402
from kubetpu.wire.httpcommon import request_json  # noqa: E402

REQUIRED_SERIES = (
    'kubetpu_schedule_latency_seconds{op="schedule_pod",quantile="0.5"}',
    'kubetpu_agent_allocate_requests_total{node="obs-h0"}',
    'kubetpu_agent_allocate_requests_total{node="obs-h1"}',
    'kubetpu_nodes{state="healthy"} 2',
    'kubetpu_nodes{state="suspect"}',
    "kubetpu_pending_pods",
    'kubetpu_chips_free{device="kubedevice/tpu"}',
    'kubetpu_chips_held{device="kubedevice/tpu"}',
    "kubetpu_controller_submits_total 2",
    "kubetpu_agent_capacity",
    # Round-11: replica identification + the fleet SLO surface
    'component="controller"',
    "kubetpu_build_info{",
    "kubetpu_process_uptime_seconds",
    "kubetpu_process_rss_bytes",
    'kubetpu_slo_value{slo="node_availability"}',
    'kubetpu_slo_ok{slo="node_availability"} 1',
    'kubetpu_slo_burn_rate{slo="node_availability",window="fast"}',
    'kubetpu_slo_burn_rate{slo="node_availability",window="slow"}',
    'kubetpu_slo_firing{slo="node_availability"} 0',
)

# the serving-style exporter scrape must carry the profiler families
REQUIRED_PROFILE_SERIES = (
    "kubetpu_profile_sampled_steps_total",
    "kubetpu_profile_step_seconds_total",
    'kubetpu_profile_phase_seconds_total{phase="device"',
    'kubetpu_jit_recompiles_total{leg="step"}',
    'kubetpu_jit_compile_seconds_total{leg="step"}',
    "kubetpu_build_info{",
)


def _get_text(url: str) -> str:
    from kubetpu.wire.httpcommon import request_text

    return request_text(url, timeout=10)


def _check_events(name: str, body: str, failures, expect_kinds=()):
    problems = validate_events_jsonl(body)
    if problems:
        failures.append(f"{name} /events not schema-valid JSONL:\n  " +
                        "\n  ".join(problems[:5]))
    for kind in expect_kinds:
        if f'"kind": "{kind}"' not in body:
            failures.append(f"{name} /events missing a {kind!r} event")


def main() -> int:
    failures = []
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h)),
            f"obs-h{h}",
        )
        for h in range(2)
    ]
    controller = ControllerServer(
        poll_interval=3600,
        # the fleet judgment surface under test: with both agents
        # healthy, availability must evaluate ok and not fire
        slos=fleet_slos(min_healthy_fraction=0.5),
    )
    controller.start()
    try:
        for a in agents:
            a.start()
            # keyed so the registration POST is retry-safe under the
            # shared client (register_agent is idempotent server-side
            # too, but the key keeps KTP002's contract uniform)
            request_json(controller.address + "/nodes", {"url": a.address},
                         idempotency_key=f"obs-check-reg-{a.node_name}")
        # one single-pod submit + one gang submit so both schedule ops and
        # both agents' allocate paths record
        with span("obs-check.submit") as root:
            request_json(
                controller.address + "/pods",
                {"pod": pod_to_json(PodInfo(
                    name="obs-p0",
                    running_containers={"main": ContainerInfo(
                        requests={ResourceTPU: 4})},
                ))},
                idempotency_key="obs-check-p0",
            )
            trace_id = root.trace_id
        request_json(
            controller.address + "/pods",
            {"gang": [pod_to_json(PodInfo(
                name=f"obs-g{i}",
                running_containers={"main": ContainerInfo(
                    requests={ResourceTPU: 4})},
            )) for i in range(2)]},
            idempotency_key="obs-check-gang",
        )
        controller.poll_once()

        text = controller._metrics_text()
        problems = validate_prometheus_text(text)
        if problems:
            failures.append("malformed Prometheus text:\n  " +
                            "\n  ".join(problems))
        for needle in REQUIRED_SERIES:
            if needle not in text:
                failures.append(f"missing required series: {needle!r}")

        trace = controller._trace(trace_id)
        comps = {s.get("component", "") for s in trace["spans"]}
        if "controller" not in comps or not any(
                c.startswith("agent:") for c in comps):
            failures.append(
                f"trace {trace_id} did not stitch across controller and "
                f"agent spans (components: {sorted(comps)})")

        # Round-11: GET /events must serve schema-valid JSONL fleet-wide
        _check_events(
            "controller",
            _get_text(controller.address + "/events"),
            failures, expect_kinds=("register",))
        for a in agents:
            _check_events(
                a.node_name,
                _get_text(a.address + "/events"),
                failures, expect_kinds=("allocate",))

        # Round-11: a serving-style exporter carrying a profiler + event
        # log (no accelerator: the profiler is exercised host-side — the
        # serving integration is pinned by the jax test suite)
        sreg = Registry()
        install_process_gauges(sreg, "serving")
        prof = ServingProfiler(sample_every=1, registry=sreg)
        rec = prof.begin_step()
        time.sleep(0.001)
        rec.mark("schedule")
        rec.mark("device")
        prof.end_step(rec)
        step = prof.watch("step", lambda *a: None)
        step(1)
        step(1.5)          # new call signature -> one tracked recompile
        slog = EventLog(component="serving")
        slog.emit("admit", rid="r0", slot=0)
        slog.emit("retire", rid="r0", slot=0)
        exporter = MetricsServer({"replica": sreg}, events=slog)
        exporter.start()
        try:
            base = exporter.address
            stext = _get_text(base + "/metrics")
            sproblems = validate_prometheus_text(stext)
            if sproblems:
                failures.append("exporter /metrics malformed:\n  " +
                                "\n  ".join(sproblems[:5]))
            for needle in REQUIRED_PROFILE_SERIES:
                if needle not in stext:
                    failures.append(
                        f"exporter missing profiler series: {needle!r}")
            _check_events(
                "exporter",
                _get_text(base + "/events"),
                failures, expect_kinds=("admit", "retire"))
        finally:
            exporter.shutdown()
    finally:
        controller.shutdown()
        for a in agents:
            a.shutdown()
    if failures:
        print("obs-check FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("obs-check OK: federated /metrics valid, required series "
          "(incl. slo/build-info/profiler) present, submit trace "
          "stitched, /events schema-valid fleet-wide")
    return 0


if __name__ == "__main__":
    sys.exit(main())
