#!/usr/bin/env python3
"""``make obs-check`` — the observability smoke oracle.

Starts a controller + 2 fake agents in-process, submits a pod so every
layer records something, scrapes the controller's FEDERATED ``/metrics``,
and fails (exit 1) on:

- malformed Prometheus text (``obs.validate_prometheus_text``);
- any missing REQUIRED series: scheduler latency summary, per-node agent
  allocate counters, the breaker-state node gauge, chips/pending gauges;
- a submit whose trace does not stitch (no shared trace_id across
  controller and agent spans).

Runs in a few seconds with no accelerator; wired into the chaos target so
every fault-injection run also proves the fleet is observable.
"""

import sys

sys.path.insert(0, ".")

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.device import (  # noqa: E402
    make_fake_tpus_info,
    new_fake_tpu_dev_manager,
)
from kubetpu.obs import span, validate_prometheus_text  # noqa: E402
from kubetpu.plugintypes import ResourceTPU  # noqa: E402
from kubetpu.wire import ControllerServer, NodeAgentServer  # noqa: E402
from kubetpu.wire.controller import pod_to_json  # noqa: E402
from kubetpu.wire.httpcommon import request_json  # noqa: E402

REQUIRED_SERIES = (
    'kubetpu_schedule_latency_seconds{op="schedule_pod",quantile="0.5"}',
    'kubetpu_agent_allocate_requests_total{node="obs-h0"}',
    'kubetpu_agent_allocate_requests_total{node="obs-h1"}',
    'kubetpu_nodes{state="healthy"} 2',
    'kubetpu_nodes{state="suspect"}',
    "kubetpu_pending_pods",
    'kubetpu_chips_free{device="kubedevice/tpu"}',
    'kubetpu_chips_held{device="kubedevice/tpu"}',
    "kubetpu_controller_submits_total 2",
    "kubetpu_agent_capacity",
)


def main() -> int:
    failures = []
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h)),
            f"obs-h{h}",
        )
        for h in range(2)
    ]
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    try:
        for a in agents:
            a.start()
            request_json(controller.address + "/nodes", {"url": a.address})
        # one single-pod submit + one gang submit so both schedule ops and
        # both agents' allocate paths record
        with span("obs-check.submit") as root:
            request_json(
                controller.address + "/pods",
                {"pod": pod_to_json(PodInfo(
                    name="obs-p0",
                    running_containers={"main": ContainerInfo(
                        requests={ResourceTPU: 4})},
                ))},
                idempotency_key="obs-check-p0",
            )
            trace_id = root.trace_id
        request_json(
            controller.address + "/pods",
            {"gang": [pod_to_json(PodInfo(
                name=f"obs-g{i}",
                running_containers={"main": ContainerInfo(
                    requests={ResourceTPU: 4})},
            )) for i in range(2)]},
            idempotency_key="obs-check-gang",
        )
        controller.poll_once()

        text = controller._metrics_text()
        problems = validate_prometheus_text(text)
        if problems:
            failures.append("malformed Prometheus text:\n  " +
                            "\n  ".join(problems))
        for needle in REQUIRED_SERIES:
            if needle not in text:
                failures.append(f"missing required series: {needle!r}")

        trace = controller._trace(trace_id)
        comps = {s.get("component", "") for s in trace["spans"]}
        if "controller" not in comps or not any(
                c.startswith("agent:") for c in comps):
            failures.append(
                f"trace {trace_id} did not stitch across controller and "
                f"agent spans (components: {sorted(comps)})")
    finally:
        controller.shutdown()
        for a in agents:
            a.shutdown()
    if failures:
        print("obs-check FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("obs-check OK: federated /metrics valid, required series "
          "present, submit trace stitched")
    return 0


if __name__ == "__main__":
    sys.exit(main())
