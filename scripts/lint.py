#!/usr/bin/env python
"""``scripts/lint.py`` — thin wrapper over ``python -m kubetpu.analysis``
so CI and operators have one obvious entry point next to the other
check scripts (obs_check, prefix_check, spec_check)."""

import os
import sys

# run from the repo root like the sibling check scripts; also resolve
# the root from this file so `python scripts/lint.py` works anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubetpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
