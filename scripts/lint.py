#!/usr/bin/env python
"""``scripts/lint.py`` — thin wrapper over ``python -m kubetpu.analysis``
so CI and operators have one obvious entry point next to the other
check scripts (obs_check, prefix_check, spec_check).

CI mode by default: unless the invocation is a ``--write-baseline``
regeneration, ``--fail-stale`` is injected so a baseline holding budget
for findings that no longer exist FAILS the run (the interactive CLI
only nudges) — paid-down ratchet debt must be committed, or the next
regression hides inside the stale budget."""

import os
import sys

# run from the repo root like the sibling check scripts; also resolve
# the root from this file so `python scripts/lint.py` works anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubetpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    args = sys.argv[1:]
    if "--write-baseline" not in args and "--fail-stale" not in args:
        args = ["--fail-stale"] + args
    raise SystemExit(main(args))
