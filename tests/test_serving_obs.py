"""Serving-side Round-8 observability: TTFT / inter-token latency /
queue-wait histograms recorded by the slot servers, their Prometheus
exposition, and the chunked-vs-monolithic TTFT ordering under a
long-prompt admission storm (ISSUE 3 satellite, via the
``serving_mixed_load`` harness family in bench_model).

Shapes deliberately mirror test_chunked_prefill / test_serving (same CFG,
n_slots, max_seq) so the process-wide jit caches are already warm when
tier-1 reaches this file.
"""

import jax
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.paged import PagedDecodeServer
from kubetpu.jobs.serving import DecodeServer
from kubetpu.obs.registry import validate_prometheus_text

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PROMPTS = [[3, 14, 15, 9, 2, 6, 5], [(i * 7) % 60 + 1 for i in range(19)]]


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def run_mixed(server):
    rids = [server.enqueue(p) for p in PROMPTS]
    for _ in range(2):
        server.step()
    server.drain()
    return rids


def test_server_records_ttft_itl_queue_wait(params):
    srv = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=6,
                       prefill_budget=3)
    rids = run_mixed(srv)
    stats = srv.metrics_summary()
    # one TTFT sample per finished request; decode gaps feed itl
    assert stats["ttft"]["count"] == len(rids)
    assert stats["itl"]["count"] > 0
    assert stats["queue_wait"]["count"] == len(rids)
    for op in ("ttft", "itl", "queue_wait"):
        assert stats[op]["p50_ms"] >= 0
        assert stats[op]["p50_ms"] <= stats[op]["p99_ms"]
        assert {"count", "p50_ms", "p90_ms", "p99_ms"} <= set(stats[op])
    # the SAME histograms render as valid Prometheus text, gauges included
    text = srv.metrics_text()
    assert validate_prometheus_text(text) == []
    assert 'kubetpu_serving_latency_seconds{op="ttft",quantile="0.5"}' in text
    assert 'kubetpu_serving_latency_seconds{op="itl",quantile="0.99"}' in text
    assert "kubetpu_serving_slots 2" in text
    assert "kubetpu_serving_active_slots 0" in text  # drained
    assert "kubetpu_serving_queue_depth 0" in text
    # pop_result releases the observability stamps with the bookkeeping
    for r in rids:
        srv.pop_result(r)
    assert not srv._arrive and not srv._last_emit


def test_paged_pool_gauges(params):
    srv = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=4, page_size=4)
    rid = srv.enqueue(PROMPTS[0])
    srv.step()
    text = srv.metrics_text()
    assert validate_prometheus_text(text) == []
    total = srv.pool_pages
    in_use = srv.pages_in_use()
    assert in_use > 0  # the admitted request holds pages
    assert f"kubetpu_serving_pool_pages {total}" in text
    assert f"kubetpu_serving_pages_in_use {in_use}" in text
    assert f"kubetpu_serving_pages_free {total - in_use}" in text
    srv.drain()
    assert srv.finished(rid)
    assert "kubetpu_serving_pages_in_use 0" in srv.metrics_text()


def test_submit_path_records_ttft_immediately(params):
    """The synchronous submit path has no queue wait and a first token at
    admission — TTFT records there too (not only on the deferred path)."""
    srv = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=3)
    srv.submit(PROMPTS[0])
    stats = srv.metrics_summary()
    assert stats["ttft"]["count"] == 1
    assert stats["queue_wait"]["count"] == 1
    assert stats["queue_wait"]["p50_ms"] <= stats["ttft"]["p50_ms"]


def test_admit_event_precedes_first_token_retire(params):
    """A request that finishes on its very first token (max_new_tokens=1)
    must still log admit -> retire in causal (seq) order — the event
    timeline exists to answer 'what happened in what order'."""
    srv = DecodeServer(CFG, params, n_slots=1, max_seq=64, max_new_tokens=1)
    srv.submit(PROMPTS[0])
    kinds = [e["kind"] for e in srv.events.events()]
    assert "admit" in kinds and "retire" in kinds
    assert kinds.index("admit") < kinds.index("retire")


# -- Round-11 sampled profiler ------------------------------------------------


def test_step_profiler_disabled_adds_no_syncs_or_uploads(monkeypatch):
    """The ISSUE 6 acceptance pin, alongside the Round-10 zero-upload
    pin: with the profiler DISABLED (the default), steady-state step()
    issues ZERO ``jax.block_until_ready`` device syncs and ZERO
    ``jnp.asarray`` host uploads — observability must never defeat the
    overlap double-buffer. With it ENABLED at sample rate N, exactly the
    sampled step pays exactly one sync."""
    import jax.numpy as jnp
    import numpy as np

    params = init_params(jax.random.PRNGKey(0), CFG)
    srv = DecodeServer(CFG, params, n_slots=2, max_seq=64,
                       max_new_tokens=40)
    srv.submit([1, 2, 3, 4])
    srv.step()                          # mirrors warm, decode mid-flight
    syncs, uploads = [], []
    real_sync, real_asarray = jax.block_until_ready, jnp.asarray

    def counting_sync(x):
        syncs.append(1)
        return real_sync(x)

    def counting_upload(x, *a, **k):
        uploads.append(np.shape(x))
        return real_asarray(x, *a, **k)

    monkeypatch.setattr(jax, "block_until_ready", counting_sync)
    monkeypatch.setattr(jnp, "asarray", counting_upload)
    for _ in range(4):
        srv.step()
    monkeypatch.undo()
    assert syncs == [], "disabled profiler issued a device sync"
    assert uploads == [], f"disabled profiler uploaded host state: {uploads}"

    # enabled at rate 2: the sampled step pays one sync, its neighbor none
    srv.enable_profiler(sample_every=2)
    monkeypatch.setattr(jax, "block_until_ready", counting_sync)
    monkeypatch.setattr(jnp, "asarray", counting_upload)
    srv.step()                          # step index 0: sampled
    sampled_syncs = len(syncs)
    srv.step()                          # step index 1: not sampled
    monkeypatch.undo()
    assert sampled_syncs == 1
    assert len(syncs) == 1
    assert uploads == [], "profiler uploaded host state"
    srv.drain()


def test_profiler_breakdown_covers_step_wall(params):
    """Enabled at rate 1 under a mixed chunked-admission load, the
    per-phase breakdown tiles the step: named phases sum to >= 90% of
    sampled wall (the acceptance bar — a breakdown that loses a tenth of
    the step hides the problem it exists to find), and the series render
    on the server's own registry."""
    srv = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=6,
                       prefill_budget=3)
    prof = srv.enable_profiler(sample_every=1)
    run_mixed(srv)
    s = prof.summary()
    assert s["sampled_steps"] == s["steps"] > 0
    assert {"schedule", "dispatch", "materialize"} <= set(s["phases"])
    assert "device" in s["phases"]           # sampled steps synced
    assert s["coverage"] >= 0.9, s
    assert s["coverage"] <= 1.0 + 1e-6
    text = srv.metrics_text()
    assert validate_prometheus_text(text) == []
    assert "kubetpu_profile_sampled_steps_total" in text
    assert 'kubetpu_profile_phase_seconds_total{phase="device"}' in text
    # profile_summary() is the bench-row surface; {} while disabled
    assert srv.profile_summary() == s
    assert DecodeServer(CFG, params, n_slots=2, max_seq=64,
                        max_new_tokens=4).profile_summary() == {}


@pytest.mark.slow
def test_gamma_walk_shows_recompile_counters(params):
    """The recompile-storm pin (ISSUE 6 acceptance): an adaptive-gamma
    walk onto a not-yet-compiled round leg reads as a NONZERO
    ``kubetpu_jit_recompiles_total{leg="round[gamma=G]"}`` counter with
    compile seconds attached — not a mystery stall. A random-init draft
    walks gamma down from gamma_max, so the gamma-1 leg compiles only
    AFTER the change; page_size 4 keeps these legs distinct from every
    other test's compile cache. Slow-marked: compiles its own draft +
    round legs."""
    from kubetpu.jobs import ModelConfig
    from kubetpu.jobs.spec_serving import PagedSpeculativeDecodeServer

    dcfg = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=32)
    d_params = init_params(jax.random.PRNGKey(7), dcfg)
    srv = PagedSpeculativeDecodeServer(CFG, dcfg, params, d_params,
                                       n_slots=1, max_seq=64,
                                       max_new_tokens=24,
                                       page_size=4, gamma_max=2)
    prof = srv.enable_profiler(sample_every=1)
    rid = srv.submit([5, 9, 3, 1, 7, 2])
    while not srv.finished(rid):
        srv.step()
    gammas = srv.events.events(kind="gamma")
    assert gammas and gammas[0]["old"] == 2 and gammas[0]["new"] == 1
    s = prof.summary()
    assert s["recompiles"].get("round[gamma=1]", {}).get(
        "recompiles", 0) >= 1, s["recompiles"]
    text = srv.metrics_text()
    assert 'kubetpu_jit_recompiles_total{leg="round[gamma=1]"}' in text
    assert 'kubetpu_jit_compile_seconds_total{leg="round[gamma=1]"}' in text
    assert s["coverage"] >= 0.9, s
    srv.check_invariants()


@pytest.mark.slow
def test_chunked_ttft_p50_beats_monolithic_under_storm():
    """ISSUE 3 satellite ordering, via the bench harness: under a
    long-prompt admission storm (one long + shorts behind it per round),
    the chunked scheduler's SERVER-RECORDED TTFT p50 is strictly below
    the monolithic server's — shorts finish with leftover per-step
    budget while the long trickles, instead of every first token waiting
    out the whole backlog's prefill. Sized so prefill compute dominates
    step overhead (the regime the knob exists for); slow-marked for the
    bucket warmup compiles."""
    import bench_model

    mono, chunked = bench_model.mixed_load_storm(
        CFG, long_len=384, max_seq=512, prefill_budget=64,
        n_shorts=3, rounds=2, max_new=4)
    assert mono["ttft"]["count"] == chunked["ttft"]["count"] == 8
    assert chunked["ttft"]["p50_ms"] < mono["ttft"]["p50_ms"], (
        f"chunked ttft p50 {chunked['ttft']['p50_ms']:.2f}ms not below "
        f"monolithic {mono['ttft']['p50_ms']:.2f}ms")
    # ITL distributions exist on both sides (the chunked server pays its
    # TTFT win with per-step chunk work — the trade the operator tunes)
    assert mono["itl"]["count"] > 0 and chunked["itl"]["count"] > 0
