"""JAX jobs tests on the virtual 8-device CPU mesh (conftest forces
JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import (
    ModelConfig,
    factor_axes,
    forward,
    init_params,
    init_state,
    make_mesh,
    make_ring_attention,
    make_train_step,
    mesh_from_allocation,
    next_token_loss,
)

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64, max_seq=64)


def test_factor_axes_balanced():
    assert factor_axes(8) == {"dp": 2, "sp": 2, "tp": 2}
    assert factor_axes(4) == {"dp": 1, "sp": 2, "tp": 2}
    assert factor_axes(2) == {"dp": 1, "sp": 1, "tp": 2}
    assert factor_axes(1) == {"dp": 1, "sp": 1, "tp": 1}
    sizes = factor_axes(16)
    assert sizes["dp"] * sizes["sp"] * sizes["tp"] == 16


def test_forward_shapes_single_device():
    rng = jax.random.PRNGKey(0)
    params = init_params(rng, CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab)
    logits = jax.jit(lambda p, t: forward(p, t, CFG))(params, tokens)
    assert logits.shape == (2, 16, CFG.vocab)
    assert jnp.isfinite(logits).all()


def test_remat_matches_no_remat():
    rng = jax.random.PRNGKey(0)
    params = init_params(rng, CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab)
    cfg_remat = ModelConfig(**{**CFG.__dict__, "remat": True})
    a = forward(params, tokens, CFG)
    b = forward(params, tokens, cfg_remat)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_remat_policy_dots_matches_full_in_gradient():
    """Selective remat ('dots': save matmul outputs, recompute elementwise)
    must be a pure scheduling choice — gradients identical to full remat."""
    rng = jax.random.PRNGKey(0)
    params = init_params(rng, CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab)
    cfg_full = ModelConfig(**{**CFG.__dict__, "remat": True})
    cfg_dots = ModelConfig(**{**CFG.__dict__, "remat": True,
                              "remat_policy": "dots"})

    def loss(cfg):
        return lambda p: jnp.sum(forward(p, tokens, cfg) ** 2)

    gf = jax.grad(loss(cfg_full))(params)
    gd = jax.grad(loss(cfg_dots))(params)
    for a, b in zip(jax.tree_util.tree_leaves(gf), jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)
    with pytest.raises(ValueError):
        ModelConfig(**{**CFG.__dict__, "remat_policy": "everything"})


def test_ring_attention_matches_dense():
    """The load-bearing numerical test: exact causal attention through the
    ring (4-way sequence parallelism) must equal the dense reference."""
    from kubetpu.jobs.model import dense_causal_attention

    mesh = make_mesh({"dp": 2, "sp": 4, "tp": 1})
    rng = jax.random.PRNGKey(0)
    b, s, h, d = 2, 32, 4, 8
    q, k, v = (
        jax.random.normal(key, (b, s, h, d), jnp.float32)
        for key in jax.random.split(rng, 3)
    )
    ring = make_ring_attention(mesh)
    out_ring = jax.jit(ring)(q, k, v)
    out_dense = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), rtol=2e-4, atol=2e-5
    )


def test_loss_with_ring_matches_dense():
    mesh = make_mesh({"dp": 1, "sp": 4, "tp": 2})
    rng = jax.random.PRNGKey(0)
    params = init_params(rng, CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, CFG.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    ring = make_ring_attention(mesh)
    loss_ring = jax.jit(
        lambda p, t, y: next_token_loss(p, t, y, CFG, ring)
    )(params, tokens, targets)
    loss_dense = next_token_loss(params, tokens, targets, CFG)
    np.testing.assert_allclose(float(loss_ring), float(loss_dense), rtol=1e-4)


def test_train_step_runs_and_learns():
    """Full sharded train step on the 2x2x2 mesh: loss must drop on a
    memorizable batch."""
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), CFG, mesh)
    step = make_train_step(CFG, mesh, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, CFG.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    for _ in range(10):
        state, loss = step(state, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses
    assert int(state.step) == 10


def test_chunked_loss_matches_unchunked_value_and_grad():
    """loss_chunk streams the CE tail over sequence chunks — the value and
    the parameter gradients must match the materialized-logits path (same
    f32 log-softmax per position, same mean)."""
    from kubetpu.jobs.model import next_token_loss

    import dataclasses
    cfg = dataclasses.replace(CFG, loss_chunk=0)
    cfg_chunked = dataclasses.replace(CFG, loss_chunk=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)

    l0, g0 = jax.value_and_grad(next_token_loss)(params, tokens, targets, cfg)
    l1, g1 = jax.value_and_grad(next_token_loss)(params, tokens, targets, cfg_chunked)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for p0, p1 in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(p0), np.asarray(p1),
                                   rtol=2e-4, atol=2e-5)

    with pytest.raises(ValueError):  # chunk must divide S
        next_token_loss(params, tokens, targets,
                        dataclasses.replace(CFG, loss_chunk=7))


def test_chunked_loss_trains_on_sharded_mesh():
    """The chunked tail under GSPMD: the (B, S, D) -> chunks reshape must
    compile and train on a dp x sp x tp mesh (chunk count divisible by sp)."""
    import dataclasses
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    cfg = dataclasses.replace(CFG, loss_chunk=8)  # S=32 -> 4 chunks, sp=2 | 4
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    for _ in range(10):
        state, loss = step(state, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses


def test_param_shardings_are_applied():
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, _ = init_state(jax.random.PRNGKey(0), CFG, mesh)
    wq = state.params["blocks"]["wq"]
    # heads axis sharded over tp
    spec = wq.sharding.spec
    assert spec[2] == "tp"
    assert state.params["head"].sharding.spec[1] == "tp"


def test_mesh_from_allocation_orders_by_coords():
    # device k is attached to chip coords[k]; the mesh must walk devices in
    # row-major coordinate order so mesh-adjacent ranks are torus-adjacent.
    coords = [(0, 1), (0, 0), (1, 1), (1, 0)]  # unsorted 2x2 block
    mesh = mesh_from_allocation(coords, {"dp": 1, "sp": 2, "tp": 2})
    assert mesh.devices.shape == (1, 2, 2)
    # sorted coords: (0,0)->dev1, (0,1)->dev0, (1,0)->dev3, (1,1)->dev2
    assert [d.id for d in mesh.devices.flat] == [1, 0, 3, 2]


def test_mesh_insufficient_devices():
    with pytest.raises(ValueError):
        make_mesh({"dp": 16, "sp": 1, "tp": 1})


@pytest.mark.slow
def test_moe_forward_and_gspmd_step():
    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64, n_experts=4)
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    for _ in range(8):
        state, loss = step(state, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_pipeline_forward_matches_reference():
    from kubetpu.jobs.pipeline import make_pipeline_forward

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=4, n_heads=4, d_ff=64)
    mesh = make_mesh({"dp": 2, "pp": 2, "sp": 2, "tp": 1, "ep": 1})
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab)
    pf = make_pipeline_forward(cfg, mesh, n_microbatches=4, use_ring=True)
    got = jax.jit(pf)(params, tokens)
    want = forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_pipeline_chunked_loss_matches_unchunked():
    """The pipelined step honors cfg.loss_chunk (head runs outside the
    manual region): one update from the same state must produce the same
    loss and parameters as the materialized-logits pipeline."""
    import dataclasses

    from kubetpu.jobs.pipeline import (
        init_pipeline_state,
        make_pipeline_train_step,
    )

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=4, n_heads=4, d_ff=64)
    cfgc = dataclasses.replace(cfg, loss_chunk=8)
    mesh = make_mesh({"dp": 2, "pp": 2, "sp": 2, "tp": 1, "ep": 1})
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)

    losses, leaves = [], []
    for c in (cfg, cfgc):
        state, opt = init_pipeline_state(jax.random.PRNGKey(0), c, mesh)
        step = make_pipeline_train_step(c, mesh, n_microbatches=4, optimizer=opt)
        state, loss = step(state, tokens, targets)
        losses.append(float(loss))
        leaves.append(jax.tree.leaves(state.params))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)
    for p0, p1 in zip(*leaves):
        np.testing.assert_allclose(np.asarray(p0), np.asarray(p1),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_pipeline_train_step_five_axes():
    """The full five-axis composition: dp data, pp stages, sp ring, tp
    heads, ep experts — one program, loss decreases."""
    from kubetpu.jobs.pipeline import init_pipeline_state, make_pipeline_train_step

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=4, n_heads=4, d_ff=64, n_experts=2)
    mesh = make_mesh({"dp": 1, "pp": 2, "sp": 2, "tp": 1, "ep": 2})
    state, opt = init_pipeline_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_pipeline_train_step(cfg, mesh, n_microbatches=2, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    for _ in range(6):
        state, loss = step(state, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # layer stack pp-sharded, experts ep-sharded
    assert state.params["blocks"]["wq"].sharding.spec[0] == "pp"
    assert state.params["blocks"]["w_gate"].sharding.spec[1] == "ep"


def test_moe_capacity_matches_dense_dispatch_when_roomy():
    """With capacity >= all tokens, the capacity path must equal the dense
    one-hot dispatch exactly (same experts, same gate weighting)."""
    from kubetpu.jobs.model import _moe_mlp, _moe_mlp_capacity, init_params

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=4, d_ff=64, n_experts=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    layer = jax.tree.map(lambda x: x[0], params["blocks"])  # unstack layer 0
    h = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 32))
    dense, _ = _moe_mlp(h, layer)
    roomy, _ = _moe_mlp_capacity(h, layer, capacity_factor=8.0)  # C >= N
    np.testing.assert_allclose(np.asarray(roomy), np.asarray(dense), rtol=1e-5, atol=1e-6)


def test_moe_capacity_drops_overflow():
    from kubetpu.jobs.model import _moe_mlp_capacity, init_params

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=4, d_ff=64, n_experts=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    layer = jax.tree.map(lambda x: x[0], params["blocks"])
    h = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 32))
    tight, _ = _moe_mlp_capacity(h, layer, capacity_factor=0.25)  # forces drops
    roomy, _ = _moe_mlp_capacity(h, layer, capacity_factor=8.0)
    assert np.isfinite(np.asarray(tight)).all()
    # capacity masking must actually drop: outputs differ from the roomy
    # path, and some token rows are exactly zero (dropped -> residual only)
    assert not np.allclose(np.asarray(tight), np.asarray(roomy))
    tight_rows = np.abs(np.asarray(tight)).sum(axis=-1).ravel()
    assert (tight_rows == 0.0).any()


@pytest.mark.slow
def test_moe_capacity_trains_on_ep_mesh():
    # Slow: a second full MoE train loop on the ep mesh; the top2
    # ep-mesh training test keeps the path tier-1.
    cfg = ModelConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        n_experts=4, moe_capacity_factor=1.5,
    )
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 1, "ep": 4})
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt, attention="dense")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    for _ in range(8):
        state, loss = step(state, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert state.params["blocks"]["w_gate"].sharding.spec[1] == "ep"


def test_bfloat16_model_config():
    import jax.numpy as jnp

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                      dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert params["embed"].dtype == jnp.bfloat16
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab)
    logits = forward(params, tokens, cfg)
    assert logits.dtype == jnp.bfloat16
    assert bool(jnp.isfinite(logits.astype(jnp.float32)).all())
    targets = jnp.roll(tokens, -1, axis=1)
    loss = next_token_loss(params, tokens, targets, cfg)
    assert loss.dtype == jnp.float32  # CE tail always accumulates in f32
    assert bool(jnp.isfinite(loss))


def test_moe_aux_top_k_counts_secondary_assignments():
    """Under top-2 routing, f_e must see second-choice experts: probs where
    every token prefers expert 0 and second-prefers expert 1 give
    f=[.5,.5,0,0] at k=2 (vs [1,0,0,0] at k=1) — hand-check both."""
    from kubetpu.jobs.model import _moe_aux_from_probs

    probs = jnp.tile(jnp.array([[0.5, 0.3, 0.1, 0.1]], jnp.float32), (8, 1))
    e, p = 4, jnp.array([0.5, 0.3, 0.1, 0.1])
    np.testing.assert_allclose(
        float(_moe_aux_from_probs(probs, top_k=1)), e * float(p[0] * 1.0), rtol=1e-6
    )
    np.testing.assert_allclose(
        float(_moe_aux_from_probs(probs, top_k=2)),
        e * float(0.5 * p[0] + 0.5 * p[1]),
        rtol=1e-6,
    )


@pytest.mark.slow
def test_moe_aux_loss_balances_router():
    """With the aux coefficient on, the loss gains a positive term that is
    1.0*coeff*L for a perfectly uniform router and larger when collapsed.
    Slow: compiles three MoE loss variants; the top2/capacity ep-mesh
    training tests keep MoE tier-1 coverage."""
    from kubetpu.jobs.model import forward as fwd

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                      n_experts=4, moe_aux_coeff=0.01)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)

    logits, aux = fwd(params, tokens, cfg, return_aux=True)
    # aux per MoE layer >= 1 (uniform lower bound), summed over layers
    assert float(aux) >= cfg.n_layers * 0.99

    loss_with = next_token_loss(params, tokens, targets, cfg)
    cfg_off = ModelConfig(**{**cfg.__dict__, "moe_aux_coeff": 0.0})
    loss_without = next_token_loss(params, tokens, targets, cfg_off)
    np.testing.assert_allclose(
        float(loss_with), float(loss_without) + 0.01 * float(aux), rtol=1e-5
    )

    # trains on an ep mesh with the aux term active
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 1, "ep": 4})
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt, attention="dense")
    losses = []
    for _ in range(6):
        state, loss = step(state, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_ring_flash_matches_dense():
    """Flash kernels inside the ring steps (interpret mode): forward must
    equal the dense causal reference, like the dense-ring impl."""
    from kubetpu.jobs.model import dense_causal_attention

    mesh = make_mesh({"dp": 2, "sp": 4, "tp": 1})
    rng = jax.random.PRNGKey(0)
    b, s, h, d = 2, 64, 4, 8
    q, k, v = (
        jax.random.normal(key, (b, s, h, d), jnp.float32)
        for key in jax.random.split(rng, 3)
    )
    ring = make_ring_attention(mesh, impl="flash", block_q=8, block_k=8,
                               interpret=True)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_causal_attention(q, k, v)),
        rtol=2e-4, atol=2e-5,
    )


def test_ring_flash_gradients_match_dense_ring():
    """The fused ring backward (dq local accumulation; dk/dv traveling with
    the rotating block) must match autodiff through the dense ring."""
    from kubetpu.jobs.model import dense_causal_attention

    mesh = make_mesh({"dp": 1, "sp": 4, "tp": 1})
    rng = jax.random.PRNGKey(3)
    b, s, h, d = 2, 32, 2, 8
    q, k, v = (
        jax.random.normal(key, (b, s, h, d), jnp.float32)
        for key in jax.random.split(rng, 3)
    )
    cot = jax.random.normal(jax.random.PRNGKey(9), (b, s, h, d), jnp.float32)

    flash_ring = make_ring_attention(mesh, impl="flash", block_q=8, block_k=8,
                                     interpret=True)

    def loss_flash(q, k, v):
        return jnp.sum(flash_ring(q, k, v) * cot)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal_attention(q, k, v) * cot)

    g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_train_step_ring_flash():
    """Full sharded train step with attention='ring_flash_interpret' on a
    dp x sp x tp mesh: loss finite and close to the dense-ring step."""
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), CFG, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, CFG.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    step = make_train_step(CFG, mesh, optimizer=opt,
                           attention="ring_flash_interpret")
    state, loss = step(state, tokens, targets)
    assert jnp.isfinite(loss)

    state2, opt2 = init_state(jax.random.PRNGKey(0), CFG, mesh)
    step2 = make_train_step(CFG, mesh, optimizer=opt2, attention="ring")
    state2, loss2 = step2(state2, tokens, targets)
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-4)


def test_ring_flash_gradients_finite_with_outlier_logits():
    """Invisible ring steps score against a global lse that does not cover
    them; with outlier logits the unclamped exp overflowed to inf and the
    0-gate turned it into NaN. Gradients must stay finite (and correct)."""
    from kubetpu.jobs.model import dense_causal_attention

    mesh = make_mesh({"dp": 1, "sp": 4, "tp": 1})
    b, s, h, d = 1, 32, 2, 8
    keys = jax.random.split(jax.random.PRNGKey(5), 3)
    q = 30.0 * jax.random.normal(keys[0], (b, s, h, d), jnp.float32)
    k = 30.0 * jax.random.normal(keys[1], (b, s, h, d), jnp.float32)
    v = jax.random.normal(keys[2], (b, s, h, d), jnp.float32)

    ring = make_ring_attention(mesh, impl="flash", block_q=8, block_k=8,
                               interpret=True)
    grad_fn = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v)),
                               argnums=(0, 1, 2)))
    # 30x logits: the pre-clamp kernel produced NaN here; only finiteness is
    # numerically meaningful at this scale (exp(s - lse) amplifies f32 lse
    # rounding by e^|s| in ANY implementation)
    for gf in grad_fn(q, k, v):
        assert np.isfinite(np.asarray(gf)).all()
    # 5x logits: still sharply peaked, but conditioned well enough that the
    # ring-flash gradients must match autodiff through the dense reference
    q5, k5 = q / 6.0, k / 6.0
    g_flash = grad_fn(q5, k5, v)
    g_dense = jax.grad(
        lambda q, k, v: jnp.sum(dense_causal_attention(q, k, v)),
        argnums=(0, 1, 2),
    )(q5, k5, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_pipeline_train_step_ring_flash():
    """The five-axis pipeline step with flash kernels inside the ring
    (the {pp, sp}-manual region takes the flash-ring local body directly):
    loss finite and equal to the dense-ring pipeline's."""
    from kubetpu.jobs.pipeline import init_pipeline_state, make_pipeline_train_step

    mesh = make_mesh({"dp": 1, "pp": 2, "sp": 2, "tp": 1, "ep": 2})
    cfg = ModelConfig(vocab=64, d_model=32, n_layers=4, n_heads=4, d_ff=64,
                      n_experts=2)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)

    state, opt = init_pipeline_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_pipeline_train_step(cfg, mesh, n_microbatches=2, optimizer=opt,
                                    ring_impl="flash", interpret=True)
    state, loss = step(state, tokens, targets)
    assert jnp.isfinite(loss)

    state2, opt2 = init_pipeline_state(jax.random.PRNGKey(0), cfg, mesh)
    step2 = make_pipeline_train_step(cfg, mesh, n_microbatches=2, optimizer=opt2)
    state2, loss2 = step2(state2, tokens, targets)
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-4)


@pytest.mark.slow
def test_gradient_accumulation_matches_full_batch():
    """accum_steps=2 over one batch must produce the SAME update as the
    unaccumulated step (equal-size chunks: mean of chunk means == full
    mean), up to float reassociation."""
    import numpy as np

    from kubetpu.jobs import ModelConfig, init_state, make_mesh, make_train_step

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                      dtype=jnp.float32)
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1}, devices=jax.devices()[:1])
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab,
                                jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)

    results = {}
    for accum in (1, 2):
        state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_train_step(cfg, mesh, optimizer=opt, use_ring=False,
                               accum_steps=accum)
        state, loss = step(state, tokens, targets)
        results[accum] = (float(loss), state.params)

    assert np.isclose(results[1][0], results[2][0], rtol=1e-5)
    flat1 = jax.tree_util.tree_leaves(results[1][1])
    flat2 = jax.tree_util.tree_leaves(results[2][1])
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_accumulation_rejects_indivisible_batch():
    from kubetpu.jobs import ModelConfig, init_state, make_mesh, make_train_step

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1}, devices=jax.devices()[:1])
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt, use_ring=False,
                           accum_steps=3)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab,
                                jnp.int32)
    with pytest.raises(ValueError, match="divisible"):
        step(state, tokens, jnp.roll(tokens, -1, axis=1))


def test_optimizer_schedule_and_clipping():
    """Warmup+cosine: lr starts ~0, peaks after warmup, decays toward the
    floor; clipping bounds the global update norm."""
    import numpy as np
    import optax

    from kubetpu.jobs.train import make_optimizer

    sched_tx = make_optimizer(lr=1.0, warmup_steps=10, decay_steps=100,
                              min_lr_ratio=0.1)
    # probe the schedule through the optimizer's update scale on a fixed
    # gradient: adamw's normalized step magnitude tracks the lr
    params = {"w": jnp.ones((4,))}
    opt_state = sched_tx.init(params)
    grads = {"w": jnp.ones((4,))}
    mags = []
    for _ in range(100):
        updates, opt_state = sched_tx.update(grads, opt_state, params)
        mags.append(float(jnp.abs(updates["w"]).max()))
    assert mags[0] < mags[9] * 0.5        # warmup: early steps tiny
    assert max(mags) == max(mags[5:15])   # peak right after warmup
    assert mags[-1] < max(mags) * 0.5     # cosine decayed

    # clipping: chain(clip, adamw) on an over-norm gradient must equal
    # plain adamw on the PRE-clipped gradient — the probe fails if the
    # clip link is dropped or chained after the update
    clip_tx = make_optimizer(lr=1.0, clip_norm=0.5)
    plain_tx = make_optimizer(lr=1.0)
    big = {"w": jnp.full((4,), 1e6)}
    gnorm = float(optax.global_norm(big))
    pre_clipped = {"w": big["w"] * (0.5 / gnorm)}
    u_clip, _ = clip_tx.update(big, clip_tx.init(params), params)
    u_ref, _ = plain_tx.update(pre_clipped, plain_tx.init(params), params)
    np.testing.assert_allclose(np.asarray(u_clip["w"]), np.asarray(u_ref["w"]),
                               rtol=1e-6)


def test_bidirectional_ring_matches_dense_fwd_and_grad():
    """causal=False ring (dense AND flash impls) == full bidirectional
    attention, forward and gradients — sequence parallelism for the
    encoder/seq2seq families."""
    from kubetpu.jobs.encoder import dense_bidirectional_attention
    from kubetpu.jobs.ring_attention import make_ring_attention

    mesh = make_mesh({"dp": 2, "sp": 4, "tp": 1})
    rng = jax.random.PRNGKey(3)
    b, s, h, d = 2, 32, 4, 8
    q, k, v = (
        jax.random.normal(key, (b, s, h, d), jnp.float32)
        for key in jax.random.split(rng, 3)
    )
    ref = dense_bidirectional_attention(q, k, v)
    for impl in ("dense", "flash"):
        ring = make_ring_attention(mesh, impl=impl, causal=False,
                                   block_q=8, block_k=8,
                                   interpret=(impl == "flash"))
        out = jax.jit(ring)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, err_msg=impl)

        gr = jax.jit(jax.grad(lambda a, b_, c: jnp.sum(ring(a, b_, c) ** 2),
                              argnums=(0, 1, 2)))(q, k, v)
        gd = jax.grad(
            lambda a, b_, c: jnp.sum(dense_bidirectional_attention(a, b_, c) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for x, y in zip(gr, gd):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=5e-3, err_msg=impl)


def test_encoder_forward_under_bidirectional_ring():
    """encoder_forward with the causal=False ring equals its dense self on
    an sp mesh (global positions supplied per shard semantics)."""
    from kubetpu.jobs.encoder import encoder_forward
    from kubetpu.jobs.ring_attention import make_ring_attention

    mesh = make_mesh({"dp": 2, "sp": 4, "tp": 1})
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, CFG.vocab)
    ref = encoder_forward(params, tokens, CFG)
    ring = make_ring_attention(mesh, causal=False)
    out = encoder_forward(params, tokens, CFG, attn_fn=ring)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3)


def test_moe_top2_matches_manual_weighted_sum_when_roomy():
    """With ample capacity, top-2 output == sum over a token's two best
    experts of raw_prob * expert(token) — computed against a hand-rolled
    per-expert reference."""
    from kubetpu.jobs.model import _moe_mlp_capacity, init_params

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=4, d_ff=64,
                      n_experts=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    layer = jax.tree.map(lambda x: x[0], params["blocks"])
    h = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 32))

    got, probs = _moe_mlp_capacity(h, layer, capacity_factor=8.0, top_k=2)

    def expert_out(tok, ei):
        gate = jax.nn.silu(tok @ layer["w_gate"][ei])
        return (gate * (tok @ layer["w_up"][ei])) @ layer["w_down"][ei]

    toks = np.asarray(h.reshape(-1, 32))
    p = np.asarray(probs)
    want = np.zeros_like(toks)
    for i, tok in enumerate(toks):
        order = np.argsort(-p[i])
        for ei in order[:2]:
            want[i] += p[i, ei] * np.asarray(expert_out(jnp.asarray(tok), int(ei)))
    np.testing.assert_allclose(np.asarray(got).reshape(-1, 32), want,
                               rtol=1e-4, atol=1e-5)


def test_moe_top2_primary_outranks_secondary_under_tight_capacity():
    """Rank-major slot claiming: when capacity is scarce, a token's
    PRIMARY expert assignment survives in preference to other tokens'
    secondary ones — the expert still computes, and no NaNs appear."""
    from kubetpu.jobs.model import _moe_mlp_capacity, init_params

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=4, d_ff=64,
                      n_experts=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    layer = jax.tree.map(lambda x: x[0], params["blocks"])
    h = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 32))
    # top_k=2 with E=2: every token picks both experts; tight capacity
    # means secondary ranks mostly drop while primaries stay
    tight, _ = _moe_mlp_capacity(h, layer, capacity_factor=0.5, top_k=2)
    roomy, _ = _moe_mlp_capacity(h, layer, capacity_factor=8.0, top_k=2)
    assert np.isfinite(np.asarray(tight)).all()
    assert not np.allclose(np.asarray(tight), np.asarray(roomy))


@pytest.mark.slow
def test_moe_top2_trains_on_ep_mesh():
    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                      n_experts=2, moe_capacity_factor=2.0, moe_top_k=2,
                      moe_aux_coeff=0.01)
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 1, "ep": 2})
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt, attention="dense")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    for _ in range(8):
        state, loss = step(state, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_top_k_validation():
    with pytest.raises(ValueError):
        ModelConfig(n_experts=2, moe_top_k=3, moe_capacity_factor=1.0)
    with pytest.raises(ValueError):
        ModelConfig(n_experts=2, moe_top_k=2)  # needs capacity path
    with pytest.raises(ValueError):
        ModelConfig(moe_top_k=0)


def test_skip_nonfinite_guards_the_update():
    """A poisoned batch (non-finite grads via inf-scaled params path) must
    leave params and optimizer state untouched while the step counter
    advances; clean batches update normally under the same compiled step."""
    from kubetpu.jobs.train import make_optimizer, make_update_step

    cfg = CFG
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt = make_optimizer(lr=1e-2)
    opt_state = opt.init(params)
    from kubetpu.jobs import TrainState
    state = TrainState(params, opt_state, jnp.zeros((), jnp.int32))

    def loss_fn(p, tokens, poison):
        from kubetpu.jobs import next_token_loss
        clean = next_token_loss(p, tokens, jnp.roll(tokens, -1, axis=1), cfg)
        return clean + poison * jnp.sum(p["head"])  # poison=inf -> inf loss

    step = make_update_step(loss_fn, opt, skip_nonfinite=True)
    step = jax.jit(step)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab)

    poisoned, loss_bad = step(state, tokens, jnp.float32(jnp.inf))
    assert not np.isfinite(float(loss_bad))
    assert int(poisoned.step) == 1  # counter still advances
    for a, b in zip(jax.tree_util.tree_leaves(poisoned.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    clean, loss_ok = step(poisoned, tokens, jnp.float32(0.0))
    assert np.isfinite(float(loss_ok)) and int(clean.step) == 2
    assert not np.allclose(np.asarray(clean.params["head"]),
                           np.asarray(state.params["head"]))


@pytest.mark.slow
def test_label_smoothing_and_z_loss_formulas():
    """Hand-check both regularizers against their definitions, and pin
    chunked/materialized parity with both active."""
    import dataclasses

    from kubetpu.jobs.model import next_token_loss, token_cross_entropy

    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (2, 8, 16)) * 3.0
    targets = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 16)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    lse = jax.nn.logsumexp(logits, axis=-1)

    eps, z = 0.1, 1e-2
    want = jnp.mean((1 - eps) * nll - eps * jnp.mean(logp, -1) + z * lse**2)
    got = token_cross_entropy(logits, targets, label_smoothing=eps, z_loss=z)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-6)
    # off = plain CE
    np.testing.assert_allclose(
        float(token_cross_entropy(logits, targets)), float(jnp.mean(nll)),
        rtol=1e-6)

    cfg = dataclasses.replace(CFG, label_smoothing=0.1, z_loss=1e-3)
    cfgc = dataclasses.replace(cfg, loss_chunk=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, cfg.vocab)
    tgt = jnp.roll(tokens, -1, axis=1)
    l0, g0 = jax.value_and_grad(next_token_loss)(params, tokens, tgt, cfg)
    l1, g1 = jax.value_and_grad(next_token_loss)(params, tokens, tgt, cfgc)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for p0, p1 in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(p0), np.asarray(p1),
                                   rtol=2e-4, atol=2e-5)

    with pytest.raises(ValueError):
        ModelConfig(label_smoothing=1.0)
    with pytest.raises(ValueError):
        ModelConfig(z_loss=-0.1)


@pytest.mark.slow
def test_windowed_training_learns_with_dense_and_banded_ring():
    import dataclasses

    cfg = dataclasses.replace(CFG, window=8)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)

    def run(mesh, **kw):
        state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
        step = make_train_step(cfg, mesh, optimizer=opt, **kw)
        losses = []
        for _ in range(10):
            state, loss = step(state, tokens, targets)
            losses.append(float(loss))
        assert np.isfinite(losses).all() and losses[-1] < losses[0] * 0.95
        return losses

    dense = run(make_mesh({"dp": 2, "sp": 1, "tp": 2}), use_ring=False)
    # round 5: window x sp compose (banded ring) — same losses as dense
    banded = run(make_mesh({"dp": 2, "sp": 2, "tp": 2}), attention="ring")
    np.testing.assert_allclose(banded, dense, rtol=1e-4)
    # eval measures the SAME banded objective (review r5: it used to build
    # an unwindowed ring for windowed configs)
    from kubetpu.jobs import make_eval_step

    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    params = init_params(jax.random.PRNGKey(0), cfg)
    eval_ring = make_eval_step(cfg, mesh)(params, tokens, targets)
    eval_dense = make_eval_step(cfg, mesh, use_ring=False)(
        params, tokens, targets)
    np.testing.assert_allclose(float(eval_ring), float(eval_dense), rtol=1e-4)
    with pytest.raises(ValueError):
        ModelConfig(window=-1)


def test_banded_ring_matches_dense_windowed_fwd_and_grad():
    """The ring x window composition is EXACT: banded-ring attention out
    and gradients equal the dense sliding-window reference."""
    from functools import partial

    from kubetpu.jobs.model import dense_attention

    window = 6
    mesh = make_mesh({"dp": 2, "sp": 4, "tp": 1})
    rng = jax.random.PRNGKey(0)
    b, s, h, d = 2, 32, 4, 8
    q, k, v = (
        jax.random.normal(key, (b, s, h, d), jnp.float32)
        for key in jax.random.split(rng, 3)
    )
    banded = make_ring_attention(mesh, window=window)
    out_ring = jax.jit(banded)(q, k, v)
    out_dense = dense_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), rtol=2e-4, atol=2e-5
    )

    def loss(core):
        return lambda q, k, v: jnp.sum(core(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss(banded), argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(
        loss(partial(dense_attention, causal=True, window=window)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)
    # window wider than the local block: clear refusal at trace time
    with pytest.raises(ValueError):
        jax.jit(make_ring_attention(mesh, window=s // 4 + 1))(q, k, v)


def test_pipeline_window_with_and_without_ring():
    import dataclasses

    from kubetpu.jobs.pipeline import make_pipeline_forward

    cfg = dataclasses.replace(
        ModelConfig(vocab=64, d_model=32, n_layers=4, n_heads=4, d_ff=64),
        window=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab)
    want = forward(params, tokens, cfg)  # default attn honors the window
    # round 5: the pipeline's ring composes with the window (banded ring)
    mesh = make_mesh({"dp": 2, "pp": 2, "sp": 2, "tp": 1, "ep": 1})
    pf_ring = make_pipeline_forward(cfg, mesh, n_microbatches=4, use_ring=True)
    got_ring = jax.jit(pf_ring)(params, tokens)
    np.testing.assert_allclose(np.asarray(got_ring), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    mesh2 = make_mesh({"dp": 2, "pp": 2, "sp": 1, "tp": 2, "ep": 1})
    pf = make_pipeline_forward(cfg, mesh2, n_microbatches=4, use_ring=False)
    got = jax.jit(pf)(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


# -- multislice (dcn axis) ---------------------------------------------------


def test_make_multislice_mesh_dcn_outermost():
    from kubetpu.jobs import make_multislice_mesh

    mesh = make_multislice_mesh({"dcn": 2, "dp": 1, "sp": 2, "tp": 2})
    assert mesh.axis_names[0] == "dcn"
    assert mesh.shape == {"dcn": 2, "dp": 1, "sp": 2, "tp": 2}
    # dcn strides across the per-slice device groups: slice 0 devices
    # all precede slice 1 devices in the flat (virtual) ordering
    devs = np.asarray(mesh.devices)
    ids0 = {d.id for d in devs[0].flat}
    ids1 = {d.id for d in devs[1].flat}
    assert max(ids0) < min(ids1)
    with pytest.raises(ValueError):
        make_multislice_mesh({"dp": 2, "tp": 2})  # no dcn axis
    with pytest.raises(ValueError):
        make_multislice_mesh({"dcn": 4, "tp": 4})  # 16 > 8 devices


def test_make_multislice_mesh_rejects_oversupply():
    """An EXPLICIT device list larger than the mesh raises (mirroring the
    undersupply errors) instead of silently truncating — dropped chips
    would sit idle behind a placement bug. The implicit jax.devices()
    path stays permissive."""
    from kubetpu.jobs import make_multislice_mesh

    devs = jax.devices()
    # flat oversupply: 8 devices explicitly supplied for a 4-device mesh
    with pytest.raises(ValueError, match="truncat"):
        make_multislice_mesh({"dcn": 2, "tp": 2}, devices=devs)
    # exact explicit supply still builds
    mesh = make_multislice_mesh({"dcn": 2, "tp": 2}, devices=devs[:4])
    assert mesh.shape == {"dcn": 2, "tp": 2}
    # implicit (process-wide) devices keep take-what-fits behavior
    mesh = make_multislice_mesh({"dcn": 2, "tp": 2})
    assert mesh.shape == {"dcn": 2, "tp": 2}

    class FakeDev:
        def __init__(self, i, s):
            self.id, self.slice_index = i, s

    # grouped oversupply: 3 slice groups for dcn=2, and a fat group
    fake6 = [FakeDev(i, i // 2) for i in range(6)]
    with pytest.raises(ValueError, match="3"):
        make_multislice_mesh({"dcn": 2, "tp": 2}, devices=fake6)
    fat = [FakeDev(0, 0), FakeDev(1, 0), FakeDev(2, 0), FakeDev(3, 1),
           FakeDev(4, 1)]
    with pytest.raises(ValueError, match="idle"):
        make_multislice_mesh({"dcn": 2, "tp": 2}, devices=fat)


@pytest.mark.slow
def test_multislice_train_step_matches_single_slice_dp():
    """{dcn:2, dp:1, sp:2, tp:2} training must be numerically the same
    computation as {dp:2, sp:2, tp:2}: dcn and dp are both pure data axes
    (params replicated over dcn; the only DCN collective is the gradient
    all-reduce)."""
    from kubetpu.jobs import make_multislice_mesh

    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, CFG.vocab)
    targets = jnp.roll(tokens, -1, axis=1)

    def run(mesh):
        state, opt = init_state(jax.random.PRNGKey(0), CFG, mesh)
        step = make_train_step(CFG, mesh, optimizer=opt)
        losses = []
        for _ in range(3):
            state, loss = step(state, tokens, targets)
            losses.append(float(loss))
        return losses

    ms = run(make_multislice_mesh({"dcn": 2, "dp": 1, "sp": 2, "tp": 2}))
    ref = run(make_mesh({"dp": 2, "sp": 2, "tp": 2}))
    np.testing.assert_allclose(ms, ref, rtol=1e-5)
    assert ms[-1] < ms[0]
