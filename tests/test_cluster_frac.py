"""Fractional (vChip, Round-18) cluster accounting edge cases: milli-unit
parsing and rounding, best-fit bin-packing and anti-fragmentation,
exact-capacity restoration on release AND preemption, coexistence with
whole-chip gangs and the multislice pseudo-resources, and the
``check_invariants`` packing oracle."""

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster, SchedulingError
from kubetpu.core.cluster import PriorityKey
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.scheduler.meshstate import (
    MILLI_PER_CHIP,
    FracKey,
    MultisliceKey,
    parse_milli,
    pod_milli,
)


def frac_pod(name, milli, **extra_requests):
    return PodInfo(name=name, requests={FracKey: milli, **extra_requests},
                   running_containers={"main": ContainerInfo()})


def tpu_pod(name, chips, **extra_requests):
    return PodInfo(
        name=name, requests=dict(extra_requests),
        running_containers={
            "main": ContainerInfo(requests={ResourceTPU: chips})})


def v5e8_cluster(num_nodes=1):
    cluster = Cluster()
    for i in range(num_nodes):
        cluster.register_node(
            f"frac-n{i}",
            device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")))
    return cluster


def free_snapshot(cluster):
    out = {}
    for name, node in sorted(cluster.nodes.items()):
        for key, val in sorted(node.info.allocatable.items()):
            if key.endswith(("/cards", "/milli")) or key == ResourceTPU:
                out[(name, key)] = val
    return out


# -- milli-unit parsing and rounding ----------------------------------------


def test_parse_milli_forms():
    assert parse_milli("250m") == 250
    assert parse_milli("0.25") == 250
    assert parse_milli(0.25) == 250
    assert parse_milli(1) == 1
    assert parse_milli("999m") == 999
    # float rounding: a third of a chip rounds to the nearest milli
    assert parse_milli(1 / 3) == 333


@pytest.mark.parametrize("bad", ["0m", "1000m", 0, 1000, 1.0, -0.5, "2.0"])
def test_parse_milli_rejects_out_of_range(bad):
    with pytest.raises(ValueError):
        parse_milli(bad)


def test_pod_milli_validates_stamp():
    assert pod_milli(frac_pod("p", 250)) == 250
    assert pod_milli(tpu_pod("w", 1)) == 0
    with pytest.raises(ValueError):
        pod_milli(frac_pod("p", 1000))
    with pytest.raises(ValueError):
        pod_milli(frac_pod("p", -1))
    # wire clients POST pod requests verbatim: the documented milli
    # grammar must work on the server-side read too, not only in
    # client-side parse_milli calls
    assert pod_milli(frac_pod("p", "250m")) == 250
    assert pod_milli(frac_pod("p", "0.5")) == 500
    with pytest.raises(ValueError):
        pod_milli(frac_pod("p", "banana"))


def test_string_stamp_schedules_end_to_end():
    cluster = v5e8_cluster()
    placed = cluster.schedule(frac_pod("s", "250m"))
    assert cluster.pod_vchip(placed)[2] == 250
    assert cluster.check_invariants() == []
    cluster.release("s")
    assert cluster.check_invariants() == []


def test_rescheduled_fractional_pod_sheds_stale_milli_key():
    """A pod object that was previously PLACED still carries its old
    chip's /milli binding when it comes back through schedule (the
    library boundary accepts re-submitted pod objects, like the
    whole-chip grammar does) — the fill must shed the stale key, or
    ``_account`` moves the share on BOTH chips and strands phantom
    capacity on the new books."""
    from kubetpu.core.group_scheduler import held_milli

    cluster = v5e8_cluster()
    cluster.schedule(frac_pod("a", 500))
    vc = cluster.schedule(frac_pod("vc", 500))      # fills chip 0
    old_coord = cluster.pod_vchip(vc)[1]
    cluster.release("vc")
    cluster.schedule(frac_pod("f", 500))            # re-fills chip 0
    placed = cluster.schedule(vc)                   # still stamped w/ chip 0
    assert cluster.pod_vchip(placed)[1] != old_coord
    assert len(held_milli(placed)) == 1             # exactly one binding
    assert cluster.check_invariants() == []


# -- placement: bin-packing, rounding, exclusivity --------------------------


def test_quarters_pack_one_chip_and_fill_exactly():
    cluster = v5e8_cluster()
    placed = [cluster.schedule(frac_pod(f"q{i}", 250)) for i in range(4)]
    coords = {cluster.pod_vchip(p)[1] for p in placed}
    assert len(coords) == 1          # best-fit concentrates the confetti
    assert cluster.check_invariants() == []
    # the chip is exactly full: a 1-milli crumb must land elsewhere
    crumb = cluster.schedule(frac_pod("crumb", 1))
    assert cluster.pod_vchip(crumb)[1] not in coords
    assert cluster.check_invariants() == []


def test_milli_rounding_999_plus_1_fills_exactly():
    cluster = v5e8_cluster()
    a = cluster.schedule(frac_pod("a", parse_milli("999m")))
    b = cluster.schedule(frac_pod("b", parse_milli("1m")))
    # best-fit: the 1m completes the 999m chip to exactly 1000
    assert cluster.pod_vchip(a)[1] == cluster.pod_vchip(b)[1]
    assert cluster.check_invariants() == []
    occ = cluster.chip_occupancy()["frac-n0"]
    assert any(f == 1.0 for f in occ.values())


def test_fractional_chip_invisible_to_whole_placement():
    cluster = v5e8_cluster()
    cluster.schedule(frac_pod("f", 250))
    # all 8 chips still advertise cards, but only 7 are whole-free:
    # an 8-chip pod must not land on the fractionally-occupied chip
    with pytest.raises(SchedulingError):
        cluster.schedule(tpu_pod("whole8", 8))
    placed = cluster.schedule(tpu_pod("whole7", 7))
    assert placed.node_name == "frac-n0"
    assert cluster.check_invariants() == []


def test_whole_held_chip_refuses_fractions():
    cluster = v5e8_cluster()
    cluster.schedule(tpu_pod("whole8", 8))   # every chip whole-held
    with pytest.raises(SchedulingError):
        cluster.schedule(frac_pod("f", 1))
    assert cluster.check_invariants() == []


def test_mixing_whole_and_frac_in_one_pod_refused():
    cluster = v5e8_cluster()
    with pytest.raises(SchedulingError, match="cannot mix"):
        cluster.schedule(tpu_pod("mixed", 1, **{FracKey: 250}))


def test_malformed_frac_stamp_raises_value_error():
    cluster = v5e8_cluster()
    with pytest.raises(ValueError):
        cluster.schedule(frac_pod("bad", 1500))


def test_release_restores_exact_capacity():
    cluster = v5e8_cluster()
    pristine = free_snapshot(cluster)
    placed = [cluster.schedule(frac_pod(f"f{i}", m))
              for i, m in enumerate((250, 500, 125, 333))]
    assert free_snapshot(cluster) != pristine
    for p in placed:
        cluster.release(p.name)
    assert free_snapshot(cluster) == pristine
    assert cluster.check_invariants() == []


# -- preemption --------------------------------------------------------------


def test_preempting_fractional_pods_restores_exact_capacity():
    """A higher-priority whole-node pod evicts the fractional occupants;
    the freed chips rejoin the whole pool at EXACTLY full capacity."""
    cluster = v5e8_cluster()
    pristine = free_snapshot(cluster)
    lows = [cluster.schedule(frac_pod(f"low{i}", 500)) for i in range(16)]
    assert cluster.check_invariants() == []
    high = tpu_pod("high8", 8, **{PriorityKey: 10})
    placed, evicted = cluster.schedule_preempting(high)
    assert placed.node_name == "frac-n0"
    assert len(evicted) == 16
    assert cluster.check_invariants() == []
    cluster.release("high8")
    assert free_snapshot(cluster) == pristine


def test_preemption_evicts_only_enough_fractions():
    """A 1-chip preemptor needs ONE chip vacated — the greedy loop must
    stop once a chip's occupants are gone, not clear the node."""
    cluster = v5e8_cluster()
    # two chips carry fractions (each 2x500m via best-fit); the other
    # six are whole-held by mid-priority fillers
    for i in range(4):
        cluster.schedule(frac_pod(f"low{i}", 500))
    for i in range(6):
        cluster.schedule(tpu_pod(f"filler{i}", 1, **{PriorityKey: 5}))
    placed, evicted = cluster.schedule_preempting(
        tpu_pod("high1", 1, **{PriorityKey: 10}))
    assert len(evicted) == 2           # one chip's worth of 500m shares
    assert cluster.check_invariants() == []


def test_fractional_preemptor_evicts_lower_priority_fraction():
    cluster = v5e8_cluster()
    # saturate every chip's milli with low-priority halves
    lows = [cluster.schedule(frac_pod(f"low{i}", 500)) for i in range(16)]
    assert len(lows) == 16
    placed, evicted = cluster.schedule_preempting(
        frac_pod("vip", 500, **{PriorityKey: 10}))
    assert pod_milli(placed) == 500
    assert len(evicted) >= 1
    assert cluster.check_invariants() == []


# -- gangs: capacity pre-filter, multislice coexistence ----------------------


def test_fractional_gang_pins_single_slice_and_prefilters():
    """An all-fractional gang is an ICI gang: the milli pre-filter must
    skip a slice that provably lacks fractional capacity."""
    cluster = Cluster()
    for uid, prefix in (("podA", "a"), ("podB", "b")):
        cluster.register_node(
            f"{prefix}0",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-8", slice_uid=uid)))
    # podA nearly full: 8 chips x 900m leaves 800 milli total
    for i in range(8):
        cluster.schedule(
            frac_pod(f"fill{i}", 900),
        )
    gang = cluster.schedule_gang(
        [frac_pod(f"g{i}", 600) for i in range(4)])
    # 4x600m does not fit podA's 8x100m remainder -> whole gang on podB
    homes = {p.node_name for p in gang}
    assert len(homes) == 1
    assert cluster.check_invariants() == []


def test_fractional_and_multislice_stamps_coexist():
    """Fractional confetti on both slices must not break a multislice
    whole-chip gang, and the gang's pseudo-resources must not confuse
    the fractional books."""
    from kubetpu.scheduler.meshstate import GangSlicesKey

    cluster = Cluster()
    for uid, prefix in (("podA", "a"), ("podB", "b")):
        for h in range(2):
            cluster.register_node(
                f"{prefix}{h}",
                device=new_fake_tpu_dev_manager(
                    make_fake_tpus_info(
                        "v5e-16", host_index=h, slice_uid=uid)))
    # a vChip on each slice
    fracs = [cluster.schedule(frac_pod(f"vc{i}", 250)) for i in range(2)]
    # a 16-chip gang must span both 8-chip-free... each v5e-16 host has
    # 8 chips; slice = 16 chips, one chip per slice is fractional ->
    # 15 whole-free per slice: an 8-pod x 3-chip gang (24 chips) needs
    # the multislice escape hatch over the two slices
    gang = cluster.schedule_gang([
        tpu_pod(f"w{i}", 3, **{MultisliceKey: 2}) for i in range(8)])
    assert len(gang) == 8
    assert all(p.requests[GangSlicesKey] == 2 for p in gang)
    assert cluster.check_invariants() == []
    # fractional pods still release exactly under the gang
    for p in fracs:
        cluster.release(p.name)
    assert cluster.check_invariants() == []


# -- the packing oracle ------------------------------------------------------


def test_check_invariants_catches_corrupted_milli():
    cluster = v5e8_cluster()
    placed = cluster.schedule(frac_pod("f", 250))
    node = cluster.nodes["frac-n0"]
    mkey = next(k for k in node.info.allocatable if k.endswith("/milli")
                and node.info.allocatable[k] == MILLI_PER_CHIP - 250)
    node.info.allocatable[mkey] += 100   # corrupt: free > cap - held
    problems = cluster.check_invariants()
    assert any("/milli" in p for p in problems)
    node.info.allocatable[mkey] -= 100
    assert cluster.check_invariants() == []
    assert placed.node_name == "frac-n0"


def test_check_invariants_catches_double_grammar_hold():
    """A chip simultaneously whole-held and fractionally occupied is the
    cardinal vChip violation."""
    cluster = v5e8_cluster()
    cluster.schedule(frac_pod("f", 250))
    node = cluster.nodes["frac-n0"]
    # forge a whole hold on the fractionally-occupied chip
    mkey = next(k for k in node.info.allocatable if k.endswith("/milli")
                and node.info.allocatable[k] == MILLI_PER_CHIP - 250)
    ckey = mkey[: -len("/milli")] + "/cards"
    forged = PodInfo(name="forged", running_containers={
        "main": ContainerInfo(allocate_from={ckey: ckey})})
    node.pods["forged"] = forged
    node.info.allocatable[ckey] -= 1
    node.info.allocatable[ResourceTPU] -= 1
    problems = cluster.check_invariants()
    assert any("whole-held AND carries" in p for p in problems)


def test_status_and_occupancy_expose_fragmentation():
    cluster = v5e8_cluster()
    cluster.schedule(frac_pod("f", 400))
    st = cluster.status()["nodes"]["frac-n0"]
    assert st["frac_partial_chips"] == 1
    assert st["free_milli"] == 8 * MILLI_PER_CHIP - 400
    assert st["free_chips"] == 7          # the broken chip left the pool
    occ = cluster.chip_occupancy()["frac-n0"]
    assert sorted(occ.values(), reverse=True)[0] == pytest.approx(0.4)
    # fill the chip exactly: a FULLY-packed chip strands nothing, so it
    # leaves the fragmentation count (status and the CLI frag line agree
    # on 0 < occupancy < 1.0 — the gauge renders packed and whole-held
    # chips identically at 1.0, so "partial" must exclude both)
    cluster.schedule(frac_pod("g", 600))
    st = cluster.status()["nodes"]["frac-n0"]
    assert st["frac_partial_chips"] == 0
    assert st["free_milli"] == 7 * MILLI_PER_CHIP


def test_controller_gauges_and_cli_frag_line():
    """The Round-18 obs surface: per-chip occupancy gauges + the
    fractional-allocations counter on the controller registry, and the
    obs CLI's fragmentation line rendered from them."""
    from kubetpu.cli.obs import render_summary
    from kubetpu.wire.controller import ControllerServer

    cluster = v5e8_cluster()
    ctl = ControllerServer(cluster=cluster)
    placed = [cluster.schedule(frac_pod(f"vc{i}", 250)) for i in range(3)]
    with ctl._lock:
        ctl._count_fractional(placed)
        ctl._update_occupancy_gauges()
    text = ctl.registry.render()
    assert ('kubetpu_chip_occupancy_frac{node="frac-n0",chip="0"} 0.75'
            in text)
    assert "kubetpu_fractional_allocations_total 3" in text
    out = render_summary(text, "controller")
    assert "frag      partial_chips=1/8 mean_occ=0.75 frac_allocs=3" in out
    # the legacy fleet gauges see the DERIVED exclusivity: the chip the
    # three vChips broke is not whole-free, even though fractional
    # accounting never touches the scalar tally
    free, held = ctl._chip_totals(ResourceTPU)
    assert (free, held) == (7, 1)
    # a chip that leaves the fleet pins to 0.0, never a stale last-good
    cluster.remove_node("frac-n0")
    with ctl._lock:
        ctl._update_occupancy_gauges()
    text = ctl.registry.render()
    assert ('kubetpu_chip_occupancy_frac{node="frac-n0",chip="0"} 0'
            in text)


def test_fractional_needs_mesh_geometry():
    """A node without slice geometry (no tpu-slice key) cannot host
    vChips — the milli advertisement rides the chip-coordinate
    grammar."""
    from kubetpu.api.types import NodeInfo

    cluster = Cluster()
    info = NodeInfo(name="flat")
    info.kube_alloc[ResourceTPU] = 4
    info.kube_cap[ResourceTPU] = 4
    info.capacity[ResourceTPU] = 4
    info.allocatable[ResourceTPU] = 4
    cluster.register_node("flat", node_info=info)
    with pytest.raises(SchedulingError):
        cluster.schedule(frac_pod("f", 250))
