"""Jobs-side tracing/profiling utilities (SURVEY.md §5.1)."""

import os

import jax
import pytest
import jax.numpy as jnp

from kubetpu.jobs.profiling import StepTimer, trace


@pytest.mark.slow
def test_trace_writes_profile(tmp_path):
    # Slow: real profiler trace write + parse round trip; the StepTimer
    # and coverage pins keep profiling tier-1.
    @jax.jit
    def f(x):
        return x @ x

    x = jnp.ones((64, 64))
    f(x).block_until_ready()  # compile outside the trace
    with trace(str(tmp_path)):
        f(x).block_until_ready()
    found = []
    for root, _dirs, files in os.walk(tmp_path):
        found.extend(files)
    assert found  # the profiler wrote trace artifacts


def test_step_timer_reports_tokens_per_s():
    timer = StepTimer(tokens_per_step=1024)
    x = jnp.ones((32, 32))
    for _ in range(5):
        with timer.step():
            (x @ x).block_until_ready()
    s = timer.summary()
    assert s["count"] == 5
    assert s["p50_ms"] > 0 and s["p99_ms"] >= s["p50_ms"]
    assert s["tokens_per_s"] > 0


def test_step_timer_empty_summary():
    assert StepTimer().summary() == {}
