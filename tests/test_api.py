"""Tests for kubetpu.api — the KubeDevice-API re-creation (SURVEY.md §1)."""

from kubetpu.api import resource, types, utils
from kubetpu.api.types import DeviceGroupPrefix, add_group_resource, new_node_info


def test_device_group_prefix_value():
    # Pinned by the reference's expected literal keys (gpu_test.go:79-81).
    assert DeviceGroupPrefix == "resource/group"


def test_add_group_resource():
    rl = {}
    add_group_resource(rl, "tpu/0/cards", 1)
    add_group_resource(rl, "tpugrp1/0/tpugrp0/1/tpu/3/cards", 1)
    assert rl == {
        "resource/group/tpu/0/cards": 1,
        "resource/group/tpugrp1/0/tpugrp0/1/tpu/3/cards": 1,
    }


def test_node_info_copy_is_deep_enough():
    n = new_node_info("n0")
    n.capacity["kubedevice/tpu"] = 8
    c = n.copy()
    c.capacity["kubedevice/tpu"] = 4
    assert n.capacity["kubedevice/tpu"] == 8


def test_container_pod_copy():
    cont = types.ContainerInfo(requests={"kubedevice/tpu": 4})
    pod = types.PodInfo(name="p", running_containers={"c": cont})
    p2 = pod.copy()
    p2.running_containers["c"].requests["kubedevice/tpu"] = 1
    assert pod.running_containers["c"].requests["kubedevice/tpu"] == 4


def test_sorted_string_keys():
    assert utils.sorted_string_keys({"b": 1, "a": 2, "c": 3}) == ["a", "b", "c"]


def test_logb_levels():
    old = utils.get_log_level()
    try:
        utils.set_log_level(3)
        assert utils.logb(3) and utils.logb(0)
        assert not utils.logb(4)
    finally:
        utils.set_log_level(old)


def test_translate_resource_wraps_flat_keys():
    # Node advertises 2 tpugrp0 groups of 2 chips each.
    node = {
        "resource/group/tpugrp0/0/tpu/A/cards": 1,
        "resource/group/tpugrp0/0/tpu/B/cards": 1,
        "resource/group/tpugrp0/1/tpu/C/cards": 1,
        "resource/group/tpugrp0/1/tpu/D/cards": 1,
    }
    req = {
        "resource/group/tpu/0/cards": 1,
        "resource/group/tpu/1/cards": 1,
        "resource/group/tpu/2/cards": 1,
    }
    modified, out = resource.translate_resource(node, req, "tpugrp0", "tpu")
    assert modified
    # 3 chips packed into groups of 2 -> group 0 gets chips 0,1; group 1 gets 2.
    assert out == {
        "resource/group/tpugrp0/0/tpu/0/cards": 1,
        "resource/group/tpugrp0/0/tpu/1/cards": 1,
        "resource/group/tpugrp0/1/tpu/2/cards": 1,
    }


def test_translate_resource_noop_when_node_flat():
    node = {"resource/group/tpu/A/cards": 1}
    req = {"resource/group/tpu/0/cards": 1}
    modified, out = resource.translate_resource(node, req, "tpugrp0", "tpu")
    assert not modified and out is req


def test_translate_resource_noop_when_already_grouped():
    node = {"resource/group/tpugrp0/0/tpu/A/cards": 1}
    req = {"resource/group/tpugrp0/0/tpu/0/cards": 1}
    modified, out = resource.translate_resource(node, req, "tpugrp0", "tpu")
    assert not modified and out is req


def test_translate_resource_second_level():
    # Stage-3 analog: wrap tpugrp0 groups into tpugrp1.
    node = {
        "resource/group/tpugrp1/0/tpugrp0/0/tpu/A/cards": 1,
        "resource/group/tpugrp1/0/tpugrp0/1/tpu/B/cards": 1,
        "resource/group/tpugrp1/1/tpugrp0/2/tpu/C/cards": 1,
        "resource/group/tpugrp1/1/tpugrp0/3/tpu/D/cards": 1,
    }
    req = {
        "resource/group/tpugrp0/0/tpu/0/cards": 1,
        "resource/group/tpugrp0/1/tpu/1/cards": 1,
    }
    modified, out = resource.translate_resource(node, req, "tpugrp1", "tpugrp0")
    assert modified
    assert out == {
        "resource/group/tpugrp1/0/tpugrp0/0/tpu/0/cards": 1,
        "resource/group/tpugrp1/0/tpugrp0/1/tpu/1/cards": 1,
    }


def test_plugin_loading_roundtrip(tmp_path):
    # The Python analog of plugin.Open + CreateDevicePlugin symbol lookup
    # (reference cmd/main.go:23): load a module by path, call its factory.
    plug = tmp_path / "myplugin.py"
    plug.write_text(
        "from kubetpu.api.device import Device\n"
        "class Fake(Device):\n"
        "    def new(self): pass\n"
        "    def start(self): pass\n"
        "    def update_node_info(self, node_info): pass\n"
        "    def allocate(self, pod, container): return ([], [], {})\n"
        "    def get_name(self): return 'fakedev'\n"
        "def create_device_plugin():\n"
        "    return Fake()\n"
    )
    from kubetpu.api.device import create_device_from_plugin

    dev = create_device_from_plugin(str(plug))
    assert dev.get_name() == "fakedev"


def test_scheduler_plugin_loading_roundtrip():
    # component #7 end-to-end: the core loads the scheduler by its factory
    # contract (analog of plugin.Open on gpuschedulerplugin.so) and
    # schedules through it.
    from kubetpu.api.devicescheduler import create_device_scheduler_from_plugin
    from kubetpu.api.types import ContainerInfo, PodInfo
    from kubetpu.core import Cluster
    from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager

    tpu_sched = create_device_scheduler_from_plugin("kubetpu.scheduler.plugin")
    assert tpu_sched.get_name() == "tpu"
    assert tpu_sched.using_group_scheduler()

    cluster = Cluster(schedulers=[tpu_sched])
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    placed = cluster.schedule(
        PodInfo(name="p", running_containers={"m": ContainerInfo(requests={"kubedevice/tpu": 2})})
    )
    assert len(placed.running_containers["m"].allocate_from) == 2
