"""The multi-process gang: controller schedules over the wire, the
launcher spawns REAL OS processes with each pod's allocation env, the
workers form ONE jax.distributed process group and train — the
cross-process gradient all-reduce is the end-to-end proof of the env
contract real multi-host TPU jobs consume (VERDICT r2 #2; reference
process topology: nvidiagpuplugin/cmd/main.go:23, SURVEY.md §3)."""

import json
import math
import subprocess
import sys

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.wire import NodeAgentServer
from kubetpu.wire.controller import ControllerServer, pod_to_json

from test_controller import _post


def tpu_pod(name, chips):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})},
    )


@pytest.mark.slow
def test_two_process_gang_trains_with_cross_process_psum():
    """Gang scheduled over the wire -> two spawned worker processes form
    one jax.distributed group (CPU backend, gloo collectives) -> one DP
    train step -> finite, identical loss on both workers."""
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=h)),
            f"h{h}",
        )
        for h in (0, 2)
    ]
    for a in agents:
        a.start()
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    try:
        for a in agents:
            _post(controller.address + "/nodes", {"url": a.address})
        out = _post(
            controller.address + "/pods",
            {"gang": [pod_to_json(tpu_pod(f"w{i}", 8)) for i in range(2)]},
        )
        assert len(out["placements"]) == 2

        from kubetpu.cli.gang_launch import launch_gang

        result = launch_gang(
            controller.address, ["w0", "w1"], platform="cpu", timeout=240,
        )
        assert [w["process_index"] for w in result["workers"]] == [0, 1]
        assert all(w["process_count"] == 2 for w in result["workers"])
        # 8 allocated chips per pod -> 8 CPU stand-in devices per worker
        assert all(w["global_devices"] == 16 for w in result["workers"])
        assert math.isfinite(result["loss"])
        losses = {w["loss"] for w in result["workers"]}
        assert len(losses) == 1  # the cross-process psum agrees everywhere
    finally:
        controller.shutdown()
        for a in agents:
            a.shutdown()


@pytest.mark.slow
def test_gang_launch_cli_end_to_end():
    """The launcher CLI as a process: same flow, driven by argv."""
    agent = NodeAgentServer(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")), "solo"
    )
    agent.start()
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    try:
        _post(controller.address + "/nodes", {"url": agent.address})
        _post(
            controller.address + "/pods",
            {"gang": [pod_to_json(tpu_pod(f"g{i}", 4)) for i in range(2)]},
        )
        proc = subprocess.run(
            [
                sys.executable, "-m", "kubetpu.cli.gang_launch",
                "--controller", controller.address,
                "--platform", "cpu", "--timeout", "240",
                "g0", "g1",
            ],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        out = json.loads(proc.stdout.splitlines()[-1])
        assert len(out["workers"]) == 2
        assert math.isfinite(out["loss"])
    finally:
        controller.shutdown()
        agent.shutdown()
