"""NVIDIA manager tests — port of the reference's device-manager test
scenarios (nvidia_gpu_manager_test.go:100-150) with programmatically-built
fixtures: an 8-GPU two-socket box with a realistic P2P matrix (pairs on a
single switch, socket-mates over hostbridge) and a 4-GPU cloud box with no
topology. Expected grouping: grp0 = i/2, grp1 = i/4 for the 8-GPU box;
degenerate per-GPU groups for the topology-less box (SURVEY.md §4 item 3)."""

from kubetpu.api.types import ContainerInfo, NodeInfo, PodInfo
from kubetpu.device.nvidia import new_fake_nvidia_gpu_manager
from kubetpu.device.nvidia.types import (
    GpuInfo,
    GpusInfo,
    MemoryInfo,
    PciInfo,
    TopologyInfo,
    VersionInfo,
)
from kubetpu.plugintypes import ResourceGPU


def titan_box():
    """8 GPUs, 2 sockets of 4; within a socket: pairs at link 5 (single
    switch), others at link 3 (hostbridge). No cross-socket links listed."""
    bus = [f"0000:{i:02X}:00.0" for i in range(8)]
    gpus = []
    for i in range(8):
        socket = i // 4
        topo = []
        for j in range(socket * 4, socket * 4 + 4):
            if j == i:
                continue
            link = 5 if j // 2 == i // 2 else 3
            topo.append(TopologyInfo(bus_id=bus[j], link=link))
        gpus.append(
            GpuInfo(
                id=f"GPU{i:02d}",
                model="Fake TITAN X",
                path=f"/dev/nvidia{i}",
                memory=MemoryInfo(global_mib=12238),
                pci=PciInfo(bus_id=bus[i], bandwidth=15760),
                topology=topo,
            )
        )
    return GpusInfo(version=VersionInfo(driver="375.20", cuda="8.0"), gpus=gpus)


def k80_box():
    """4 GPUs, no P2P topology (cloud box)."""
    gpus = [
        GpuInfo(
            id=f"K80-{i}",
            model="Fake K80",
            path=f"/dev/nvidia{i}",
            memory=MemoryInfo(global_mib=11439),
            pci=PciInfo(bus_id=f"{0x7000 + i:04X}:00:00.0", bandwidth=15760),
            topology=[],
        )
        for i in range(4)
    ]
    return GpusInfo(version=VersionInfo(driver="384.111", cuda="9.0"), gpus=gpus)


def test_titan_box_two_level_grouping():
    info = titan_box()
    mgr = new_fake_nvidia_gpu_manager(info, "vol", "drv")
    node = NodeInfo(name="gpu-node")
    mgr.update_node_info(node)

    expected = {ResourceGPU: 8}
    for i in range(8):
        prefix = f"resource/group/gpugrp1/{i // 4}/gpugrp0/{i // 2}/gpu/GPU{i:02d}"
        expected[prefix + "/cards"] = 1
        expected[prefix + "/memory"] = 12238 * 1024 * 1024
    assert node.capacity == expected
    assert node.allocatable == expected


def test_k80_box_degenerate_grouping():
    info = k80_box()
    mgr = new_fake_nvidia_gpu_manager(info, "vol", "drv")
    node = NodeInfo(name="k80-node")
    mgr.update_node_info(node)

    expected = {ResourceGPU: 4}
    for i in range(4):
        prefix = f"resource/group/gpugrp1/{i}/gpugrp0/{i}/gpu/K80-{i}"
        expected[prefix + "/cards"] = 1
        expected[prefix + "/memory"] = 11439 * 1024 * 1024
    assert node.capacity == expected


def test_allocate_env_path():
    info = titan_box()
    mgr = new_fake_nvidia_gpu_manager(info, "vol", "drv")
    mgr.start()
    cont = ContainerInfo()
    for frm, to in [(0, 2), (1, 5)]:
        cont.allocate_from[f"resource/group/gpu/{frm}/cards"] = (
            f"resource/group/gpugrp1/{to // 4}/gpugrp0/{to // 2}/gpu/GPU{to:02d}/cards"
        )
    _, _, env = mgr.allocate(PodInfo(name="p"), cont)
    assert sorted(env["NVIDIA_VISIBLE_DEVICES"].split(",")) == ["GPU02", "GPU05"]


def test_allocate_old_devices_and_control_nodes():
    # Port of the reference TestAlloc's AllocateOld leg (alloc = {4:2, 3:0, 5:1}).
    info = k80_box()
    mgr = new_fake_nvidia_gpu_manager(info, "vol", "drv")
    mgr.start()
    cont = ContainerInfo()
    alloc = {4: 2, 3: 0, 5: 1}
    for frm, to in alloc.items():
        cont.allocate_from[f"resource/group/gpu/{frm}/cards"] = (
            f"resource/group/gpugrp1/{to}/gpugrp0/{to}/gpu/K80-{to}/cards"
        )
    _, devices, _ = mgr.allocate_old(PodInfo(name="TestPod"), cont)
    expected = ["/dev/nvidiactl", "/dev/nvidia-uvm", "/dev/nvidia-uvm-tools"] + [
        info.gpus[to].path for to in alloc.values()
    ]
    assert sorted(devices) == sorted(expected)


def test_json_roundtrip_preserves_wire_format():
    from kubetpu.device.nvidia.types import dump_gpus_info, parse_gpus_info

    info = titan_box()
    again = parse_gpus_info(dump_gpus_info(info))
    assert [g.id for g in again.gpus] == [g.id for g in info.gpus]
    assert again.gpus[0].topology[0].link == 5
    assert again.version.driver == "375.20"
