"""Multi-LoRA serving: per-example adapters in one batch, exact parity
with single-adapter merged decoding, stack validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.decode import forward_chunk, init_kv_cache
from kubetpu.jobs.lora import LoraConfig, init_lora_params, merge_lora
from kubetpu.jobs.multi_lora import MultiLoraDecodeServer, stack_adapters
from kubetpu.jobs.serving import DecodeServer

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                  max_seq=128)
LCFG = LoraConfig(rank=4, alpha=8.0)


def _adapter(seed):
    """A LoRA tree with a REAL effect: B factors randomized (init_lora's
    B = 0 would make every adapter the base model)."""
    lora = init_lora_params(jax.random.PRNGKey(seed), CFG, LCFG)
    keys = jax.random.split(jax.random.PRNGKey(seed + 100), 4)
    for i, t in enumerate(LCFG.targets):
        b = lora["blocks"][f"{t}_b"]
        lora["blocks"][f"{t}_b"] = (
            jax.random.normal(keys[i], b.shape, b.dtype) * 0.05
        )
    return lora


def test_stack_validation():
    with pytest.raises(ValueError):
        stack_adapters(LCFG, [])
    # validation inspects the adapters' ACTUAL keys: an adapter trained
    # with an MLP target is refused even under an attention-only lcfg
    mixed_cfg = LoraConfig(rank=2, targets=("wq", "w_gate"))
    mixed = init_lora_params(jax.random.PRNGKey(0), CFG, mixed_cfg)
    with pytest.raises(ValueError):
        stack_adapters(LCFG, [mixed])
    odd = _adapter(1)
    del odd["blocks"]["wq_a"], odd["blocks"]["wq_b"]
    with pytest.raises(ValueError):
        stack_adapters(LCFG, [_adapter(0), odd])


def test_chunk_forward_matches_merged_per_example():
    """The core exactness claim: a mixed batch where example i uses
    adapter a_i produces the SAME logits and cache as running each example
    through the merged model W + sA@B."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(1), _adapter(2)]
    stack = stack_adapters(LCFG, adapters)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 6), 0, CFG.vocab)
    aids = jnp.array([0, 1, 1, 0], jnp.int32)

    kc, vc = init_kv_cache(CFG, 4, 16)
    logits, kc, vc = forward_chunk(CFG, base, tokens, kc, vc, 0,
                                   lora=stack, adapter_ids=aids,
                                   lora_scale=LCFG.scale)
    for i in range(4):
        merged = merge_lora(base, adapters[int(aids[i])], LCFG)
        kc1, vc1 = init_kv_cache(CFG, 1, 16)
        want, kc1, vc1 = forward_chunk(CFG, merged, tokens[i:i + 1],
                                       kc1, vc1, 0)
        np.testing.assert_allclose(np.asarray(logits[i]),
                                   np.asarray(want[0]),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(kc[:, i]), np.asarray(kc1[:, 0]),
                                   rtol=2e-4, atol=2e-4)


def test_server_greedy_parity_with_merged_single_tenant():
    """Three concurrent requests on two adapters: each stream's greedy
    output must equal a single-tenant DecodeServer on the merged model."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(1), _adapter(2)]
    stack = stack_adapters(LCFG, adapters)
    srv = MultiLoraDecodeServer(CFG, base, LCFG, stack, n_slots=3,
                                max_seq=64, max_new_tokens=12, eos_id=None)
    srv.warmup()
    prompts = [[5, 6, 7], [9, 10], [5, 6, 7]]
    picks = [0, 1, 1]
    rids = [srv.submit(p, adapter=a) for p, a in zip(prompts, picks)]
    assert None not in rids
    srv.drain()
    for rid, prompt, a in zip(rids, prompts, picks):
        got = srv.result(rid)
        ref = DecodeServer(CFG, merge_lora(base, adapters[a], LCFG),
                           n_slots=1, max_seq=64, max_new_tokens=12,
                           eos_id=None)
        rref = ref.submit(prompt)
        ref.drain()
        assert got == ref.result(rref), (got, ref.result(rref))


def test_adapter_rides_queue_and_slot_reuse():
    """enqueue carries the adapter id through the queue; a slot reused by
    a different adapter switches cleanly (no stale id)."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(1), _adapter(2)]
    stack = stack_adapters(LCFG, adapters)
    srv = MultiLoraDecodeServer(CFG, base, LCFG, stack, n_slots=1,
                                max_seq=64, max_new_tokens=6, eos_id=None)
    r0 = srv.enqueue([5, 6, 7], adapter=0)
    r1 = srv.enqueue([5, 6, 7], adapter=1)  # same prompt, other adapter
    srv.drain()
    out0, out1 = srv.result(r0), srv.result(r1)
    ref = {}
    for a in (0, 1):
        s = DecodeServer(CFG, merge_lora(base, adapters[a], LCFG), n_slots=1,
                         max_seq=64, max_new_tokens=6, eos_id=None)
        r = s.submit([5, 6, 7])
        s.drain()
        ref[a] = s.result(r)
    assert out0 == ref[0] and out1 == ref[1]
    assert out0 != out1  # the adapters actually steer the output


def test_adapter_out_of_range_rejected():
    base = init_params(jax.random.PRNGKey(0), CFG)
    stack = stack_adapters(LCFG, [_adapter(1)])
    srv = MultiLoraDecodeServer(CFG, base, LCFG, stack, n_slots=1,
                                max_seq=64, max_new_tokens=4, eos_id=None)
    with pytest.raises(ValueError):
        srv.submit([1, 2], adapter=1)
    with pytest.raises(ValueError):
        srv.enqueue([1, 2], adapter=-1)
    # the rejected enqueue left NO zombie bookkeeping (a queued ghost
    # would later run under adapter 0)
    assert srv.queued() == 0 and not srv._prompts

    # an early pop_result of an unfinished request must not corrupt the
    # queued request's adapter choice
    rid = srv.enqueue([1, 2], adapter=0)
    srv._rid_adapter[rid] = 0
    with pytest.raises(KeyError):
        srv.pop_result(rid)
    assert srv._rid_adapter[rid] == 0
