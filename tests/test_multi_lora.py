"""Multi-LoRA serving: per-example adapters in one batch, exact parity
with single-adapter merged decoding, stack validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.decode import forward_chunk, init_kv_cache
from kubetpu.jobs.lora import LoraConfig, init_lora_params, merge_lora
from kubetpu.jobs.multi_lora import MultiLoraDecodeServer, stack_adapters
from kubetpu.jobs.serving import DecodeServer

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                  max_seq=128)
LCFG = LoraConfig(rank=4, alpha=8.0)


def _adapter(seed):
    """A LoRA tree with a REAL effect: B factors randomized (init_lora's
    B = 0 would make every adapter the base model)."""
    lora = init_lora_params(jax.random.PRNGKey(seed), CFG, LCFG)
    keys = jax.random.split(jax.random.PRNGKey(seed + 100), 4)
    for i, t in enumerate(LCFG.targets):
        b = lora["blocks"][f"{t}_b"]
        lora["blocks"][f"{t}_b"] = (
            jax.random.normal(keys[i], b.shape, b.dtype) * 0.05
        )
    return lora


def test_stack_validation():
    with pytest.raises(ValueError):
        stack_adapters(LCFG, [])
    # validation inspects the adapters' ACTUAL keys: an adapter trained
    # with an MLP target is refused even under an attention-only lcfg
    mixed_cfg = LoraConfig(rank=2, targets=("wq", "w_gate"))
    mixed = init_lora_params(jax.random.PRNGKey(0), CFG, mixed_cfg)
    with pytest.raises(ValueError):
        stack_adapters(LCFG, [mixed])
    odd = _adapter(1)
    del odd["blocks"]["wq_a"], odd["blocks"]["wq_b"]
    with pytest.raises(ValueError):
        stack_adapters(LCFG, [_adapter(0), odd])


def test_chunk_forward_matches_merged_per_example():
    """The core exactness claim: a mixed batch where example i uses
    adapter a_i produces the SAME logits and cache as running each example
    through the merged model W + sA@B."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(1), _adapter(2)]
    stack = stack_adapters(LCFG, adapters)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 6), 0, CFG.vocab)
    aids = jnp.array([0, 1, 1, 0], jnp.int32)

    kc, vc = init_kv_cache(CFG, 4, 16)
    logits, kc, vc = forward_chunk(CFG, base, tokens, kc, vc, 0,
                                   lora=stack, adapter_ids=aids,
                                   lora_scale=LCFG.scale)
    for i in range(4):
        merged = merge_lora(base, adapters[int(aids[i])], LCFG)
        kc1, vc1 = init_kv_cache(CFG, 1, 16)
        want, kc1, vc1 = forward_chunk(CFG, merged, tokens[i:i + 1],
                                       kc1, vc1, 0)
        np.testing.assert_allclose(np.asarray(logits[i]),
                                   np.asarray(want[0]),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(kc[:, i]), np.asarray(kc1[:, 0]),
                                   rtol=2e-4, atol=2e-4)


def test_server_greedy_parity_with_merged_single_tenant():
    """Three concurrent requests on two adapters: each stream's greedy
    output must equal a single-tenant DecodeServer on the merged model."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(1), _adapter(2)]
    stack = stack_adapters(LCFG, adapters)
    srv = MultiLoraDecodeServer(CFG, base, LCFG, stack, n_slots=3,
                                max_seq=64, max_new_tokens=12, eos_id=None)
    srv.warmup()
    prompts = [[5, 6, 7], [9, 10], [5, 6, 7]]
    picks = [0, 1, 1]
    rids = [srv.submit(p, adapter=a) for p, a in zip(prompts, picks)]
    assert None not in rids
    srv.drain()
    for rid, prompt, a in zip(rids, prompts, picks):
        got = srv.result(rid)
        ref = DecodeServer(CFG, merge_lora(base, adapters[a], LCFG),
                           n_slots=1, max_seq=64, max_new_tokens=12,
                           eos_id=None)
        rref = ref.submit(prompt)
        ref.drain()
        assert got == ref.result(rref), (got, ref.result(rref))


def test_adapter_rides_queue_and_slot_reuse():
    """enqueue carries the adapter id through the queue; a slot reused by
    a different adapter switches cleanly (no stale id)."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(1), _adapter(2)]
    stack = stack_adapters(LCFG, adapters)
    srv = MultiLoraDecodeServer(CFG, base, LCFG, stack, n_slots=1,
                                max_seq=64, max_new_tokens=6, eos_id=None)
    r0 = srv.enqueue([5, 6, 7], adapter=0)
    r1 = srv.enqueue([5, 6, 7], adapter=1)  # same prompt, other adapter
    srv.drain()
    out0, out1 = srv.result(r0), srv.result(r1)
    ref = {}
    for a in (0, 1):
        s = DecodeServer(CFG, merge_lora(base, adapters[a], LCFG), n_slots=1,
                         max_seq=64, max_new_tokens=6, eos_id=None)
        r = s.submit([5, 6, 7])
        s.drain()
        ref[a] = s.result(r)
    assert out0 == ref[0] and out1 == ref[1]
    assert out0 != out1  # the adapters actually steer the output


def test_adapter_out_of_range_rejected():
    base = init_params(jax.random.PRNGKey(0), CFG)
    stack = stack_adapters(LCFG, [_adapter(1)])
    srv = MultiLoraDecodeServer(CFG, base, LCFG, stack, n_slots=1,
                                max_seq=64, max_new_tokens=4, eos_id=None)
    with pytest.raises(ValueError):
        srv.submit([1, 2], adapter=1)
    with pytest.raises(ValueError):
        srv.enqueue([1, 2], adapter=-1)
    # the rejected enqueue left NO zombie bookkeeping (a queued ghost
    # would later run under adapter 0)
    assert srv.queued() == 0 and not srv._prompts

    # an early pop_result of an unfinished request must not corrupt the
    # queued request's adapter choice
    rid = srv.enqueue([1, 2], adapter=0)
    srv._rid_adapter[rid] = 0
    with pytest.raises(KeyError):
        srv.pop_result(rid)
    assert srv._rid_adapter[rid] == 0


# ---------------------------------------------------------------------------
# Round-22: the packed paged replica (PagedMultiLoraDecodeServer)
# ---------------------------------------------------------------------------

from kubetpu.jobs.multi_lora import (  # noqa: E402
    PagedMultiLoraDecodeServer, SpecMultiLoraDecodeServer,
    adapter_fingerprint)
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402

PS = 8


def _paged_multi(base, adapters, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("max_seq", 64)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("page_size", PS)
    kw.setdefault("eos_id", None)
    return PagedMultiLoraDecodeServer(CFG, base, LCFG, adapters, **kw)


def _merged_ref(base, adapter, prompt, **kw):
    kw.setdefault("n_slots", 1)
    kw.setdefault("max_seq", 64)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("page_size", PS)
    kw.setdefault("eos_id", None)
    srv = PagedDecodeServer(CFG, merge_lora(base, adapter, LCFG), **kw)
    rid = srv.enqueue(prompt)
    srv.drain()
    return srv.pop_result(rid)


@pytest.mark.parametrize("kv_int8", [False, True])
@pytest.mark.parametrize("chunked", [False, True])
def test_paged_parity_matrix(chunked, kv_int8):
    """The tentpole exactness claim, across the leg matrix: a packed
    mixed-tenant batch through {monolithic, chunked} x {f32, kv_int8}
    paged decode equals single-tenant merged decode per stream."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(1), _adapter(2), _adapter(3)]
    kw = dict(kv_int8=kv_int8, prefill_budget=PS if chunked else 0)
    srv = _paged_multi(base, adapters, **kw)
    prompts = [[5, 6, 7, 9, 11], list(range(1, 2 * PS + 2)), [9, 10]]
    picks = [0, 2, 1]
    rids = [srv.submit(p, adapter=a) for p, a in zip(prompts, picks)]
    assert None not in rids
    srv.drain()
    srv.check_invariants()
    for rid, prompt, a in zip(rids, prompts, picks):
        got = srv.pop_result(rid)
        want = _merged_ref(base, adapters[a], prompt, **kw)
        assert got == want, (a, got, want)


def test_paged_prefix_hit_parity_and_cross_tenant_isolation():
    """Adapter-salted prefix keys: a same-tenant replay HITS the warm
    tree (and still matches merged decode); the SAME prompt under a
    different adapter must MISS — adapter A's KV pages encode A's wk/wv
    deltas and may never warm-start adapter B."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(1), _adapter(2)]
    srv = _paged_multi(base, adapters, prefix_cache_pages=16)
    prompt = list(range(1, 2 * PS + 2))  # two full pages + a tail

    r0 = srv.submit(prompt, adapter=0)
    srv.drain()
    assert srv.prefix_cache_stats()["requests_hit"] == 0
    r1 = srv.submit(prompt, adapter=0)
    srv.drain()
    assert srv.prefix_cache_stats()["requests_hit"] == 1  # warm replay
    r2 = srv.submit(prompt, adapter=1)
    srv.drain()
    # the cross-tenant request found pages under A's salt — and ignored
    # them: the hit counter must NOT move
    assert srv.prefix_cache_stats()["requests_hit"] == 1
    srv.check_invariants()

    want = {a: _merged_ref(base, adapters[a], prompt,
                           prefix_cache_pages=16) for a in (0, 1)}
    assert srv.pop_result(r0) == want[0]
    assert srv.pop_result(r1) == want[0]  # the hit changed no token
    assert srv.pop_result(r2) == want[1]


def test_spec_multilora_greedy_parity():
    """Speculative rounds over the packed pool: draft is adapterless,
    verify applies each slot's adapter — output must equal plain merged
    greedy decode per tenant (speculation may only change latency)."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    draft = init_params(jax.random.PRNGKey(7), CFG)
    adapters = [_adapter(1), _adapter(2)]
    srv = SpecMultiLoraDecodeServer(
        CFG, CFG, base, draft, LCFG, adapters, n_slots=2, max_seq=64,
        max_new_tokens=6, page_size=PS, eos_id=None, gamma_max=2)
    prompts = [[5, 6, 7, 9], [9, 10, 4]]
    rids = [srv.submit(p, adapter=a) for p, a in zip(prompts, (0, 1))]
    assert None not in rids
    srv.drain()
    srv.check_invariants()
    for rid, prompt, a in zip(rids, prompts, (0, 1)):
        assert srv.pop_result(rid) == _merged_ref(base, adapters[a], prompt)


def test_64_adapters_one_packed_server():
    """The acceptance bar: ONE packed replica serving 64 resident
    adapters through the paged path, spot-checked token-exact against
    merged single-tenant decode at both ends and the middle."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(s) for s in range(1, 65)]
    srv = _paged_multi(base, adapters, n_slots=2, max_new_tokens=4)
    assert srv.n_adapters == 64
    assert len(srv.resident_adapters()) == 64
    prompt = [5, 6, 7, 9]
    for t in (0, 17, 40, 63):
        rid = srv.submit(prompt, adapter=t)
        srv.drain()
        got = srv.pop_result(rid)
        want = _merged_ref(base, adapters[t], prompt, max_new_tokens=4)
        assert got == want, (t, got, want)
    srv.check_invariants()


def test_hot_load_evict_directory():
    """The residency life cycle: content-idempotent load, shape
    validation, LRU eviction when the stack is full, in-use eviction
    refusal, and stale names refusing at enqueue."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    a0, a1, a2, a3 = (_adapter(s) for s in (1, 2, 3, 4))
    srv = _paged_multi(base, [a0, a1], max_adapters=3, n_slots=1)
    n0 = adapter_fingerprint(a0)

    # idempotency is by NAME (the tenant identity — wire pushes name by
    # fingerprint, so replays dedupe): re-loading a resident name is a
    # no-op; an explicit alias is a distinct tenant and takes an index
    assert srv.load_adapter(a0) == n0
    assert len(srv.resident_adapters()) == 2
    assert srv.load_adapter(a0, name="alias") == "alias"
    assert len(srv.resident_adapters()) == 3
    assert srv.evict_adapter("alias") is True

    # malformed trees refuse before touching the stack
    bad = {"blocks": {k: v for k, v in a2["blocks"].items()
                      if not k.endswith("wq_b")}}
    with pytest.raises(ValueError):
        srv.load_adapter(bad)

    n2 = srv.load_adapter(a2, name="t2")      # fills the free index
    assert n2 == "t2"
    assert len(srv.resident_adapters()) == 3

    # stack full + everything idle: the 4th load LRU-evicts
    n3 = srv.load_adapter(a3, name="t3")
    assert n3 == "t3"
    res = srv.resident_adapters()
    assert len(res) == 3 and "t3" in res
    evicted = ({n0, adapter_fingerprint(a1), "t2"} - set(res)).pop()
    srv.check_invariants()

    # the evicted name refuses at enqueue — never a stale index
    with pytest.raises(ValueError):
        srv.enqueue([1, 2, 3], adapter=evicted)

    # a live stream pins its adapter against explicit eviction
    rid = srv.enqueue([5, 6, 7], adapter="t3")
    srv.step()  # admit it
    with pytest.raises(RuntimeError):
        srv.evict_adapter("t3")
    srv.drain()
    srv.pop_result(rid)
    assert srv.evict_adapter("t3") is True    # idle now: clean evict
    assert srv.evict_adapter("t3") is False   # replayed evict: no-op
    srv.check_invariants()

    # loaded-by-name parity: the hot-loaded tenant decodes exactly
    rid = srv.enqueue([5, 6, 7], adapter="t2")
    srv.drain()
    assert srv.pop_result(rid) == _merged_ref(base, a2, [5, 6, 7],
                                              n_slots=1)


def test_recycled_index_never_serves_stale_prefix():
    """Eviction bumps the index's prefix-salt generation: a tenant
    hot-loaded into a RECYCLED stack index must not warm-start from the
    evicted occupant's cached pages (same prompt, same index — without
    the generation term the salted keys collide and the new tenant
    decodes from the old tenant's KV)."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    a0, a1, a2 = (_adapter(s) for s in (1, 2, 3))
    srv = _paged_multi(base, [a0, a1], max_adapters=2, n_slots=1,
                       prefix_cache_pages=16)
    prompt = list(range(5, 14))
    rid = srv.enqueue(prompt, adapter=0)      # a0 publishes the prefix
    srv.drain()
    srv.pop_result(rid)
    hits0 = srv.prefix_cache_stats()["requests_hit"]
    srv.load_adapter(a2, name="t2")           # LRU-evicts an idle index
    recycled = ({adapter_fingerprint(a0), adapter_fingerprint(a1)}
                - set(srv.resident_adapters())).pop()
    rid = srv.enqueue(prompt, adapter="t2")
    srv.drain()
    out = srv.pop_result(rid)
    assert srv.prefix_cache_stats()["requests_hit"] == hits0, (
        f"t2 warm-started from {recycled}'s cached pages")
    assert out == _merged_ref(base, a2, prompt, n_slots=1)
    srv.check_invariants()


def test_adapter_hbm_budget_caps_capacity():
    """``adapter_hbm_bytes`` is the real bound: capacity (a compiled
    SHAPE) is min(max_adapters, budget // per-adapter bytes), and a
    budget that can't hold the initial set refuses at construction."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    a0, a1 = _adapter(1), _adapter(2)
    probe = _paged_multi(base, [a0], n_slots=1)
    per = probe._adapter_bytes_each
    assert per > 0

    srv = _paged_multi(base, [a0], max_adapters=8, n_slots=1,
                       adapter_hbm_bytes=2 * per)
    assert srv.n_adapters == 2              # budget bound max_adapters
    srv.load_adapter(a1, name="t1")
    res = set(srv.resident_adapters())
    srv.load_adapter(_adapter(3), name="t2")    # full: LRU evicts
    assert len(srv.resident_adapters()) == 2
    srv.check_invariants()

    with pytest.raises(ValueError):
        _paged_multi(base, [a0, a1], n_slots=1, adapter_hbm_bytes=per)
    del res


def test_rid_adapter_map_never_leaks():
    """The Round-22 leak fix, pinned at every request exit: pop_result,
    cancel (queued AND admitted), and queue-TTL expiry all reclaim the
    rid->adapter entry through ``_drop_request_state``."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    srv = _paged_multi(base, [_adapter(1), _adapter(2)], n_slots=1,
                       max_new_tokens=3)

    rid = srv.submit([5, 6, 7], adapter=1)     # normal completion
    srv.drain()
    srv.pop_result(rid)
    assert srv._rid_adapter == {}

    r0 = srv.enqueue([5, 6, 7], adapter=0)     # admitted then canceled
    r1 = srv.enqueue([9, 10], adapter=1)       # canceled while queued
    srv.step()
    assert srv.cancel(r0) and srv.cancel(r1)
    srv.drain()
    assert srv._rid_adapter == {}

    r2 = srv.enqueue([5, 6], adapter=1, ttl=0.0)   # expires in queue
    r3 = srv.enqueue([7, 8], adapter=0)
    import time as _t
    _t.sleep(0.01)
    srv.drain()
    assert srv.expire_reason(r2) == "queue_ttl"
    srv.pop_result(r3)
    assert srv._rid_adapter == {}, srv._rid_adapter
    srv.check_invariants()


def test_multilora_slots_refuse_migration():
    base = init_params(jax.random.PRNGKey(0), CFG)
    srv = _paged_multi(base, [_adapter(1)], n_slots=1)
    rid = srv.submit([5, 6, 7], adapter=0)
    srv.step()
    with pytest.raises(NotImplementedError):
        srv.snapshot_slot(rid)
    with pytest.raises(NotImplementedError):
        srv.restore_slot({"rid": rid})
    srv.drain()
    srv.pop_result(rid)


def test_tenant_counters_track_requests_and_tokens():
    """Per-tenant observability: requests and decode tokens land on the
    adapter's label; past the top-K the overflow bucket absorbs new
    labels (bounded cardinality)."""
    from kubetpu.jobs.multi_lora import _TENANT_OVERFLOW, _TENANT_TOPK
    base = init_params(jax.random.PRNGKey(0), CFG)
    srv = _paged_multi(base, [_adapter(1), _adapter(2)], n_slots=1,
                       max_new_tokens=3)
    names = srv.resident_adapters()
    rid = srv.submit([5, 6, 7], adapter=0)
    srv.drain()
    out = srv.pop_result(rid)
    req = srv.obs.counter("kubetpu_tenant_requests_total",
                          adapter=srv._adapter_label(0))
    tok = srv.obs.counter("kubetpu_tenant_decode_tokens_total",
                          adapter=srv._adapter_label(0))
    assert int(req.value) == 1
    # decode steps only: the first emitted token is prefill's product
    assert int(tok.value) == len(out) - 3 - 1
    assert len(names) == 2

    # cardinality bound: hammer one metric with many fake labels
    for aid in range(200):
        srv._tenant_counter("req", aid % srv.n_adapters)
    labels = srv._tenant_counters["req"]
    assert len(labels) <= _TENANT_TOPK + 1
    assert _TENANT_OVERFLOW not in labels or len(labels) == _TENANT_TOPK + 1
