"""Pallas kernel tests (interpret mode on CPU; compiled path runs on real
TPU via scripts/tpu_smoke.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs.model import dense_causal_attention
from kubetpu.ops import flash_attention


def _qkv(b=2, s=128, h=4, d=32, dtype=jnp.float32, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in keys)


def test_flash_matches_dense():
    q, k, v = _qkv()
    out = flash_attention(q, k, v, 64, 64, True)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_flash_uneven_block_ratio():
    # block_q != block_k exercises the diagonal arithmetic
    q, k, v = _qkv(s=128)
    out = flash_attention(q, k, v, 32, 64, True)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)
    out = flash_attention(q, k, v, 64, 32, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_flash_single_block():
    q, k, v = _qkv(s=32)
    out = flash_attention(q, k, v, 128, 128, True)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_flash_gradients_match_dense():
    q, k, v = _qkv(s=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, 32, 32, True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4)


def test_flash_in_model_forward():
    import functools

    from kubetpu.jobs import ModelConfig, forward, init_params

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab)
    attn = functools.partial(flash_attention, block_q=32, block_k=32, interpret=True)
    got = forward(params, tokens, cfg, attn_fn=attn)
    want = forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


def test_flash_in_train_step():
    """'flash' as the train-step attention on an sp=1 mesh (interpret mode
    can't run under jit, so this exercises the compiled-path wiring only at
    trace level via dense fallback on CPU is not possible — instead run the
    uncompiled loss)."""
    import functools

    import jax.numpy as jnp

    from kubetpu.jobs import ModelConfig, init_params, next_token_loss

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    attn = functools.partial(flash_attention, block_q=32, block_k=32, interpret=True)
    loss_flash = next_token_loss(params, tokens, targets, cfg, attn)
    loss_dense = next_token_loss(params, tokens, targets, cfg)
    np.testing.assert_allclose(float(loss_flash), float(loss_dense), rtol=1e-4)


@pytest.mark.slow
def test_noncausal_flash_matches_dense_bidirectional():
    """flash_attention(causal=False): the encoder-style full-visibility
    core must match a plain softmax over ALL positions, forward and grad."""
    import jax
    import jax.numpy as jnp

    b, s, h, d = 2, 64, 2, 8
    q, k, v = (
        jax.random.normal(key, (b, s, h, d), jnp.float32)
        for key in jax.random.split(jax.random.PRNGKey(0), 3)
    )

    def dense_full(q, k, v):
        scale = d ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    import functools

    flash = functools.partial(flash_attention, block_q=16, block_k=16,
                              interpret=True, causal=False)
    np.testing.assert_allclose(
        np.asarray(flash(q, k, v)), np.asarray(dense_full(q, k, v)),
        rtol=2e-4, atol=2e-5,
    )
    g_flash = jax.grad(lambda q, k, v: jnp.sum(flash(q, k, v) ** 2),
                       argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(lambda q, k, v: jnp.sum(dense_full(q, k, v) ** 2),
                       argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-3, atol=2e-4)


# -- sliding-window attention -------------------------------------------------


def test_dense_window_matches_band_mask():
    from kubetpu.jobs.model import dense_attention

    rng = jax.random.PRNGKey(0)
    b, s, h, d = 2, 16, 2, 8
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in jax.random.split(rng, 3))
    W = 5
    got = dense_attention(q, k, v, causal=True, window=W)
    # manual band-mask reference
    scale = d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    pos = jnp.arange(s)
    mask = (pos[:, None] >= pos[None, :]) & (pos[:, None] - pos[None, :] < W)
    scores = jnp.where(mask[None, None], scores, -1e30)
    want = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError):
        dense_attention(q, k, v, causal=False, window=W)


@pytest.mark.parametrize("window", [3, 8, 13])
def test_flash_window_matches_dense_fwd_and_grad(window):
    """The kernel's block-skip bounds (forward, dQ, dK/dV) are exercised
    across block boundaries: s=32 with block 8 and windows that are
    smaller than / equal to / straddling the block size."""
    from kubetpu.jobs.model import dense_attention
    from kubetpu.ops import flash_attention

    rng = jax.random.PRNGKey(1)
    b, s, h, d = 2, 32, 2, 8
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in jax.random.split(rng, 3))

    out_f = flash_attention(q, k, v, 8, 8, True, True, window)
    out_d = dense_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=2e-3, atol=2e-3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, 8, 8, True, True, window) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True, window=window) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=5e-3)
