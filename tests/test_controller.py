"""The control-plane daemon: operator HTTP API + reconcile loop over live
agent servers — submit/status/release over the wire, dead agents drive
automatic rescheduling, pods that fit nowhere wait in the pending queue."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.wire import NodeAgentServer
from kubetpu.wire.controller import ControllerServer, pod_to_json


def tpu_pod(name, chips):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})},
    )


def _post(url, obj, token=None):
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 headers=headers, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


@pytest.fixture
def stack():
    """Two agent servers + one controller, all live."""
    # hosts 0 and 2 are vertically adjacent in the v5e-64 host grid (4x2),
    # so a 2-host gang can tile a perfect 4x4 chip square
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h)
            ),
            f"h{h}",
        )
        for h in (0, 2)
    ]
    for a in agents:
        a.start()
    # long poll interval: tests drive reconciliation via poll_once().
    # dead_after=1 pins the legacy one-strike eviction these tests drive
    # deliberately (the default circuit breaker takes 3 misses; breaker
    # behavior itself is covered in test_resilience.py)
    controller = ControllerServer(poll_interval=3600, dead_after=1)
    controller.start()
    for a in agents:
        _post(controller.address + "/nodes", {"url": a.address})
    yield controller, agents
    controller.shutdown()
    for a in agents:
        try:
            a.shutdown()
        except Exception:  # noqa: BLE001 — may already be down
            pass


def test_submit_status_release_over_api(stack):
    controller, _agents = stack
    out = _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("j", 4))})
    assert out["placements"][0]["pod"] == "j"
    node = out["placements"][0]["node"]
    env = out["placements"][0]["containers"]["main"]["env"]
    assert env["TPU_VISIBLE_DEVICES"].count(",") == 3

    status = _get(controller.address + "/status")
    assert "j" in status["nodes"][node]["pods"]
    nodes = _get(controller.address + "/nodes")
    assert nodes[node]["url"]

    req = urllib.request.Request(
        controller.address + "/pods/j", method="DELETE"
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        assert json.loads(r.read())["released"] == "j"
    status = _get(controller.address + "/status")
    assert status["nodes"][node]["pods"] == []


def test_gang_submit_over_api(stack):
    controller, _agents = stack
    out = _post(
        controller.address + "/pods",
        {"gang": [pod_to_json(tpu_pod(f"w{i}", 8)) for i in range(2)]},
    )
    assert len(out["placements"]) == 2
    assert out["gang_contiguity"] == 1.0


def test_unschedulable_is_409(stack):
    controller, _agents = stack
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("big", 64))})
    assert e.value.code == 409


def test_malformed_vchip_stamp_is_400(stack):
    """A vChip stamp outside the milli grammar is the CLIENT's error: a
    deterministic 400 at the wire boundary (BadRequestError), never a
    retryable-looking 500 from a ValueError escaping mid-schedule —
    while a well-formed fractional pod still places."""
    from kubetpu.scheduler.meshstate import FracKey

    controller, _agents = stack
    bad = PodInfo(name="badfrac", requests={FracKey: "1500m"},
                  running_containers={"main": ContainerInfo()})
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(controller.address + "/pods", {"pod": pod_to_json(bad)})
    assert e.value.code == 400
    ok = PodInfo(name="okfrac", requests={FracKey: "250m"},
                 running_containers={"main": ContainerInfo()})
    out = _post(controller.address + "/pods", {"pod": pod_to_json(ok)})
    assert len(out["placements"]) == 1


def test_dead_agent_reconcile_reschedules(stack):
    controller, agents = stack
    out = _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("job", 4))})
    node = out["placements"][0]["node"]
    victim = next(a for a in agents if a.node_name == node)
    victim.shutdown()

    result = controller.poll_once()
    assert result["failed_nodes"] == [node]
    assert result["rescheduled"][0]["pod"] == "job"
    assert result["rescheduled"][0]["node"] != node
    assert result["pending"] == []


def test_nowhere_to_go_stays_pending_then_recovers(stack):
    controller, agents = stack
    # fill BOTH nodes, then kill one: its pod cannot re-place until space
    out0 = _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("a", 8))})
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("b", 8))})
    victim_node = out0["placements"][0]["node"]
    victim = next(a for a in agents if a.node_name == victim_node)
    victim.shutdown()

    result = controller.poll_once()
    assert result["pending"] == ["a"]
    # release "b": the next reconcile pass finds room
    req = urllib.request.Request(controller.address + "/pods/b", method="DELETE")
    urllib.request.urlopen(req, timeout=10).read()
    result = controller.poll_once()
    assert result["rescheduled"][0]["pod"] == "a"
    assert controller.pending_pods == []


def test_controller_auth():
    controller = ControllerServer(poll_interval=3600, token="t0k3n")
    controller.start()
    try:
        assert _get(controller.address + "/healthz")["ok"]  # liveness open
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(controller.address + "/status")
        assert e.value.code == 401
        req = urllib.request.Request(
            controller.address + "/status",
            headers={"Authorization": "Bearer t0k3n"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert "nodes" in json.loads(r.read())
    finally:
        controller.shutdown()


def test_duplicate_pod_name_is_409(stack):
    controller, _agents = stack
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("dup", 2))})
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("dup", 2))})
    assert e.value.code == 409
    # original pod untouched, capacity not double-counted
    status = _get(controller.address + "/status")
    held = sum(
        8 - entry["kubedevice/tpu"]["free"] for entry in status["nodes"].values()
    )
    assert held == 2


def test_allocation_fetch_for_existing_pod(stack):
    controller, _agents = stack
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("x", 2))})
    out = _get(controller.address + "/pods/x")
    assert out["containers"]["main"]["env"]["TPU_VISIBLE_DEVICES"].count(",") == 1
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(controller.address + "/pods/ghost")
    assert e.value.code == 404


def test_reconcile_rescheduled_pod_carries_launcher_env(stack):
    controller, agents = stack
    out = _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("job", 4))})
    node = out["placements"][0]["node"]
    next(a for a in agents if a.node_name == node).shutdown()
    result = controller.poll_once()
    entry = result["rescheduled"][0]
    assert entry["pod"] == "job" and entry["node"] != node
    assert entry["containers"]["main"]["env"]["TPU_VISIBLE_DEVICES"]
    # and the env stays fetchable afterwards
    again = _get(controller.address + "/pods/job")
    assert again["containers"]["main"]["devices"]


def test_submit_rolls_back_when_allocate_fails(stack, monkeypatch):
    """If the agent dies between placement and allocation, the submission
    must not leave capacity held by an unlaunchable pod — and the error
    is a RETRYABLE 503 (the state rolled back; a keyed retry may
    succeed), not a dead-end 500."""
    controller, agents = stack

    def dying_allocations(device, pod_copy):
        raise ConnectionError("agent vanished mid-submit")

    monkeypatch.setattr(controller, "_run_allocations", dying_allocations)
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("z", 4))})
    assert e.value.code == 503
    monkeypatch.undo()
    status = _get(controller.address + "/status")
    for entry in status["nodes"].values():
        assert entry["kubedevice/tpu"]["free"] == 8  # fully rolled back
        assert entry["pods"] == []


def test_reconcile_never_straddles_gang_across_slices():
    """A gang member evicted by a node death must re-place only within its
    surviving mates' slice: cross-slice chips are DCN, and an unconstrained
    reschedule would silently wreck the gang's collectives."""
    # slice0: hosts 0 and 2 (adjacent); sliceB: an unrelated slice with room
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=h)),
            f"s0-h{h}",
        )
        for h in (0, 2)
    ] + [
        NodeAgentServer(
            new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=0, slice_uid="sliceB")
            ),
            "sB-h0",
        )
    ]
    for a in agents:
        a.start()
    controller = ControllerServer(poll_interval=3600, dead_after=1)
    controller.start()
    try:
        for a in agents:
            _post(controller.address + "/nodes", {"url": a.address})
        out = _post(
            controller.address + "/pods",
            {"gang": [pod_to_json(tpu_pod(f"w{i}", 8)) for i in range(2)]},
        )
        nodes = {p["pod"]: p["node"] for p in out["placements"]}
        assert set(nodes.values()) == {"s0-h0", "s0-h2"}  # gang on slice0

        victim = next(a for a in agents if a.node_name == nodes["w0"])
        victim.shutdown()
        result = controller.poll_once()
        # sliceB has 8 free chips, but w0 must NOT land there: it stays
        # pending rather than straddle its gang over DCN
        assert result["rescheduled"] == []
        assert result["pending"] == ["w0"]

        # a replacement host joins slice0 -> w0 recovers INSIDE the slice
        replacement = NodeAgentServer(
            new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=1)),
            "s0-h1",
        )
        replacement.start()
        agents.append(replacement)
        _post(controller.address + "/nodes", {"url": replacement.address})
        result = controller.poll_once()
        assert result["rescheduled"][0]["pod"] == "w0"
        assert result["rescheduled"][0]["node"] == "s0-h1"
    finally:
        controller.shutdown()
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass


def test_whole_gang_reassembles_on_one_slice():
    """When EVERY member of a gang is evicted (whole slice died), the
    reconcile pass re-places the members ATOMICALLY via schedule_gang —
    the gang reassembles on ONE slice instead of scattering."""
    s0 = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=h)),
            f"s0-h{h}",
        )
        for h in (0, 2)
    ]
    for a in s0:
        a.start()
    controller = ControllerServer(poll_interval=3600, dead_after=1)
    controller.start()
    extra = []
    try:
        for a in s0:
            _post(controller.address + "/nodes", {"url": a.address})
        _post(
            controller.address + "/pods",
            {"gang": [pod_to_json(tpu_pod(f"w{i}", 8)) for i in range(2)]},
        )
        for a in s0:  # the whole slice dies
            a.shutdown()
        result = controller.poll_once()
        assert sorted(result["pending"]) == ["w0", "w1"]

        # two replacement slices appear. Node names INTERLEAVE the slices
        # alphabetically (a/c = sliceX, b/d = sliceY): without the gang
        # slice filter the scheduler's (-score, name) tie-break would place
        # w0 on a-h0 (X) and w1 on b-h0 (Y) — scattered. The filter must
        # force w1 to follow w0's slice instead.
        slice_of = {"a": "sliceX", "b": "sliceY", "c": "sliceX", "d": "sliceY"}
        host_of = {"a": 0, "b": 0, "c": 2, "d": 2}
        for prefix in "abcd":
            a = NodeAgentServer(
                new_fake_tpu_dev_manager(
                    make_fake_tpus_info(
                        "v5e-64", host_index=host_of[prefix],
                        slice_uid=slice_of[prefix],
                    )
                ),
                f"{prefix}-h{host_of[prefix]}",
            )
            a.start()
            extra.append(a)
            _post(controller.address + "/nodes", {"url": a.address})
        result = controller.poll_once()
        placed_nodes = {r["pod"]: r["node"] for r in result["rescheduled"]}
        assert sorted(placed_nodes) == ["w0", "w1"]
        slices = {slice_of[n.split("-")[0]] for n in placed_nodes.values()}
        assert len(slices) == 1  # reassembled on ONE slice, not scattered
    finally:
        controller.shutdown()
        for a in s0 + extra:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass


def test_evicted_gang_reassembly_skips_too_small_slice():
    """Atomic reassembly of a fully-evicted gang must land the WHOLE gang
    on a slice that fits it — greedy member-by-member re-placement could
    drop the first member on a slice with room for only one, pinning its
    mates to pend forever while it holds chips (ADVICE r2)."""
    # sliceA: ONE v5e-8 host (8 chips — fits one member, never two);
    # sliceZ: two v5e-64 hosts (8+8 — fits the gang). Names sort A first.
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")),
            "a-h0",
        )
    ] + [
        NodeAgentServer(
            new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h, slice_uid="sliceZ")
            ),
            f"z-h{h}",
        )
        for h in (0, 2)
    ]
    for a in agents:
        a.start()
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    try:
        for a in agents:
            _post(controller.address + "/nodes", {"url": a.address})
        # seed a fully-evicted gang: two members, shared gang id, nobody
        # placed (as if their whole slice died)
        from kubetpu.core.cluster import GangKey

        members = [tpu_pod(f"g{i}", 8) for i in range(2)]
        for m in members:
            m.requests[GangKey] = 777
        with controller._lock:
            controller._pending.extend(members)

        result = controller.poll_once()
        placed_nodes = {r["pod"]: r["node"] for r in result["rescheduled"]}
        assert sorted(placed_nodes) == ["g0", "g1"]
        assert set(placed_nodes.values()) == {"z-h0", "z-h2"}
        assert result["pending"] == []
    finally:
        controller.shutdown()
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass


def test_priority_preemption_over_api(stack):
    """A pod carrying kubetpu/priority preempts lower-priority pods when
    nothing fits; victims surface under "evicted", wait pending, and
    re-place automatically once capacity frees."""
    controller, _agents = stack
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("low-a", 8))})
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("low-b", 8))})

    high = tpu_pod("high", 4)
    high.requests["kubetpu/priority"] = 10
    out = _post(controller.address + "/pods", {"pod": pod_to_json(high)})
    assert out["placements"][0]["pod"] == "high"
    assert out["evicted"] in (["low-a"], ["low-b"])
    victim = out["evicted"][0]
    assert controller.pending_pods == [victim]

    # evicted victim needs 8 chips; only 4 free next to `high` -> pending
    assert controller.poll_once()["pending"] == [victim]
    # release the other low pod: the victim recovers on the next pass
    other = "low-b" if victim == "low-a" else "low-a"
    req = urllib.request.Request(
        controller.address + f"/pods/{other}", method="DELETE"
    )
    urllib.request.urlopen(req, timeout=10).read()
    result = controller.poll_once()
    assert result["rescheduled"][0]["pod"] == victim
    assert controller.pending_pods == []


def test_defrag_over_api():
    """POST /defrag plans and executes a migration that opens a perfect
    block; the pending pod lands contiguity-1.0 on the opened block."""
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")), f"n{i}"
        )
        for i in range(2)
    ]
    for a in agents:
        a.start()
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    try:
        for a in agents:
            _post(controller.address + "/nodes", {"url": a.address})
        # fragment n0 exactly like schedsim config 7: keep two awkward chips
        cluster = controller.cluster
        placed = {}
        for i in range(8):
            p = cluster.schedule(tpu_pod(f"s{i}", 1), lambda n: n == "n0")
            _t, coords = cluster.pod_chip_coords(p)
            placed[coords[0]] = p.name
        for coord, pname in placed.items():
            if coord not in {(0, 1), (1, 2)}:
                cluster.release(pname)
        cluster.schedule(tpu_pod("n1pod", 4), lambda n: n == "n1")

        out = _post(controller.address + "/defrag", {
            "chips": 6, "execute": True, "pending": pod_to_json(tpu_pod("big6", 6)),
        })
        assert out["plan"]  # at least one migration was needed
        assert out["pending_pod"]["pod"] == "big6"
        big6 = next(
            node.pods["big6"] for node in cluster.nodes.values()
            if "big6" in node.pods
        )
        assert cluster.gang_contiguity([big6]) == 1.0

        # a plan that cannot exist is a 409
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(controller.address + "/defrag", {"chips": 64})
        assert e.value.code == 409
    finally:
        controller.shutdown()
        for a in agents:
            try:
                a.shutdown()
            except Exception:  # noqa: BLE001
                pass


def test_preemption_submit_restores_victims_on_allocate_failure(stack, monkeypatch):
    """If allocation fails AFTER a preemption placed the pod, the victims
    must be restored to their node — a failed submit must not disrupt
    running workloads."""
    controller, _agents = stack
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("low-a", 8))})
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("low-b", 8))})

    def dying_allocations(device, pod_copy):
        raise ConnectionError("agent vanished mid-submit")

    monkeypatch.setattr(controller, "_run_allocations", dying_allocations)
    high = tpu_pod("high", 4)
    high.requests["kubetpu/priority"] = 10
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(controller.address + "/pods", {"pod": pod_to_json(high)})
    assert e.value.code == 503  # rolled back + retryable (wire leg died)

    # both low pods back in place, nothing pending, no capacity lost
    placed = {
        name for node in controller.cluster.nodes.values() for name in node.pods
    }
    assert placed == {"low-a", "low-b"}
    assert controller.pending_pods == []
    status_free = sum(
        node.info.allocatable["kubedevice/tpu"]
        for node in controller.cluster.nodes.values()
    )
    assert status_free == 0  # 8 + 8 held by the restored low pods


def test_pending_pod_is_deletable(stack):
    """An eviction victim waiting in the pending queue must be removable
    via DELETE — otherwise the next reconcile resurrects it."""
    controller, _agents = stack
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("low-a", 8))})
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("low-b", 8))})
    high = tpu_pod("high", 4)
    high.requests["kubetpu/priority"] = 10
    out = _post(controller.address + "/pods", {"pod": pod_to_json(high)})
    victim = out["evicted"][0]

    req = urllib.request.Request(
        controller.address + f"/pods/{victim}", method="DELETE"
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        body = json.loads(r.read())
    assert body == {"released": victim, "was_pending": True}
    assert controller.pending_pods == []
    # free capacity elsewhere: the deleted pod must NOT come back
    other = "low-b" if victim == "low-a" else "low-a"
    req = urllib.request.Request(
        controller.address + f"/pods/{other}", method="DELETE"
    )
    urllib.request.urlopen(req, timeout=10).read()
    assert controller.poll_once()["rescheduled"] == []


class _GatedAllocateManager:
    """Wraps a fake TPU manager; allocate() blocks until released, then
    optionally fails — the 'slow-but-alive agent' (accepted socket, stalled
    response) of VERDICT r2 weak #1."""

    def __init__(self, inner):
        self._inner = inner
        self.started = threading.Event()   # an allocate is in flight
        self.proceed = threading.Event()   # release the stall
        self.fail = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def allocate(self, pod, container):
        self.started.set()
        assert self.proceed.wait(30), "test never released the gate"
        if self.fail:
            raise RuntimeError("injected allocate failure")
        return self._inner.allocate(pod, container)


@pytest.fixture
def slow_stack():
    """One gated-allocate agent + controller (reconcile driven manually)."""
    mgr = _GatedAllocateManager(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    agent = NodeAgentServer(mgr, "slow0")
    agent.start()
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    _post(controller.address + "/nodes", {"url": agent.address})
    yield controller, agent, mgr
    mgr.proceed.set()  # never leave a handler thread stuck
    controller.shutdown()
    agent.shutdown()


def test_operator_api_responsive_during_stalled_allocate(slow_stack):
    """POST /pods against a slow-but-alive agent must not freeze the
    operator API: the wire allocate runs OUTSIDE the controller lock, so
    /status and DELETE answer while the submit stalls (ADVICE r2 medium)."""
    controller, _agent, mgr = slow_stack
    result = {}

    def submit():
        try:
            result["out"] = _post(
                controller.address + "/pods",
                {"pod": pod_to_json(tpu_pod("stalled", 4))},
            )
        except Exception as e:  # noqa: BLE001
            result["err"] = e

    t = threading.Thread(target=submit)
    t.start()
    assert mgr.started.wait(10), "submit never reached the agent"

    # while the allocate is stalled: status answers fast, shows the pod
    # placed (placement commits before the wire phase)...
    t0 = time.monotonic()
    status = _get(controller.address + "/status")
    assert time.monotonic() - t0 < 2.0
    assert "stalled" in status["nodes"]["slow0"]["pods"]
    # ...and DELETE of an unknown pod answers fast too
    t0 = time.monotonic()
    req = urllib.request.Request(
        controller.address + "/pods/nope", method="DELETE"
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=10)
    assert e.value.code == 404
    assert time.monotonic() - t0 < 2.0

    mgr.proceed.set()
    t.join(timeout=10)
    assert "out" in result, result.get("err")
    assert result["out"]["placements"][0]["pod"] == "stalled"


def test_reconcile_rollback_revalidates_deleted_pod(slow_stack):
    """A pending pod re-placed by the reconcile pass whose allocate fails
    must NOT be resurrected into the pending queue if the operator DELETEd
    it during the wire phase — and its chips stay free (no double
    placement)."""
    controller, _agent, mgr = slow_stack
    # seed a pending pod directly (the eviction path is tested elsewhere)
    with controller._lock:
        controller._pending.append(tpu_pod("ghost", 4))

    mgr.fail = True
    result = {}

    def reconcile():
        result["out"] = controller.poll_once()

    t = threading.Thread(target=reconcile)
    t.start()
    assert mgr.started.wait(10), "reconcile never reached the agent"
    # phase 2 in flight: the pod is placed; the operator deletes it
    req = urllib.request.Request(
        controller.address + "/pods/ghost", method="DELETE"
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        assert json.loads(r.read())["released"] == "ghost"

    mgr.proceed.set()
    t.join(timeout=10)
    # the failed allocate's rollback must respect the deletion: not placed,
    # not pending, all chips free
    assert result["out"]["rescheduled"] == []
    assert controller.pending_pods == []
    assert all(
        "ghost" not in node.pods for node in controller.cluster.nodes.values()
    )
    free = sum(
        node.info.allocatable["kubedevice/tpu"]
        for node in controller.cluster.nodes.values()
    )
    assert free == 8


def test_controller_cli_daemon_end_to_end():
    """The kubetpu-controller CLI as a REAL process: registers spawned
    agent processes at startup (skipping a dead URL with a warning instead
    of crash-looping), serves the API, and schedules over the wire."""
    import os
    import subprocess
    import sys

    from tests.test_wire import REPO, spawn_agent

    # a runner-level KUBETPU_WIRE_TOKEN would enable auth in the spawned
    # daemon while the helpers below send no token: pin it off
    env = {**os.environ, "KUBETPU_WIRE_TOKEN": ""}
    agent_proc, agent_url, agent_name = spawn_agent(0, topo="v5e-8", env=env)
    ctrl = subprocess.Popen(
        [sys.executable, "-m", "kubetpu.cli.controller",
         "--agents", agent_url, "http://127.0.0.1:1",  # second one is dead
         "--port", "0", "--poll-interval", "3600"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, cwd=REPO, text=True,
        env=env,
    )
    try:
        hello = json.loads(ctrl.stdout.readline())
        assert hello["nodes"] == [agent_name]
        assert hello["skipped"] == ["http://127.0.0.1:1"]

        out = _post(hello["listening"] + "/pods",
                    {"pod": pod_to_json(tpu_pod("job", 4))})
        assert out["placements"][0]["node"] == agent_name
        assert _get(hello["listening"] + "/status")["nodes"][agent_name]["pods"] == ["job"]
    finally:
        ctrl.kill()
        ctrl.wait(timeout=10)
        if agent_proc.poll() is None:
            agent_proc.kill()
        agent_proc.wait(timeout=10)


def _delete(addr, name):
    req = urllib.request.Request(addr + f"/pods/{name}", method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_queued_submission_waits_for_capacity(stack):
    """POST /pods with "queue": true pends instead of 409ing when the pod
    doesn't fit, and the reconcile pass places it once capacity frees."""
    controller, _ = stack
    for i in range(4):
        _post(controller.address + "/pods",
              {"pod": pod_to_json(tpu_pod(f"s{i}", 4))})
    out = _post(controller.address + "/pods",
                {"pod": pod_to_json(tpu_pod("late", 4)), "queue": True})
    assert out == {"queued": ["late"]}
    assert controller.poll_once()["pending"] == ["late"]
    _delete(controller.address, "s0")
    res = controller.poll_once()
    assert res["pending"] == []
    assert res["rescheduled"][0]["pod"] == "late"
    # the launcher env came along, same as any reconcile re-place
    assert "TPU_VISIBLE_DEVICES" in (
        res["rescheduled"][0]["containers"]["main"]["env"]
    )


def test_gang_reservation_prevents_starvation(stack):
    """The classic failure: a big gang waits while small pods keep grabbing
    every freed chip. After reserve_after passes the head-of-line gang
    claims the device class — new small submissions 409 (or queue BEHIND
    it), pending small pods stop placing, and when the gang finally
    assembles the queue drains normally."""
    controller, _ = stack
    assert controller.reserve_after == 3
    for i in range(4):
        _post(controller.address + "/pods",
              {"pod": pod_to_json(tpu_pod(f"s{i}", 4))})
    # 2-host gang needs all 16 chips; queue it
    out = _post(controller.address + "/pods",
                {"gang": [pod_to_json(tpu_pod("g0", 8)),
                          pod_to_json(tpu_pod("g1", 8))],
                 "queue": True})
    assert out == {"queued": ["g0", "g1"]}

    # age the gang past the threshold
    for _ in range(3):
        assert controller.poll_once()["reserved_gang"] is None
    assert controller.poll_once()["reserved_gang"] is not None

    # free 4 chips: a small pod WOULD fit, but the reservation refuses it
    _delete(controller.address, "s0")
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(controller.address + "/pods",
              {"pod": pod_to_json(tpu_pod("sneak", 4))})
    assert err.value.code == 409
    assert "reserved" in json.loads(err.value.read())["error"]

    # ...but it may queue behind the gang; the reconcile pass must NOT
    # place it while the reservation holds
    out = _post(controller.address + "/pods",
                {"pod": pod_to_json(tpu_pod("sneak", 4)), "queue": True})
    assert out == {"queued": ["sneak"]}
    res = controller.poll_once()
    assert res["rescheduled"] == []
    assert set(res["pending"]) == {"g0", "g1", "sneak"}

    # free the rest: the gang assembles on this pass (sneak still waits)
    for i in (1, 2, 3):
        _delete(controller.address, f"s{i}")
    res = controller.poll_once()
    assert {r["pod"] for r in res["rescheduled"]} == {"g0", "g1"}
    assert res["pending"] == ["sneak"]

    # reservation is gone; once chips free again the queued pod places
    assert controller.poll_once()["reserved_gang"] is None
    _delete(controller.address, "g0")
    res = controller.poll_once()
    assert {r["pod"] for r in res["rescheduled"]} == {"sneak"}


def test_priority_outranks_reservation(stack):
    """Reservation blocks same-or-lower priority work only: a pod that
    outranks the waiting gang still places immediately (preemption keeps
    working during a reservation)."""
    controller, _ = stack
    for i in range(4):
        _post(controller.address + "/pods",
              {"pod": pod_to_json(tpu_pod(f"s{i}", 4))})
    _post(controller.address + "/pods",
          {"gang": [pod_to_json(tpu_pod("g0", 8)),
                    pod_to_json(tpu_pod("g1", 8))],
           "queue": True})
    for _ in range(4):
        controller.poll_once()
    _delete(controller.address, "s0")
    high = tpu_pod("vip", 4)
    high.requests["kubetpu/priority"] = 10
    out = _post(controller.address + "/pods", {"pod": pod_to_json(high)})
    assert out["placements"][0]["pod"] == "vip"


def test_queue_refuses_request_beyond_total_capacity(stack):
    """A queued gang bigger than the whole cluster could never place but
    WOULD age into a class-wide reservation — refuse it at submit time."""
    controller, _ = stack
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(controller.address + "/pods",
              {"gang": [pod_to_json(tpu_pod(f"g{i}", 8)) for i in range(4)],
               "queue": True})
    assert err.value.code == 409
    assert "capacity" in json.loads(err.value.read())["error"]


def test_reservation_expires_and_reacquires(stack):
    """A reservation the cluster can't satisfy within reserve_hold passes
    expires (blocked work flows again), then re-acquires if the gang keeps
    waiting — no permanent soft-lock."""
    controller, _ = stack
    controller.reserve_hold = 2
    for i in range(4):
        _post(controller.address + "/pods",
              {"pod": pod_to_json(tpu_pod(f"s{i}", 4))})
    _post(controller.address + "/pods",
          {"gang": [pod_to_json(tpu_pod("g0", 8)),
                    pod_to_json(tpu_pod("g1", 8))],
           "queue": True})
    for _ in range(3):
        controller.poll_once()
    # held pass 1, pass 2, then expiry
    assert controller.poll_once()["reserved_gang"] is not None
    assert controller.poll_once()["reserved_gang"] is not None
    res = controller.poll_once()
    assert res["reserved_gang"] is None  # expired: small work flows again
    _delete(controller.address, "s0")
    out = _post(controller.address + "/pods",
                {"pod": pod_to_json(tpu_pod("flow", 4))})
    assert out["placements"][0]["pod"] == "flow"
    # it re-ages and re-reserves
    for _ in range(3):
        controller.poll_once()
    assert controller.poll_once()["reserved_gang"] is not None


def test_deleted_pending_age_not_inherited(stack):
    """DELETE of an aged queued pod drops its age: a same-name
    resubmission must wait the full reserve_after again."""
    controller, _ = stack
    for i in range(4):
        _post(controller.address + "/pods",
              {"pod": pod_to_json(tpu_pod(f"s{i}", 4))})
    _post(controller.address + "/pods",
          {"gang": [pod_to_json(tpu_pod("g0", 8)),
                    pod_to_json(tpu_pod("g1", 8))],
           "queue": True})
    for _ in range(4):
        controller.poll_once()
    assert controller._active_reservation() is not None
    _delete(controller.address, "g0")
    _delete(controller.address, "g1")
    _post(controller.address + "/pods",
          {"gang": [pod_to_json(tpu_pod("g0", 8)),
                    pod_to_json(tpu_pod("g1", 8))],
           "queue": True})
    res = controller.poll_once()
    assert res["reserved_gang"] is None  # fresh gang starts aging at 1


def test_surviving_gang_member_does_not_reserve(stack):
    """A pending member of a PARTIALLY-placed gang is slice-pinned — it
    must never hold a cluster-wide reservation (one evicted pod must not
    freeze the device class)."""
    controller, agents = stack
    out = _post(controller.address + "/pods",
                {"gang": [pod_to_json(tpu_pod("g0", 8)),
                          pod_to_json(tpu_pod("g1", 8))]})
    assert len(out["placements"]) == 2
    # find which agent hosts g0 and kill it; reconcile evicts g0 to pending
    node_of_g0 = next(p["node"] for p in out["placements"] if p["pod"] == "g0")
    victim = next(a for a in agents if a.node_name == node_of_g0)
    victim.shutdown()
    res = controller.poll_once()
    assert node_of_g0 in res["failed_nodes"]
    # age the survivor far past the threshold: its mates' slice is full
    # (g1 holds all 8 chips of the remaining host)
    for _ in range(5):
        res = controller.poll_once()
    assert res["reserved_gang"] is None
    assert "g0" in res["pending"]


def test_evicted_priority_pod_preempts_on_reconcile(stack):
    """A priority pod evicted by a node failure keeps its preemption
    rights when the reconcile pass re-places it — plain schedule would
    pin it pending behind lower-priority work forever."""
    controller, agents = stack
    vip = tpu_pod("vip", 8)
    vip.requests["kubetpu/priority"] = 10
    out = _post(controller.address + "/pods", {"pod": pod_to_json(vip)})
    vip_node = out["placements"][0]["node"]
    for i in range(2):  # fill the OTHER host with low-priority work
        _post(controller.address + "/pods",
              {"pod": pod_to_json(tpu_pod(f"low{i}", 4))})
    next(a for a in agents if a.node_name == vip_node).shutdown()
    res = controller.poll_once()
    assert vip_node in res["failed_nodes"]
    assert {r["pod"] for r in res["rescheduled"]} == {"vip"}
    assert set(res["pending"]) == {"low0", "low1"}  # preempted victims


def test_cordon_drain_over_api(stack):
    """Operator maintenance over the wire: cordon blocks placement, drain
    migrates with fresh launcher env, unplaceable pods pend and re-place
    after uncordon."""
    controller, _ = stack
    out = _post(controller.address + "/pods",
                {"pod": pod_to_json(tpu_pod("keep", 4))})
    node = out["placements"][0]["node"]
    other = "h0" if node == "h2" else "h2"

    # cordon the OTHER node: next pod must land on `node`
    _post(controller.address + f"/nodes/{other}/cordon", {})
    out2 = _post(controller.address + "/pods",
                 {"pod": pod_to_json(tpu_pod("second", 2))})
    assert out2["placements"][0]["node"] == node
    _post(controller.address + f"/nodes/{other}/uncordon", {})

    # drain the busy node: both pods migrate to the other host, env included
    res = _post(controller.address + f"/nodes/{node}/drain", {})
    assert res["drained"] == node
    moved = {m["pod"]: m for m in res["migrated"]}
    assert set(moved) == {"keep", "second"} and res["pending"] == []
    for m in moved.values():
        assert m["node"] == other
        assert "TPU_VISIBLE_DEVICES" in m["containers"]["main"]["env"]
    # the drained node takes nothing new until uncordoned
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("x", 2))})
    status = _get(controller.address + "/status")
    assert status["nodes"][node]["pods"] == []

    # unknown node -> 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(controller.address + "/nodes/ghost/drain", {})
    assert e.value.code == 404


def test_drain_unplaceable_pods_pend_and_recover(stack):
    controller, _ = stack
    # fill BOTH hosts so a drained pod has nowhere to go
    a = _post(controller.address + "/pods",
              {"pod": pod_to_json(tpu_pod("a", 8))})
    _post(controller.address + "/pods", {"pod": pod_to_json(tpu_pod("b", 8))})
    node_a = a["placements"][0]["node"]
    res = _post(controller.address + f"/nodes/{node_a}/drain", {})
    assert res["migrated"] == [] and res["pending"] == ["a"]
    # capacity appears elsewhere: the reconcile loop re-places "a" — but
    # never back onto the cordoned node
    _delete(controller.address, "b")
    poll = controller.poll_once()
    assert {r["pod"] for r in poll["rescheduled"]} == {"a"}
    assert poll["rescheduled"][0]["node"] != node_a


def test_drain_exempts_gang_survivors_from_reservation(stack):
    """Draining a node that hosts a RUNNING gang's member while a
    reservation is active must migrate the member within its mates'
    slice (slice-pinned placement cannot consume reserved capacity) —
    not evict it."""
    controller, _ = stack
    out = _post(controller.address + "/pods",
                {"gang": [pod_to_json(tpu_pod("g0", 4)),
                          pod_to_json(tpu_pod("g1", 4))]})
    nodes = {p["pod"]: p["node"] for p in out["placements"]}
    _post(controller.address + "/pods",
          {"gang": [pod_to_json(tpu_pod("big0", 8)),
                    pod_to_json(tpu_pod("big1", 8))],
           "queue": True})
    for _ in range(4):
        controller.poll_once()
    assert controller._active_reservation() is not None
    res = _post(controller.address + f"/nodes/{nodes['g0']}/drain", {})
    moved = {m["pod"]: m["node"] for m in res["migrated"]}
    assert moved.get("g0") == nodes["g1"], res  # migrated beside its mate
    assert "g0" not in res["pending"]


def test_multislice_gang_over_the_wire():
    """A multislice gang submitted through the controller HTTP API: the
    knob rides the pod JSON, placement spans both slices, and the
    returned launcher env carries the MEGASCALE identity (round 5)."""
    agents = []
    try:
        for uid, pre in (("podA", "a"), ("podB", "b")):
            for h in range(2):
                agents.append(NodeAgentServer(
                    new_fake_tpu_dev_manager(
                        make_fake_tpus_info("v5e-64", host_index=h,
                                            slice_uid=uid)
                    ),
                    f"{pre}{h}",
                ))
        for a in agents:
            a.start()
        ctl = ControllerServer(poll_interval=3600)
        try:
            ctl.start()
            for a in agents:
                _post(ctl.address + "/nodes", {"url": a.address})

            from kubetpu.scheduler.meshstate import MultisliceKey

            def mpod(name):
                p = tpu_pod(name, 8)
                p.requests[MultisliceKey] = 2
                return p

            # 4 pods x 8 chips = 32 > 16 per slice: must span both
            out = _post(
                ctl.address + "/pods",
                {"gang": [pod_to_json(mpod(f"w{i}")) for i in range(4)]},
            )
            placements = out["placements"]
            assert len(placements) == 4
            slice_ids = set()
            for pl in placements:
                envs = [c["env"] for c in pl["containers"].values()
                        if c["env"].get("TPU_VISIBLE_DEVICES")]
                assert envs, pl
                env = envs[0]
                assert env["MEGASCALE_NUM_SLICES"] == "2"
                slice_ids.add(env["MEGASCALE_SLICE_ID"])
            assert slice_ids == {"0", "1"}
        finally:
            ctl.shutdown()
    finally:
        for a in agents:
            a.shutdown()
