"""Round-20 durable control plane: the ``Journal`` WAL + reducer, and
the cold-restart replay BOUNDARY property — a controller restored from
a WAL truncated after ANY record prefix (the every-possible-crash-point
sweep) must reconcile to a consistent cluster (``check_invariants``
clean) with the wire reporting ready, and a torn partial tail must be
dropped and counted, never guessed at."""

import json

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Journal, JournalCorrupt
from kubetpu.core.journal import empty_state, reduce_records
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.wire import ControllerServer, NodeAgentServer
from kubetpu.wire.controller import pod_to_json
from kubetpu.wire.httpcommon import request_json


def tpu_pod(name, chips=4):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(
            requests={ResourceTPU: chips})},
    )


# -- the WAL itself ----------------------------------------------------------


def test_append_replay_roundtrip(tmp_path):
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    s1 = j.append("node_register", {"name": "n0", "url": "http://x"})
    s2 = j.append("pod_pending", {"pod": {"name": "p0"}})
    assert (s1, s2) == (1, 2)
    j.close()

    state, records = Journal(path).replay()
    assert state == {}
    assert [(r["seq"], r["kind"]) for r in records] == [
        (1, "node_register"), (2, "pod_pending")]
    # pure read: replaying twice yields the same result
    assert Journal(path).replay() == (state, records)


def test_seq_resumes_across_restart(tmp_path):
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    j.append("pod_pending", {"pod": {"name": "p0"}})
    j.close()
    j2 = Journal(path)
    assert j2.append("pod_pending", {"pod": {"name": "p1"}}) == 2
    j2.close()


def test_torn_tail_dropped_and_counted(tmp_path):
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    j.append("node_register", {"name": "n0", "url": "http://x"})
    j.close()
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"seq": 2, "kind": "pod_place", "da')  # the SIGKILL cut

    j2 = Journal(path)
    _state, records = j2.replay()
    assert [r["seq"] for r in records] == [1]
    assert j2.stats()["torn_tail_dropped"] == 1
    # the torn line must not eat the next seq either
    assert j2.append("pod_pending", {"pod": {"name": "p0"}}) == 2


def test_append_after_torn_tail_never_merges(tmp_path):
    """THE torn-tail repair contract: a restarted Journal must truncate
    the partial last line BEFORE its first append. Without the repair,
    post-crash records land ON the fragment — one acked append is then
    silently lost at the next replay (the merged line reads as a torn
    tail), and two or more turn into mid-file corruption that refuses
    to boot."""
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    j.append("node_register", {"name": "n0", "url": "http://x"})
    j.close()
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"seq": 2, "kind": "pod_place", "da')  # the SIGKILL cut

    j2 = Journal(path)  # repair happens here, before any append
    assert j2.stats()["torn_tail_dropped"] == 1
    j2.append("pod_pending", {"pod": {"name": "p0"}})
    j2.append("pod_pending", {"pod": {"name": "p1"}})
    j2.close()

    # BOTH acked post-crash appends survive the next restart
    _state, records = Journal(path).replay()
    assert [(r["seq"], r["kind"]) for r in records] == [
        (1, "node_register"), (2, "pod_pending"), (3, "pod_pending")]


def test_valid_unterminated_tail_kept_and_terminated(tmp_path):
    """A crash BETWEEN the record's JSON and its newline leaves a valid
    but unterminated last line — that op was acked, so the repair must
    finish the line (not drop it) and the next append must start fresh."""
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    j.append("node_register", {"name": "n0", "url": "http://x"})
    j.append("pod_pending", {"pod": {"name": "p0"}})
    j.close()
    raw = open(path, encoding="utf-8").read()
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(raw.rstrip("\n"))  # strip ONLY the final terminator

    j2 = Journal(path)
    assert j2.stats()["torn_tail_dropped"] == 0
    assert j2.append("pod_pending", {"pod": {"name": "p1"}}) == 3
    j2.close()
    _state, records = Journal(path).replay()
    assert [r["seq"] for r in records] == [1, 2, 3]


def test_journal_files_owner_only(tmp_path):
    """The WAL and snapshot carry agent bearer tokens: both must be
    created 0600, and a pre-existing looser file is tightened at init."""
    import os as _os
    import stat
    import sys
    if sys.platform == "win32":
        pytest.skip("posix permissions")
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    j.append("node_register",
             {"name": "n0", "url": "http://x", "token": "secret"})
    j.snapshot(j.replay_state())
    j.close()
    for p in (path, path + ".snap"):
        assert stat.S_IMODE(_os.stat(p).st_mode) == 0o600, p
    _os.chmod(path, 0o644)
    Journal(path).close()
    assert stat.S_IMODE(_os.stat(path).st_mode) == 0o600


def test_bad_crc_tail_dropped(tmp_path):
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    seq = j.append("node_register", {"name": "n0", "url": "http://x"})
    j.close()
    # a complete-looking record whose checksum lies is as untrustworthy
    # as a half-written one
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps({"seq": seq + 1, "kind": "pod_place",
                             "data": {}, "crc": 1}) + "\n")
    _state, records = Journal(path).replay()
    assert [r["seq"] for r in records] == [seq]


def test_mid_file_corruption_raises(tmp_path):
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    j.append("node_register", {"name": "n0", "url": "http://x"})
    j.append("pod_pending", {"pod": {"name": "p0"}})
    j.close()
    lines = open(path, encoding="utf-8").readlines()
    lines[0] = lines[0][:20] + "\n"  # damage a NON-tail record
    with open(path, "w", encoding="utf-8") as fh:
        fh.writelines(lines)
    with pytest.raises(JournalCorrupt):
        Journal(path).replay()


def test_snapshot_compacts_and_replays_idempotently(tmp_path):
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    j.append("node_register", {"name": "n0", "url": "http://x"})
    j.append("pod_pending", {"pod": {"name": "p0"}})
    baseline = reduce_records(empty_state(), j.replay()[1])
    j.snapshot(baseline)
    assert j.stats()["wal_bytes"] == 0          # WAL compacted away
    after = j.append("pod_pending", {"pod": {"name": "p1"}})
    j.close()

    j2 = Journal(path)
    state, records = j2.replay()
    assert state["agents"] == {"n0": {"url": "http://x", "token": None}}
    assert [r["seq"] for r in records] == [after]
    # a record with seq <= the snapshot's must be skipped even if the
    # WAL still holds it (crash between snapshot write and truncation)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(
            {"seq": 1, "kind": "node_register",
             "data": {"name": "ghost", "url": "http://y"},
             "crc": __import__("zlib").crc32(json.dumps(
                 [1, "node_register", {"name": "ghost", "url": "http://y"}],
                 sort_keys=True, separators=(",", ":")).encode())
             & 0xFFFFFFFF}, sort_keys=True, separators=(",", ":")) + "\n")
    state3 = Journal(path).replay_state()
    assert "ghost" not in state3["agents"]


# -- the reducer -------------------------------------------------------------


def test_reducer_semantics():
    pod = {"name": "p0", "requests": {"kubetpu/gang": 7}}
    recs = [
        {"seq": 1, "kind": "node_register",
         "data": {"name": "n0", "url": "u0", "token": "t"}},
        {"seq": 2, "kind": "pod_pending", "data": {"pod": pod}},
        {"seq": 3, "kind": "pod_place", "data": {"pod": pod, "node": "n0"}},
        {"seq": 4, "kind": "cordon", "data": {"name": "n0", "on": True}},
        {"seq": 5, "kind": "mystery_future_kind", "data": {"x": 1}},
    ]
    st = reduce_records(empty_state(), recs)
    assert st["agents"]["n0"] == {"url": "u0", "token": "t"}
    assert st["pending"] == []                  # place consumed the queue
    assert st["placements"]["p0"]["node"] == "n0"
    assert st["cordons"] == ["n0"]
    assert st["gang_seq"] == 7                  # high-water for new gangs

    # node death re-pends its placements, the breaker-eviction motion
    st = reduce_records(st, [
        {"seq": 6, "kind": "node_dead", "data": {"name": "n0"}}])
    assert st["agents"] == {}
    assert st["placements"] == {}
    assert [p["name"] for p in st["pending"]] == ["p0"]

    st = reduce_records(st, [
        {"seq": 7, "kind": "pod_delete", "data": {"name": "p0"}}])
    assert st["pending"] == []

    # idempotence as a property of plain data
    assert reduce_records(dict(st), []) == st


def test_gang_seq_only_journal_still_recovers(tmp_path):
    """A WAL whose reduced state carries ONLY a gang_seq high-water
    (every pod deleted, every node dead) must still trigger recovery:
    a restarted controller that skips the restore would re-issue
    already-replayed gang-id stamps."""
    path = str(tmp_path / "j.journal")
    j = Journal(path)
    j.append("pod_pending",
             {"pod": {"name": "g0", "requests": {"kubetpu/gang": 7}}})
    j.append("pod_delete", {"name": "g0"})
    j.close()
    state = Journal(path).replay_state()
    assert (state["agents"], state["placements"], state["pending"],
            state["cordons"]) == ({}, {}, [], [])
    assert state["gang_seq"] == 7

    c = ControllerServer(poll_interval=3600, journal_path=path)
    assert c.recovering
    c.start()
    try:
        assert not c.recovering  # recovery ran and opened the wire
        assert c.cluster.new_gang_id() == 8  # high-water restored
    finally:
        c.shutdown(graceful=False)


# -- every-crash-point replay boundary sweep ---------------------------------


def test_replay_boundary_every_truncation_reconciles(tmp_path):
    """Build a real journaled run (2 agents, 3 pods placed, 1 delete),
    then cold-restart a controller from the WAL truncated after EVERY
    record prefix — plus a torn mid-record tail on the full WAL. Each
    restart must come up ready (not recovering), with clean cluster
    invariants; orphaned agent allocations from beyond the truncation
    point must be freed by the reconcile diff."""
    src = str(tmp_path / "src.journal")
    agents = [
        NodeAgentServer(
            new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h)),
            f"bnd-h{h}")
        for h in range(2)
    ]
    for a in agents:
        a.start()
    c1 = ControllerServer(poll_interval=3600, journal_path=src)
    c1.start()
    try:
        for a in agents:
            request_json(c1.address + "/nodes", {"url": a.address},
                         idempotency_key=f"bnd-reg-{a.node_name}")
        for i in range(3):
            request_json(
                c1.address + "/pods",
                {"pod": pod_to_json(tpu_pod(f"bnd-p{i}"))},
                idempotency_key=f"bnd-p{i}")
        request_json(c1.address + "/pods/bnd-p2", None, method="DELETE",
                     idempotency_key="bnd-del")
    finally:
        c1.shutdown(graceful=False)

    lines = open(src, encoding="utf-8").readlines()
    assert len(lines) >= 6          # 2 registers + 3 places + 1 delete

    def restart_from(wal_text, tag):
        path = str(tmp_path / f"cut-{tag}.journal")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(wal_text)
        c = ControllerServer(poll_interval=3600, journal_path=path)
        c.start()
        try:
            assert not c.recovering, f"cut {tag}: wire never opened"
            problems = c.cluster.check_invariants()
            assert not problems, f"cut {tag}: {problems}"
            placed = {p for n in c.cluster.nodes.values() for p in n.pods}
            # every pod the truncated journal knows about is either
            # placed or pending — nothing silently vanishes
            state = Journal(path).replay_state()
            known = (set(state["placements"])
                     | {p["name"] for p in state["pending"]})
            assert known == placed | set(c.pending_pods), (
                f"cut {tag}: journal knows {sorted(known)}, cluster has "
                f"{sorted(placed)} + pending {c.pending_pods}")
        finally:
            c.shutdown(graceful=False)

    # agent allocations beyond a cut are freed as orphans by that cut's
    # reconcile, then re-allocated by the next (longer) cut's replay —
    # the sweep exercises both directions of the diff
    for k in range(len(lines) + 1):
        restart_from("".join(lines[:k]), str(k))
    restart_from("".join(lines) + '{"seq": 999, "kind": "pod_pl',
                 "torn")

    for a in agents:
        a.shutdown()
