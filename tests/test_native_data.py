"""The native data loader: C-speed mmap gather must agree with numpy
slicing exactly, be deterministic per seed, and fail loudly on bad input."""

import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "_output", "libkubetpu_dataio.so")


@pytest.fixture(scope="module", autouse=True)
def dataio_lib():
    # unconditional: make's own mtime check rebuilds after loader.cc edits
    # (an exists() guard would silently test a stale binary)
    subprocess.run(["make", "-C", REPO, "dataio"], check=True,
                   capture_output=True)
    return LIB


@pytest.fixture
def corpus(tmp_path):
    from kubetpu.jobs.native_data import write_token_file

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 50_000, size=10_000).astype(np.uint16)
    path = tmp_path / "corpus.bin"
    write_token_file(str(path), tokens)
    return str(path), tokens


def test_gather_matches_numpy(corpus):
    from kubetpu.jobs.native_data import TokenFile

    path, tokens = corpus
    with TokenFile(path) as tf:
        assert tf.num_tokens == len(tokens)
        offsets = np.asarray([0, 17, 9000, len(tokens) - 64])
        rows = tf.gather(offsets, 64)
        for i, off in enumerate(offsets):
            np.testing.assert_array_equal(
                rows[i], tokens[off:off + 64].astype(np.int32)
            )


def test_uint32_corpus(tmp_path):
    from kubetpu.jobs.native_data import TokenFile, write_token_file

    tokens = np.arange(100_000, 100_500, dtype=np.uint32)
    path = str(tmp_path / "c32.bin")
    write_token_file(path, tokens, dtype=np.uint32)
    with TokenFile(path, dtype_bytes=4) as tf:
        rows = tf.gather(np.asarray([10]), 5)
        np.testing.assert_array_equal(rows[0], tokens[10:15].astype(np.int32))


def test_batches_shifted_and_deterministic(corpus):
    from kubetpu.jobs.native_data import TokenFile

    path, _tokens = corpus
    with TokenFile(path) as tf:
        it1 = tf.batches(4, 32, seed=7)
        it2 = tf.batches(4, 32, seed=7)
        for _ in range(3):
            t1, y1 = next(it1)
            t2, y2 = next(it2)
            np.testing.assert_array_equal(t1, t2)
            np.testing.assert_array_equal(y1, y2)
            np.testing.assert_array_equal(t1[:, 1:], y1[:, :-1])  # shift-by-1


def test_out_of_range_offsets_raise(corpus):
    from kubetpu.jobs.native_data import TokenFile

    path, tokens = corpus
    with TokenFile(path) as tf:
        with pytest.raises(ValueError):
            tf.gather(np.asarray([len(tokens) - 3]), 8)
        with pytest.raises(ValueError):
            tf.gather(np.asarray([-1]), 8)


def test_missing_file_and_bad_dtype(tmp_path):
    from kubetpu.jobs.native_data import TokenFile

    with pytest.raises(OSError):
        TokenFile(str(tmp_path / "nope.bin"))
    with pytest.raises(ValueError):
        TokenFile(str(tmp_path / "x"), dtype_bytes=3)


@pytest.mark.slow
def test_feeds_the_train_step(corpus):
    """End to end: native batches drive the real sharded train step."""
    import jax

    from kubetpu.jobs import ModelConfig, init_state, make_mesh, make_train_step
    from kubetpu.jobs.native_data import TokenFile

    path, _tokens = corpus
    cfg = ModelConfig(vocab=50_000, d_model=32, n_layers=1, n_heads=4, d_ff=64)
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt)
    with TokenFile(path) as tf:
        for (tokens_np, targets_np), _ in zip(tf.batches(4, 32, seed=1), range(2)):
            state, loss = step(state, tokens_np, targets_np)
    assert np.isfinite(float(loss))


def test_write_refuses_out_of_range_tokens(tmp_path):
    from kubetpu.jobs.native_data import write_token_file

    with pytest.raises(ValueError):
        write_token_file(str(tmp_path / "bad.bin"),
                         np.asarray([1, 70_000]))  # > uint16 max


def test_closed_tokenfile_raises_clearly(corpus):
    from kubetpu.jobs.native_data import TokenFile

    path, _tokens = corpus
    tf = TokenFile(path)
    tf.close()
    with pytest.raises(ValueError, match="closed"):
        tf.gather(np.asarray([0]), 4)


def test_worker_sharded_batches_are_disjoint(corpus):
    """Each worker's windows come from its own contiguous span of the
    corpus — disjoint data for multi-process dp, deterministic per
    (seed, worker)."""
    from kubetpu.jobs.native_data import TokenFile

    path, _tokens = corpus
    with TokenFile(path) as tf:
        seen = {}
        for w in range(2):
            tokens, _ = next(tf.batches(batch=64, seq=4, seed=5,
                                        worker=w, num_workers=2))
            seen[w] = tokens
        # same seed, different workers -> different streams
        assert not np.array_equal(seen[0], seen[1])
        # determinism: same (seed, worker) replays exactly
        again, _ = next(tf.batches(batch=64, seq=4, seed=5,
                                   worker=1, num_workers=2))
        np.testing.assert_array_equal(seen[1], again)
        with pytest.raises(ValueError):
            next(tf.batches(batch=1, seq=4, worker=2, num_workers=2))
