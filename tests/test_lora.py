"""LoRA fine-tuning: zero-delta init, adapter-only training, merged export,
sharding consistency, and config validation."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, forward, init_params, make_mesh
from kubetpu.jobs.lora import (
    LoraConfig,
    init_lora_params,
    init_lora_state,
    lora_param_count,
    lora_param_specs,
    make_lora_train_step,
    merge_lora,
)
from kubetpu.jobs.model import next_token_loss

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                  max_seq=64)
LCFG = LoraConfig(rank=4, alpha=8.0)


def test_lora_init_is_identity():
    """B = 0 at init: the merged model must reproduce the base
    bit-for-bit before any training."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    lora = init_lora_params(jax.random.PRNGKey(1), CFG, LCFG)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, CFG.vocab)
    out_base = forward(base, tokens, CFG)
    out_merged = forward(merge_lora(base, lora, LCFG), tokens, CFG)
    np.testing.assert_array_equal(np.asarray(out_base), np.asarray(out_merged))


@pytest.mark.slow
def test_lora_trains_and_base_is_untouched():
    """Fine-tuning drops the loss while every base leaf stays frozen and
    only the adapters move; the merged export reproduces the trained
    behavior.
    Slow: a real train loop on an 8-way mesh; the structural pins
    (identity init, targeting, spec coverage) stay tier-1."""
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    base = init_params(jax.random.PRNGKey(0), CFG)
    base_snapshot = jax.tree.map(np.asarray, base)
    from kubetpu.jobs.train import make_optimizer

    # LoRA's standard recipe is a much higher LR than pretraining (only
    # the rank-r factors move)
    state, opt = init_lora_state(jax.random.PRNGKey(1), CFG, LCFG, mesh,
                                 optimizer=make_optimizer(lr=1e-2))
    step = make_lora_train_step(CFG, LCFG, mesh, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, CFG.vocab)
    targets = jnp.roll(tokens, -1, axis=1)

    losses = []
    for _ in range(12):
        state, loss = step(state, base, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses

    for before, after in zip(jax.tree.leaves(base_snapshot),
                             jax.tree.leaves(base)):
        np.testing.assert_array_equal(before, np.asarray(after))
    # at least one B factor moved off zero
    moved = any(
        float(jnp.abs(state.params["blocks"][f"{t}_b"]).max()) > 0
        for t in LCFG.targets
    )
    assert moved

    # merged export reproduces the trained model: its loss continues the
    # descent (losses[-1] is pre-12th-update; merged params are post)
    merged = merge_lora(base, state.params, LCFG)
    final = float(next_token_loss(merged, tokens, targets, CFG))
    assert final <= losses[-1] + 1e-3, (final, losses[-1])
    base_loss = float(next_token_loss(base, tokens, targets, CFG))
    assert final < base_loss * 0.9


def test_lora_param_count_is_tiny():
    """Exact adapter count for the toy config, and the trainable fraction
    for flagship-shaped dims (computed analytically — materializing 0.75B
    on CPU is not a unit test)."""
    lora = init_lora_params(jax.random.PRNGKey(1), CFG, LCFG)
    L, d, r = CFG.n_layers, CFG.d_model, LCFG.rank
    per_proj = L * (d * r + r * d)  # A (L,d,r) + B (L,r,h,hd); h*hd == d
    assert lora_param_count(lora) == 4 * per_proj

    # flagship dims: vocab 32k, d 2048, 12 layers (bench_model.flagship_cfg)
    Lf, df, vf, ff = 12, 2048, 32000, 5632
    base_f = vf * df * 2 + Lf * (2 * df + 4 * df * df + 3 * df * ff) + df
    lora_f = 4 * Lf * (df * 8 + 8 * df)  # rank 8, four projections
    assert lora_f / base_f < 0.005


def test_lora_mlp_targets_dense_only():
    lcfg = LoraConfig(rank=2, targets=("wq", "w_gate", "w_down"))
    lora = init_lora_params(jax.random.PRNGKey(0), CFG, lcfg)
    assert lora["blocks"]["w_gate_b"].shape == (CFG.n_layers, 2, CFG.d_ff)
    moe = dataclasses.replace(CFG, n_experts=2)
    with pytest.raises(ValueError):
        init_lora_params(jax.random.PRNGKey(0), moe, lcfg)


def test_lora_config_validation():
    with pytest.raises(ValueError):
        LoraConfig(rank=0)
    with pytest.raises(ValueError):
        LoraConfig(targets=("wq", "nope"))
    with pytest.raises(ValueError):
        LoraConfig(targets=())


def test_lora_specs_cover_params_and_put_heads_on_tp():
    lcfg = LoraConfig(rank=2, targets=("wq", "wo", "w_up", "w_down"))
    lora = init_lora_params(jax.random.PRNGKey(0), CFG, lcfg)
    specs = lora_param_specs(CFG, lcfg)
    assert jax.tree.structure(lora) == jax.tree.structure(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    )
    assert specs["blocks"]["wq_b"][2] == "tp"
    assert specs["blocks"]["wo_a"][1] == "tp"
    assert specs["blocks"]["w_up_b"][2] == "tp"
    assert specs["blocks"]["w_down_a"][1] == "tp"


def test_lora_gqa_shapes_follow_kv_heads():
    cfg = dataclasses.replace(CFG, n_kv_heads=2)
    lora = init_lora_params(jax.random.PRNGKey(0), cfg, LCFG)
    assert lora["blocks"]["wk_b"].shape == (cfg.n_layers, LCFG.rank, 2,
                                            cfg.head_dim)
    assert lora["blocks"]["wq_b"].shape == (cfg.n_layers, LCFG.rank,
                                            cfg.n_heads, cfg.head_dim)
    base = init_params(jax.random.PRNGKey(1), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, cfg.vocab)
    out = forward(merge_lora(base, lora, LCFG), tokens, cfg)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(forward(base, tokens, cfg)))


def test_lora_accum_steps_rejected():
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 1})
    with pytest.raises(NotImplementedError):
        make_lora_train_step(CFG, LCFG, mesh, accum_steps=2)
