"""Round-12: the static invariant linter (`kubetpu.analysis`).

Fixture-driven per rule (one violating + one clean snippet each),
suppression + baseline-ratchet mechanics, the CLI's JSON surface, the
new `httpcommon.request_text` wire path the migrations ride, and the
meta-test: the repo itself lints clean against the committed baseline.
"""

import json
import os
import textwrap

import pytest

from kubetpu.analysis import baseline as baseline_mod
from kubetpu.analysis.cli import main as lint_main
from kubetpu.analysis.core import all_rules, run_lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def lint(tmp_path, files, rules=None, baseline=None):
    root = make_tree(tmp_path, files)
    picked = None
    if rules is not None:
        want = set(rules)
        picked = [r for r in all_rules() if r.code in want]
        assert {r.code for r in picked} == want
    return run_lint(root, ["."], rules=picked, baseline=baseline)


def codes(result):
    return [f.code for f in result.active]


# -- KTP001 hot-path-sync ----------------------------------------------------

HOT_VIOLATING = """
    class Server:
        def step(self):
            return self._advance()

        def _advance(self):
            vals = jnp.asarray(self.host_buf)      # upload in the hot loop
            return vals.tolist()                   # and a sync
    """

HOT_CLEAN = """
    class Server:
        def step(self):
            return self._advance()

        def _advance(self):
            return self._step_fn(self.cache)

        def warmup(self):
            # barrier leg: uploads here are by design
            jnp.asarray([0])
    """


def test_hotpath_flags_sync_reachable_from_step(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/serving.py": HOT_VIOLATING},
               rules=["KTP001"])
    assert codes(res) == ["KTP001", "KTP001"]
    msgs = [f.message for f in res.active]
    assert any("jnp.asarray" in m for m in msgs)
    assert any(".tolist()" in m for m in msgs)


def test_hotpath_clean_and_barriers_exempt(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/serving.py": HOT_CLEAN},
               rules=["KTP001"])
    assert res.active == []


def test_hotpath_follows_inheritance_across_modules(tmp_path):
    # base step() in serving.py, the offending override lives in paged.py
    # — the closure must flatten the hierarchy across files
    res = lint(tmp_path, {
        "kubetpu/jobs/serving.py": """
            class SlotServerBase:
                def step(self):
                    return self._device_step()

                def _device_step(self):
                    raise NotImplementedError
            """,
        "kubetpu/jobs/paged.py": """
            from kubetpu.jobs.serving import SlotServerBase

            class PagedDecodeServer(SlotServerBase):
                def _device_step(self):
                    return self.tokens.item()
            """,
    }, rules=["KTP001"])
    assert [(f.path, f.code) for f in res.active] == [
        ("kubetpu/jobs/paged.py", "KTP001")]


def test_hotpath_ignores_cold_modules(tmp_path):
    # same code outside the hot modules: not serving's step, no finding
    res = lint(tmp_path, {"kubetpu/jobs/train.py": HOT_VIOLATING},
               rules=["KTP001"])
    assert res.active == []


# -- KTP002 wire-hygiene -----------------------------------------------------


def test_wire_flags_raw_urlopen_and_naked_post(tmp_path):
    res = lint(tmp_path, {"kubetpu/cli/thing.py": """
        import urllib.request
        from kubetpu.wire.httpcommon import request_json

        def scrape(url):
            with urllib.request.urlopen(url) as r:   # raw socket
                return r.read()

        def submit(url, pod):
            return request_json(url + "/pods", {"pod": pod})  # naked POST
        """}, rules=["KTP002"])
    assert codes(res) == ["KTP002", "KTP002"]
    assert "urlopen" in res.active[0].message
    assert "idempotency_key" in res.active[1].message


def test_wire_clean_sites_pass(tmp_path):
    res = lint(tmp_path, {
        # the one module allowed to urlopen: the shared client itself
        "kubetpu/wire/httpcommon.py": """
            import urllib.request

            def request_json(url):
                with urllib.request.urlopen(url) as r:
                    return r.read()
            """,
        "kubetpu/cli/thing.py": """
            from kubetpu.wire.httpcommon import request_json

            def ok(url, pod, key):
                request_json(url, {"pod": pod}, idempotency_key=key)
                request_json(url + "/pods/p0")            # GET
                request_json(url, method="DELETE")        # idempotent verb
            """,
    }, rules=["KTP002"])
    assert res.active == []


# -- KTP003 lock-discipline --------------------------------------------------

LOCK_VIOLATING = """
    import threading

    class Registry:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = {}

        def add(self, k):
            with self._lock:
                self.items[k] = 1

        def clear(self):
            self.items = {}          # unguarded write to guarded state
    """


def test_lock_flags_unguarded_write(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/reg2.py": LOCK_VIOLATING},
               rules=["KTP003"])
    assert codes(res) == ["KTP003"]
    assert "self.items" in res.active[0].message


def test_lock_clean_under_lock_and_locked_convention(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/reg2.py": """
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = {}

            def add(self, k):
                with self._lock:
                    self.items[k] = 1

            def clear(self):
                with self._lock:
                    self.items = {}

            def _evict_locked(self, k):
                # caller holds the lock (project convention)
                del self.items[k]
        """}, rules=["KTP003"])
    assert res.active == []


# -- KTP004 metric-hygiene ---------------------------------------------------


def test_metric_flags_fstring_grammar_and_counter_suffix(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/thing.py": """
        def setup(reg, name):
            reg.counter(f"kubetpu_{name}_total").inc()   # unbounded
            reg.counter("kubetpu_requests")              # not *_total
            reg.gauge("badprefix_depth")                 # wrong grammar
            reg.histogram(name)                          # non-literal
        """}, rules=["KTP004"])
    assert codes(res) == ["KTP004"] * 4


def test_metric_clean_names_pass(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/thing.py": """
        def setup(reg):
            reg.counter("kubetpu_requests_total").inc()
            reg.gauge("kubetpu_queue_depth").set(0)
            reg.histogram("kubetpu_ttft_seconds", op="serve")
        """}, rules=["KTP004"])
    assert res.active == []


# -- KTP005 determinism ------------------------------------------------------


def test_determinism_flags_wall_clock_and_stdlib_random(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/widget.py": """
        import random
        import time

        def pick(xs):
            t = time.time()
            return random.choice(xs), t
        """}, rules=["KTP005"])
    assert codes(res) == ["KTP005", "KTP005"]


def test_determinism_allows_seeded_and_monotonic(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/widget.py": """
        import time

        def pick(xs, rng, key):
            t0 = time.perf_counter()
            a = np.random.RandomState(0).permutation(len(xs))
            b = jax.random.fold_in(key, 3)
            return a, b, time.monotonic() - t0
        """}, rules=["KTP005"])
    assert res.active == []


def test_determinism_scoped_to_jobs(tmp_path):
    # obs/wire legitimately read wall clock (timestamps, TTLs)
    res = lint(tmp_path, {"kubetpu/obs/clock.py": """
        import time

        def now():
            return time.time()
        """}, rules=["KTP005"])
    assert res.active == []


# -- KTP006 jit-leg-hygiene --------------------------------------------------


def test_jit_flags_in_loop_and_step_closure(tmp_path):
    res = lint(tmp_path, {
        "kubetpu/jobs/legs.py": """
            def compile_all(fns):
                legs = []
                for fn in fns:
                    legs.append(jax.jit(fn))      # fresh leg per iteration
                return legs
            """,
        "kubetpu/jobs/serving.py": """
            class Server:
                def step(self):
                    return self._advance()

                def _advance(self):
                    return jax.jit(self._fn)(self.cache)   # per-step jit
            """,
    }, rules=["KTP006"])
    got = sorted((f.path, f.code) for f in res.active)
    assert got == [("kubetpu/jobs/legs.py", "KTP006"),
                   ("kubetpu/jobs/serving.py", "KTP006")]


def test_jit_flags_decorator_and_comprehension_in_loop(tmp_path):
    # the def's body runs later, but its DECORATORS evaluate per loop
    # iteration — a fresh leg each time; comprehensions are loops too
    res = lint(tmp_path, {"kubetpu/jobs/legs.py": """
        from functools import partial

        def per_gamma(fns, gammas):
            legs = []
            for g in gammas:
                @partial(jax.jit, static_argnums=(0,))
                def leg(cache):
                    return cache
                legs.append(leg)
            return legs

        def all_at_once(fns):
            return [jax.jit(f) for f in fns]
        """}, rules=["KTP006"])
    assert codes(res) == ["KTP006", "KTP006"]
    assert all("inside a loop" in f.message for f in res.active)


def test_jit_clean_factory_passes(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/legs.py": """
        from functools import partial

        def make_leg(fn):
            @partial(jax.jit, donate_argnums=(0,))
            def leg(cache, tok):
                return fn(cache, tok)
            return leg
        """}, rules=["KTP006"])
    assert res.active == []


# -- suppressions ------------------------------------------------------------


def test_inline_suppression_trailing_and_line_above(tmp_path):
    res = lint(tmp_path, {"kubetpu/cli/thing.py": """
        import urllib.request

        def a(url):
            return urllib.request.urlopen(url)  # ktlint: disable=KTP002

        def b(url):
            # local read-only scrape — justified
            # ktlint: disable=KTP002
            return urllib.request.urlopen(url)

        def c(url):
            return urllib.request.urlopen(url)  # ktlint: disable=KTP001
        """}, rules=["KTP002"])
    # a + b suppressed; c's disable names the WRONG code, so it fails
    assert len(res.suppressed) == 2
    assert [f.line for f in res.active] == [13]


# -- baseline ratchet --------------------------------------------------------

TWO_URLOPEN = """
    import urllib.request

    def a(url):
        return urllib.request.urlopen(url)

    def b(url):
        return urllib.request.urlopen(url)
    """


def test_baseline_absorbs_up_to_budget_and_ratchets(tmp_path):
    files = {"kubetpu/cli/thing.py": TWO_URLOPEN}
    bare = lint(tmp_path, files, rules=["KTP002"])
    assert len(bare.active) == 2

    # write the baseline from the bare run: both findings become debt
    bl_path = str(tmp_path / "lint_baseline.json")
    data = baseline_mod.write_baseline(bl_path, bare.findings)
    assert data["counts"] == {"kubetpu/cli/thing.py::KTP002": 2}

    # same tree + baseline: clean (ratcheted, not blocking)
    again = lint(tmp_path, files, rules=["KTP002"],
                 baseline=baseline_mod.load_baseline(bl_path))
    assert again.active == [] and len(again.baselined) == 2

    # a THIRD violation exceeds the budget: exactly one new finding
    files3 = {"kubetpu/cli/thing.py": textwrap.dedent(TWO_URLOPEN)
              + "\ndef c(url):\n    return urllib.request.urlopen(url)\n"}
    worse = lint(tmp_path, files3, rules=["KTP002"],
                 baseline=baseline_mod.load_baseline(bl_path))
    assert len(worse.active) == 1 and len(worse.baselined) == 2


def test_baseline_reports_paid_down_debt_as_stale(tmp_path):
    baseline = {"version": 1, "counts": {"kubetpu/cli/thing.py::KTP002": 5}}
    res = lint(tmp_path, {"kubetpu/cli/thing.py": TWO_URLOPEN},
               rules=["KTP002"], baseline=baseline)
    assert res.active == []
    stale = baseline_mod.stale_keys(res.findings, baseline)
    assert stale == {"kubetpu/cli/thing.py::KTP002": 3}


def test_baseline_rejects_wrong_version(tmp_path):
    p = tmp_path / "bl.json"
    p.write_text(json.dumps({"version": 99, "counts": {}}))
    with pytest.raises(ValueError):
        baseline_mod.load_baseline(str(p))


# -- CLI surface -------------------------------------------------------------


def test_cli_json_format_and_exit_codes(tmp_path, capsys):
    root = make_tree(tmp_path, {"kubetpu/cli/thing.py": TWO_URLOPEN})
    rc = lint_main(["--root", root, "--no-baseline", "--format", "json",
                    "--rules", "KTP002", "kubetpu"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["new"] == 2 and out["counts"] == {"KTP002": 2}
    assert {f["code"] for f in out["findings"]} == {"KTP002"}
    assert any(r["code"] == "KTP002" for r in out["rules"])

    clean_root = make_tree(tmp_path / "clean",
                           {"kubetpu/cli/ok.py": "x = 1\n"})
    rc = lint_main(["--root", clean_root, "--no-baseline",
                    "--format", "json", "kubetpu"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["new"] == 0


def test_cli_write_baseline_round_trip(tmp_path, capsys):
    root = make_tree(tmp_path, {"kubetpu/cli/thing.py": TWO_URLOPEN})
    bl = os.path.join(root, "lint_baseline.json")
    # a SCOPED write-baseline would silently drop out-of-scope budget:
    # refused outright
    assert lint_main(["--root", root, "--baseline", bl,
                      "--write-baseline", "kubetpu"]) == 2
    assert lint_main(["--root", root, "--baseline", bl,
                      "--write-baseline", "--rules", "KTP002"]) == 2
    # the full default run regenerates
    assert lint_main(["--root", root, "--baseline", bl,
                      "--write-baseline"]) == 0
    capsys.readouterr()
    # with the ratchet in place the same tree now exits 0
    assert lint_main(["--root", root, "--baseline", bl, "kubetpu"]) == 0
    # but ignoring it fails
    assert lint_main(["--root", root, "--no-baseline", "kubetpu"]) == 1


def test_cli_list_rules_covers_catalog(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in ("KTP001", "KTP002", "KTP003", "KTP004", "KTP005",
                 "KTP006"):
        assert code in out


# -- request_text (the migration the lint forced) ----------------------------


def test_request_text_rides_the_shared_client():
    from kubetpu.obs.exporter import MetricsServer
    from kubetpu.obs.registry import Registry, default_registry
    from kubetpu.wire.httpcommon import NO_RETRY, request_text

    reg = Registry()
    reg.counter("kubetpu_widget_total").inc(3)
    server = MetricsServer({"replica0": reg})
    server.start()
    try:
        before = default_registry().counter(
            "kubetpu_wire_requests_total").value
        text = request_text(server.address + "/metrics", timeout=5,
                            retry=NO_RETRY)
        assert 'kubetpu_widget_total' in text
        # the scrape rode the shared client: the wire counter moved
        after = default_registry().counter(
            "kubetpu_wire_requests_total").value
        assert after == before + 1
    finally:
        server.shutdown()


# -- the meta-test: this repo lints clean ------------------------------------


def test_repo_lints_clean_against_committed_baseline():
    """`make lint` green is a merge gate; this pins it in tier-1. Any
    new violation of KTP001–KTP006 in kubetpu/ or scripts/ fails here
    at the offending path:line unless it carries a justified inline
    disable or the (shrink-only) baseline covers it."""
    bl_path = os.path.join(REPO_ROOT, baseline_mod.DEFAULT_BASELINE)
    baseline = baseline_mod.load_baseline(bl_path)
    res = run_lint(REPO_ROOT, ["kubetpu", "scripts"], baseline=baseline)
    assert [f.render() for f in res.active] == []
    # the ratchet only ever shrinks: every budgeted finding must still
    # exist, otherwise the baseline is stale and must be regenerated
    assert baseline_mod.stale_keys(res.findings, baseline) == {}
