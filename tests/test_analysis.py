"""Round-12: the static invariant linter (`kubetpu.analysis`).

Fixture-driven per rule (one violating + one clean snippet each),
suppression + baseline-ratchet mechanics, the CLI's JSON surface, the
new `httpcommon.request_text` wire path the migrations ride, and the
meta-test: the repo itself lints clean against the committed baseline.
"""

import json
import os
import textwrap

import pytest

from kubetpu.analysis import baseline as baseline_mod
from kubetpu.analysis.cli import main as lint_main
from kubetpu.analysis.core import all_rules, run_lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def lint(tmp_path, files, rules=None, baseline=None):
    root = make_tree(tmp_path, files)
    picked = None
    if rules is not None:
        want = set(rules)
        picked = [r for r in all_rules() if r.code in want]
        assert {r.code for r in picked} == want
    return run_lint(root, ["."], rules=picked, baseline=baseline)


def codes(result):
    return [f.code for f in result.active]


# -- KTP001 hot-path-sync ----------------------------------------------------

HOT_VIOLATING = """
    class Server:
        def step(self):
            return self._advance()

        def _advance(self):
            vals = jnp.asarray(self.host_buf)      # upload in the hot loop
            return vals.tolist()                   # and a sync
    """

HOT_CLEAN = """
    class Server:
        def step(self):
            return self._advance()

        def _advance(self):
            return self._step_fn(self.cache)

        def warmup(self):
            # barrier leg: uploads here are by design
            jnp.asarray([0])
    """


def test_hotpath_flags_sync_reachable_from_step(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/serving.py": HOT_VIOLATING},
               rules=["KTP001"])
    assert codes(res) == ["KTP001", "KTP001"]
    msgs = [f.message for f in res.active]
    assert any("jnp.asarray" in m for m in msgs)
    assert any(".tolist()" in m for m in msgs)


def test_hotpath_clean_and_barriers_exempt(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/serving.py": HOT_CLEAN},
               rules=["KTP001"])
    assert res.active == []


def test_hotpath_follows_inheritance_across_modules(tmp_path):
    # base step() in serving.py, the offending override lives in paged.py
    # — the closure must flatten the hierarchy across files
    res = lint(tmp_path, {
        "kubetpu/jobs/serving.py": """
            class SlotServerBase:
                def step(self):
                    return self._device_step()

                def _device_step(self):
                    raise NotImplementedError
            """,
        "kubetpu/jobs/paged.py": """
            from kubetpu.jobs.serving import SlotServerBase

            class PagedDecodeServer(SlotServerBase):
                def _device_step(self):
                    return self.tokens.item()
            """,
    }, rules=["KTP001"])
    assert [(f.path, f.code) for f in res.active] == [
        ("kubetpu/jobs/paged.py", "KTP001")]


def test_hotpath_ignores_cold_modules(tmp_path):
    # same code outside the hot modules: not serving's step, no finding
    res = lint(tmp_path, {"kubetpu/jobs/train.py": HOT_VIOLATING},
               rules=["KTP001"])
    assert res.active == []


# -- KTP002 wire-hygiene -----------------------------------------------------


def test_wire_flags_raw_urlopen_and_naked_post(tmp_path):
    res = lint(tmp_path, {"kubetpu/cli/thing.py": """
        import urllib.request
        from kubetpu.wire.httpcommon import request_json

        def scrape(url):
            with urllib.request.urlopen(url) as r:   # raw socket
                return r.read()

        def submit(url, pod):
            return request_json(url + "/pods", {"pod": pod})  # naked POST
        """}, rules=["KTP002"])
    assert codes(res) == ["KTP002", "KTP002"]
    assert "urlopen" in res.active[0].message
    assert "idempotency_key" in res.active[1].message


def test_wire_clean_sites_pass(tmp_path):
    res = lint(tmp_path, {
        # the one module allowed to urlopen: the shared client itself
        "kubetpu/wire/httpcommon.py": """
            import urllib.request

            def request_json(url):
                with urllib.request.urlopen(url) as r:
                    return r.read()
            """,
        "kubetpu/cli/thing.py": """
            from kubetpu.wire.httpcommon import request_json

            def ok(url, pod, key):
                request_json(url, {"pod": pod}, idempotency_key=key)
                request_json(url + "/pods/p0")            # GET
                request_json(url, method="DELETE")        # idempotent verb
            """,
    }, rules=["KTP002"])
    assert res.active == []


# -- KTP003 lock-discipline --------------------------------------------------

LOCK_VIOLATING = """
    import threading

    class Registry:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = {}

        def add(self, k):
            with self._lock:
                self.items[k] = 1

        def clear(self):
            self.items = {}          # unguarded write to guarded state
    """


def test_lock_flags_unguarded_write(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/reg2.py": LOCK_VIOLATING},
               rules=["KTP003"])
    assert codes(res) == ["KTP003"]
    assert "self.items" in res.active[0].message


def test_lock_clean_under_lock_and_locked_convention(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/reg2.py": """
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = {}

            def add(self, k):
                with self._lock:
                    self.items[k] = 1

            def clear(self):
                with self._lock:
                    self.items = {}

            def _evict_locked(self, k):
                # caller holds the lock (project convention)
                del self.items[k]
        """}, rules=["KTP003"])
    assert res.active == []


# -- KTP004 metric-hygiene ---------------------------------------------------


def test_metric_flags_fstring_grammar_and_counter_suffix(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/thing.py": """
        def setup(reg, name):
            reg.counter(f"kubetpu_{name}_total").inc()   # unbounded
            reg.counter("kubetpu_requests")              # not *_total
            reg.gauge("badprefix_depth")                 # wrong grammar
            reg.histogram(name)                          # non-literal
        """}, rules=["KTP004"])
    assert codes(res) == ["KTP004"] * 4


def test_metric_clean_names_pass(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/thing.py": """
        def setup(reg):
            reg.counter("kubetpu_requests_total").inc()
            reg.gauge("kubetpu_queue_depth").set(0)
            reg.histogram("kubetpu_ttft_seconds", op="serve")
        """}, rules=["KTP004"])
    assert res.active == []


# -- KTP005 determinism ------------------------------------------------------


def test_determinism_flags_wall_clock_and_stdlib_random(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/widget.py": """
        import random
        import time

        def pick(xs):
            t = time.time()
            return random.choice(xs), t
        """}, rules=["KTP005"])
    assert codes(res) == ["KTP005", "KTP005"]


def test_determinism_allows_seeded_and_monotonic(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/widget.py": """
        import time

        def pick(xs, rng, key):
            t0 = time.perf_counter()
            a = np.random.RandomState(0).permutation(len(xs))
            b = jax.random.fold_in(key, 3)
            return a, b, time.monotonic() - t0
        """}, rules=["KTP005"])
    assert res.active == []


def test_determinism_scoped_to_jobs(tmp_path):
    # obs/wire legitimately read wall clock (timestamps, TTLs)
    res = lint(tmp_path, {"kubetpu/obs/clock.py": """
        import time

        def now():
            return time.time()
        """}, rules=["KTP005"])
    assert res.active == []


# -- KTP006 jit-leg-hygiene --------------------------------------------------


def test_jit_flags_in_loop_and_step_closure(tmp_path):
    res = lint(tmp_path, {
        "kubetpu/jobs/legs.py": """
            def compile_all(fns):
                legs = []
                for fn in fns:
                    legs.append(jax.jit(fn))      # fresh leg per iteration
                return legs
            """,
        "kubetpu/jobs/serving.py": """
            class Server:
                def step(self):
                    return self._advance()

                def _advance(self):
                    return jax.jit(self._fn)(self.cache)   # per-step jit
            """,
    }, rules=["KTP006"])
    got = sorted((f.path, f.code) for f in res.active)
    assert got == [("kubetpu/jobs/legs.py", "KTP006"),
                   ("kubetpu/jobs/serving.py", "KTP006")]


def test_jit_flags_decorator_and_comprehension_in_loop(tmp_path):
    # the def's body runs later, but its DECORATORS evaluate per loop
    # iteration — a fresh leg each time; comprehensions are loops too
    res = lint(tmp_path, {"kubetpu/jobs/legs.py": """
        from functools import partial

        def per_gamma(fns, gammas):
            legs = []
            for g in gammas:
                @partial(jax.jit, static_argnums=(0,))
                def leg(cache):
                    return cache
                legs.append(leg)
            return legs

        def all_at_once(fns):
            return [jax.jit(f) for f in fns]
        """}, rules=["KTP006"])
    assert codes(res) == ["KTP006", "KTP006"]
    assert all("inside a loop" in f.message for f in res.active)


def test_jit_clean_factory_passes(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/legs.py": """
        from functools import partial

        def make_leg(fn):
            @partial(jax.jit, donate_argnums=(0,))
            def leg(cache, tok):
                return fn(cache, tok)
            return leg
        """}, rules=["KTP006"])
    assert res.active == []


# -- KTP007 implicit-sync taint (Round-13) -----------------------------------

TAINT_VIOLATING = """
    class Server:
        def step(self):
            return self._advance()

        def _advance(self):
            mask = jnp.greater(self.pos, 0)
            if mask.any():                    # branch on a device value
                n = int(jnp.sum(mask))        # int() on a device value
            vals = self._dev("active", lambda: self.active)
            for v in vals:                    # iterating a device mirror
                pass
            return f"active={vals}"           # f-string materializes
    """

TAINT_CLEAN = """
    class Server:
        def step(self):
            return self._advance()

        def _advance(self):
            mask = jnp.greater(self.pos, 0)
            host = np.asarray(mask)           # KTP001's finding, not 007's
            if host.any():                    # host array: no implicit sync
                n = int(host.sum())
            if self.active.any():             # plain host state
                pass
            k = len(self.host_list)
            return k
    """


def test_taint_flags_implicit_syncs_in_step_closure(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/serving.py": TAINT_VIOLATING},
               rules=["KTP007"])
    whats = [f.message.split(":")[1].split(" on ")[0].strip()
             for f in res.active]
    assert codes(res) == ["KTP007"] * 4
    assert whats == ["branch condition", "`int()`", "iteration",
                     "f-string interpolation"]


def test_taint_clean_after_sanitizer_and_host_state(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/serving.py": TAINT_CLEAN},
               rules=["KTP007"])
    assert res.active == []


def test_taint_survives_branch_join_and_loop_back_edge(tmp_path):
    # taint assigned in ONE branch must survive the join (may-analysis);
    # taint created in a loop body must reach the loop HEADER via the
    # back edge — both are flow facts a per-line matcher cannot see
    res = lint(tmp_path, {"kubetpu/jobs/serving.py": """
        class Server:
            def step(self):
                x = self.host
                if self.flag:
                    x = jnp.ones(3)
                if x.any():                  # tainted via one branch only
                    pass
                y = self.host
                while y.any():               # tainted via the back edge
                    y = jnp.cumsum(y)
        """}, rules=["KTP007"])
    assert [f.line for f in res.active] == [7, 10]


def test_taint_cleared_by_reassignment(tmp_path):
    res = lint(tmp_path, {"kubetpu/jobs/serving.py": """
        class Server:
            def step(self):
                x = jnp.ones(3)
                x = self.host_list
                if x:                        # strong update killed the taint
                    pass
        """}, rules=["KTP007"])
    assert res.active == []


def test_taint_ignores_jitted_inner_defs(tmp_path):
    # a nested def in the closure is a traced leg: its body cannot
    # host-sync mid-trace, so device-value branches there are legal
    res = lint(tmp_path, {"kubetpu/jobs/serving.py": """
        class Server:
            def step(self):
                def leg(cache):
                    m = jnp.greater(cache, 0)
                    return jnp.where(m, cache, 0)
                return self._legs["step"](self.cache)
        """}, rules=["KTP007"])
    assert res.active == []


# -- KTP008 lock-order deadlock graph (Round-13) ------------------------------

THREE_LOCK_CYCLE = """
    import threading

    class A:
        def __init__(self):
            self._lock = threading.Lock()
            self.b = B()

        def fwd(self):
            with self._lock:
                self.b.fwd()

    class B:
        def __init__(self):
            self._lock = threading.Lock()
            self.c = C()

        def fwd(self):
            with self._lock:
                self.c.poke()

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.a = A()

        def poke(self):
            with self._lock:
                pass

        def back(self):
            with self._lock:
                self.a.fwd()
    """


def test_lock_order_flags_three_lock_cycle(tmp_path):
    res = lint(tmp_path, {"kubetpu/wire/locks.py": THREE_LOCK_CYCLE},
               rules=["KTP008"])
    cycles = [f for f in res.active if "lock-order cycle" in f.message]
    assert any("`A._lock`" in f.message and "`B._lock`" in f.message
               and "`C._lock`" in f.message for f in cycles)


def test_lock_order_flags_self_reacquisition_but_not_rlock(tmp_path):
    res = lint(tmp_path, {"kubetpu/wire/locks.py": """
        import threading

        class Plain:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.helper()

            def helper(self):
                with self._lock:
                    pass

        class Reentrant:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.helper()

            def helper(self):
                with self._lock:
                    pass
        """}, rules=["KTP008"])
    assert codes(res) == ["KTP008"]
    assert "Plain._lock" in res.active[0].message


def test_lock_order_clean_consistent_order_passes(tmp_path):
    # A -> B everywhere: a DAG, no finding (and *_locked callees that
    # take nothing themselves add no edges)
    res = lint(tmp_path, {"kubetpu/wire/locks.py": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def one(self):
                with self._lock:
                    self.b.poke()

            def two(self):
                with self._lock:
                    self._apply_locked()
                    self.b.poke()

            def _apply_locked(self):
                self.x = 1

        class B:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    pass
        """}, rules=["KTP008"])
    assert res.active == []


# -- KTP009 thread-escape (Round-13) ------------------------------------------

# the cross-module shape: the wire module embeds the handler and writes
# through the `srv = self` closure alias; the LOOP half (step) lives in
# a subclass in another module — the model must flatten the hierarchy
ESCAPE_WIRE = """
    import threading
    from http.server import BaseHTTPRequestHandler

    class ExporterBase:
        def __init__(self):
            self._lock = threading.Lock()
            self.paused = False
            self.limit = 0
            srv = self

            class Handler(BaseHTTPRequestHandler):
                def do_POST(self):
                    srv.paused = True          # unguarded handler write
                    with srv._lock:
                        srv.limit = 10         # guarded: clean

                def do_GET(self):
                    srv._bump()                # escapes via a server method
            srv.handler_cls = Handler

        def _bump(self):
            self.hits = self.hits + 1          # unguarded, handler-reached
    """

ESCAPE_JOBS = """
    from kubetpu.wire.exp import ExporterBase

    class StepExporter(ExporterBase):
        def step(self):
            if self.paused:                    # loop role reads the flag
                return None
            return self.hits + self.limit
    """


def test_thread_escape_flags_cross_module_handler_write(tmp_path):
    res = lint(tmp_path, {"kubetpu/wire/exp.py": ESCAPE_WIRE,
                          "kubetpu/jobs/stepper.py": ESCAPE_JOBS},
               rules=["KTP009"])
    attrs = sorted(f.message.split("`")[1] for f in res.active)
    # paused (direct write) + hits (via _bump); limit is lock-guarded
    assert codes(res) == ["KTP009", "KTP009"]
    assert attrs == ["ExporterBase.hits", "ExporterBase.paused"]
    assert all("wire-handler thread" in f.message for f in res.active)


def test_thread_escape_clean_when_locked_or_unread(tmp_path):
    res = lint(tmp_path, {"kubetpu/wire/exp.py": """
        import threading
        from http.server import BaseHTTPRequestHandler

        class Exporter:
            def __init__(self):
                self._lock = threading.Lock()
                self.limit = 0
                srv = self

                class Handler(BaseHTTPRequestHandler):
                    def do_POST(self):
                        with srv._lock:
                            srv.limit = 10     # guarded
                        srv.stats = {}         # never read by the loop

            def step(self):
                return self.limit
        """}, rules=["KTP009"])
    assert res.active == []


# -- KTP010 resource safety (Round-13) ----------------------------------------


def test_resource_flags_early_return_leak_and_never_closed(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/sink2.py": """
        def leak(path, cond):
            fh = open(path)
            if cond:
                return None              # fh leaks out of scope open
            fh.close()

        def never(path):
            fh = open(path)
            fh.write("x")

        def dropped(path):
            open(path)                   # no handle at all
        """}, rules=["KTP010"])
    assert codes(res) == ["KTP010"] * 3
    assert "leaks across the early exit" in res.active[0].message
    assert "never closed" in res.active[1].message
    assert "immediately dropped" in res.active[2].message


def test_resource_close_only_in_except_does_not_cover_normal_path(tmp_path):
    # an except handler runs only on the raising path; a close that
    # lives nowhere else leaves the handle open on every normal exit
    # (a finally-close, by contrast, covers every path)
    res = lint(tmp_path, {"kubetpu/obs/sink3.py": """
        def except_only(path):
            fh = open(path)
            try:
                risky()
            except ValueError:
                fh.close()
            return fh.read()

        def ok_finally(path):
            fh = open(path)
            try:
                risky()
            finally:
                fh.close()
            return 0
        """}, rules=["KTP010"])
    assert codes(res) == ["KTP010"]
    assert "only the exception path closes it" in res.active[0].message


def test_resource_bind_then_with_is_managed(tmp_path):
    # `f = open(...)` then `with f:` delegates the close to __exit__ —
    # managed, not a leak; but an early exit BEFORE the with still is
    res = lint(tmp_path, {"kubetpu/obs/sink5.py": """
        def ok_bind_then_with(path):
            f = open(path)
            with f:
                return f.read()

        def leak_before_with(path, cond):
            f = open(path)
            if cond:
                return None
            with f:
                return f.read()
        """}, rules=["KTP010"])
    assert codes(res) == ["KTP010"]
    assert res.active[0].line == 8
    assert "leaks across the early exit" in res.active[0].message


def test_resource_clean_with_finally_escape_and_scope(tmp_path):
    res = lint(tmp_path, {
        "kubetpu/obs/sink2.py": """
            def ok_with(path):
                with open(path) as fh:
                    return fh.read()

            def ok_finally(path, cond):
                fh = open(path)
                try:
                    if cond:
                        return None
                finally:
                    fh.close()

            def ok_escape_self(self, path):
                new_sink = open(path, "a")
                self._sink = new_sink        # ownership moves to the object

            def ok_return(path):
                return open(path)
            """,
        # jobs/ is out of scope for KTP010 (checkpoint IO has its own
        # atomic-rename discipline)
        "kubetpu/jobs/ckpt2.py": """
            def raw(path):
                fh = open(path)
                fh.write("x")
            """,
    }, rules=["KTP010"])
    assert res.active == []


# -- KTP004 bounded-f-string proof (Round-13 refinement) ----------------------


def test_metric_fstring_over_literal_tuple_is_proven(tmp_path):
    res = lint(tmp_path, {"kubetpu/obs/thing2.py": """
        def setup(reg):
            for key in ("a", "b"):
                reg.counter(f"kubetpu_agent_{key}_total")    # provable

        def bad(reg):
            for key in ("a", "B!"):
                reg.counter(f"kubetpu_agent_{key}_total")    # bad expansion

        def unbounded(reg, key):
            reg.counter(f"kubetpu_agent_{key}_total")        # parameter
        """}, rules=["KTP004"])
    msgs = [f.message for f in res.active]
    assert len(msgs) == 2
    assert "kubetpu_agent_B!_total" in msgs[0]    # the expansion, by name
    assert "unbounded series cardinality" in msgs[1]


def test_metric_fstring_proof_voided_by_rebound_loop_var(tmp_path):
    # a rebind inside the loop (assignment, or an inner non-literal for
    # shadowing the name) means the literal tuple no longer vouches for
    # the interpolated value — the proof must refuse, not validate the
    # wrong name set
    res = lint(tmp_path, {"kubetpu/obs/thing3.py": """
        def reassigned(reg, dyn):
            for key in ("a", "b"):
                key = dyn[key]
                reg.counter(f"kubetpu_agent_{key}_total")

        def shadowed(reg, runtime_list):
            for key in ("a", "b"):
                for key in runtime_list():
                    reg.counter(f"kubetpu_agent_{key}_total")
        """}, rules=["KTP004"])
    assert codes(res) == ["KTP004", "KTP004"]
    assert all("unbounded series cardinality" in f.message
               for f in res.active)


# -- CFG/taint engine unit tests (synthetic functions) ------------------------


def _taint_envs(src, source_names=("taint",)):
    import ast as ast_mod

    from kubetpu.analysis.core import call_name
    from kubetpu.analysis.flow import TaintEngine

    tree = ast_mod.parse(textwrap.dedent(src))
    func = tree.body[0]
    eng = TaintEngine(lambda c: call_name(c) in source_names)
    return func, eng, eng.run(func)


def test_cfg_branches_union_at_join():
    func, eng, before = _taint_envs("""
        def f(cond):
            x = 1
            if cond:
                x = taint()
            else:
                y = 2
            return x
        """)
    ret = func.body[-1]
    assert "x" in before[id(ret)]


def test_cfg_loop_back_edge_propagates():
    func, eng, before = _taint_envs("""
        def f(n):
            x = 1
            while n:
                use(x)
                x = taint()
            return x
        """)
    use_stmt = func.body[1].body[0]
    # on the second iteration `x` arrives tainted at the loop body head
    assert "x" in before[id(use_stmt)]
    assert "x" in before[id(func.body[-1])]


def test_cfg_try_except_reaches_handler_mid_body():
    func, eng, before = _taint_envs("""
        def f():
            try:
                x = taint()
                risky()
            except ValueError:
                use(x)
            return 0
        """)
    handler_use = func.body[0].handlers[0].body[0]
    assert "x" in before[id(handler_use)]


def test_cfg_break_skips_loop_tail():
    func, eng, before = _taint_envs("""
        def f(n):
            x = 1
            for i in range(n):
                if i:
                    break
                x = taint()
            return x
        """)
    assert "x" in before[id(func.body[-1])]


def test_taint_strong_update_kills():
    func, eng, before = _taint_envs("""
        def f():
            x = taint()
            x = 1
            return x
        """)
    assert "x" not in before[id(func.body[-1])]


def test_cfg_handler_sees_taint_killed_later_in_try_body():
    # risky() can raise while x is still the device value; the kill on
    # the NEXT line must not launder the handler's view (exceptional
    # edges carry the union of the try body's intermediate states)
    func, eng, before = _taint_envs("""
        def f():
            try:
                x = taint()
                risky()
                x = 1
            except ValueError:
                use(x)
            return x
        """)
    handler_use = func.body[0].handlers[0].body[0]
    assert "x" in before[id(handler_use)]
    # and the may-analysis unions at the post-try join: the handler
    # path reaches the return with x still tainted
    assert "x" in before[id(func.body[-1])]


def test_cfg_handler_edge_covers_try_bodys_leading_statements():
    # with a COMPOUND statement in the try body, the leading simple
    # statements live in the body's entry block — the exceptional edge
    # must include that block too, or the kill there launders the
    # handler's view of the leading taint
    func, eng, before = _taint_envs("""
        def f(c):
            try:
                x = taint()
                risky()
                x = 1
                if c:
                    pass
            except ValueError:
                use(x)
            return 0
        """)
    handler_use = func.body[0].handlers[0].body[0]
    assert "x" in before[id(handler_use)]


def test_lock_order_ignores_nested_defs_under_lock(tmp_path):
    # a callback DEFINED under the lock runs later, on another call
    # path — charging its acquisitions to the enclosing method would
    # fabricate an A->B edge (and, with B->A elsewhere, a phantom
    # deadlock cycle) that cannot happen
    res = lint(tmp_path, {"kubetpu/wire/locks.py": """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def register(self):
                with self._lock:
                    def cb():
                        self.b.poke()
                    self.cbs.append(cb)

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self.a = A()

            def poke(self):
                with self._lock:
                    pass

            def back(self):
                with self._lock:
                    self.a.noop()
        """}, rules=["KTP008"])
    assert res.active == []


# -- suppressions ------------------------------------------------------------


def test_inline_suppression_trailing_and_line_above(tmp_path):
    res = lint(tmp_path, {"kubetpu/cli/thing.py": """
        import urllib.request

        def a(url):
            return urllib.request.urlopen(url)  # ktlint: disable=KTP002

        def b(url):
            # local read-only scrape — justified
            # ktlint: disable=KTP002
            return urllib.request.urlopen(url)

        def c(url):
            return urllib.request.urlopen(url)  # ktlint: disable=KTP001
        """}, rules=["KTP002"])
    # a + b suppressed; c's disable names the WRONG code, so it fails
    assert len(res.suppressed) == 2
    assert [f.line for f in res.active] == [13]


# -- baseline ratchet --------------------------------------------------------

TWO_URLOPEN = """
    import urllib.request

    def a(url):
        return urllib.request.urlopen(url)

    def b(url):
        return urllib.request.urlopen(url)
    """


def test_baseline_absorbs_up_to_budget_and_ratchets(tmp_path):
    files = {"kubetpu/cli/thing.py": TWO_URLOPEN}
    bare = lint(tmp_path, files, rules=["KTP002"])
    assert len(bare.active) == 2

    # write the baseline from the bare run: both findings become debt
    bl_path = str(tmp_path / "lint_baseline.json")
    data = baseline_mod.write_baseline(bl_path, bare.findings)
    assert data["counts"] == {"kubetpu/cli/thing.py::KTP002": 2}

    # same tree + baseline: clean (ratcheted, not blocking)
    again = lint(tmp_path, files, rules=["KTP002"],
                 baseline=baseline_mod.load_baseline(bl_path))
    assert again.active == [] and len(again.baselined) == 2

    # a THIRD violation exceeds the budget: exactly one new finding
    files3 = {"kubetpu/cli/thing.py": textwrap.dedent(TWO_URLOPEN)
              + "\ndef c(url):\n    return urllib.request.urlopen(url)\n"}
    worse = lint(tmp_path, files3, rules=["KTP002"],
                 baseline=baseline_mod.load_baseline(bl_path))
    assert len(worse.active) == 1 and len(worse.baselined) == 2


def test_baseline_reports_paid_down_debt_as_stale(tmp_path):
    baseline = {"version": 1, "counts": {"kubetpu/cli/thing.py::KTP002": 5}}
    res = lint(tmp_path, {"kubetpu/cli/thing.py": TWO_URLOPEN},
               rules=["KTP002"], baseline=baseline)
    assert res.active == []
    stale = baseline_mod.stale_keys(res.findings, baseline)
    assert stale == {"kubetpu/cli/thing.py::KTP002": 3}


def test_baseline_rejects_wrong_version(tmp_path):
    p = tmp_path / "bl.json"
    p.write_text(json.dumps({"version": 99, "counts": {}}))
    with pytest.raises(ValueError):
        baseline_mod.load_baseline(str(p))


# -- CLI surface -------------------------------------------------------------


def test_cli_json_format_and_exit_codes(tmp_path, capsys):
    root = make_tree(tmp_path, {"kubetpu/cli/thing.py": TWO_URLOPEN})
    rc = lint_main(["--root", root, "--no-baseline", "--format", "json",
                    "--rules", "KTP002", "kubetpu"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["new"] == 2 and out["counts"] == {"KTP002": 2}
    assert {f["code"] for f in out["findings"]} == {"KTP002"}
    assert any(r["code"] == "KTP002" for r in out["rules"])

    clean_root = make_tree(tmp_path / "clean",
                           {"kubetpu/cli/ok.py": "x = 1\n"})
    rc = lint_main(["--root", clean_root, "--no-baseline",
                    "--format", "json", "kubetpu"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["new"] == 0


def test_cli_write_baseline_round_trip(tmp_path, capsys):
    root = make_tree(tmp_path, {"kubetpu/cli/thing.py": TWO_URLOPEN})
    bl = os.path.join(root, "lint_baseline.json")
    # a SCOPED write-baseline would silently drop out-of-scope budget:
    # refused outright
    assert lint_main(["--root", root, "--baseline", bl,
                      "--write-baseline", "kubetpu"]) == 2
    assert lint_main(["--root", root, "--baseline", bl,
                      "--write-baseline", "--rules", "KTP002"]) == 2
    # the full default run regenerates
    assert lint_main(["--root", root, "--baseline", bl,
                      "--write-baseline"]) == 0
    capsys.readouterr()
    # with the ratchet in place the same tree now exits 0
    assert lint_main(["--root", root, "--baseline", bl, "kubetpu"]) == 0
    # but ignoring it fails
    assert lint_main(["--root", root, "--no-baseline", "kubetpu"]) == 1


def test_cli_list_rules_covers_catalog(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in ("KTP001", "KTP002", "KTP003", "KTP004", "KTP005",
                 "KTP006", "KTP007", "KTP008", "KTP009", "KTP010"):
        assert code in out


def test_cli_github_format_emits_annotations(tmp_path, capsys):
    root = make_tree(tmp_path, {"kubetpu/cli/thing.py": TWO_URLOPEN})
    rc = lint_main(["--root", root, "--no-baseline", "--format", "github",
                    "--rules", "KTP002", "kubetpu"])
    out = capsys.readouterr().out
    assert rc == 1
    lines = [ln for ln in out.splitlines() if ln.startswith("::error")]
    assert len(lines) == 2
    assert "file=kubetpu/cli/thing.py" in lines[0]
    assert "title=KTP002" in lines[0]


def test_cli_fail_stale_turns_nudge_into_failure(tmp_path, capsys):
    root = make_tree(tmp_path, {"kubetpu/cli/ok.py": "x = 1\n"})
    bl = tmp_path / "lint_baseline.json"
    bl.write_text(json.dumps({
        "version": 1,
        "counts": {"kubetpu/cli/gone.py::KTP002": 3},
    }))
    # default: stale baseline only nudges (full default-path run)
    assert lint_main(["--root", root, "--baseline", str(bl)]) == 0
    # CI mode (what scripts/lint.py injects): stale FAILS
    assert lint_main(["--root", root, "--baseline", str(bl),
                      "--fail-stale"]) == 1
    assert "stale" in capsys.readouterr().err
    # an explicitly-pathed run is SCOPED — staleness is undecidable
    # there, so it must not fail (mirrors the --write-baseline refusal)
    assert lint_main(["--root", root, "--baseline", str(bl),
                      "--fail-stale", "kubetpu"]) == 0
    # ...but staleness is only decidable over the FULL finding set: a
    # --rules scope sees a slice, so every out-of-scope key would read
    # as paid down and a clean tree would spuriously fail
    assert lint_main(["--root", root, "--baseline", str(bl),
                      "--fail-stale", "--rules", "KTP004"]) == 0
    # --changed-only still LINTS the full default paths (it filters the
    # report), so staleness stays exact and must still fail
    assert lint_main(["--root", root, "--baseline", str(bl),
                      "--fail-stale", "--changed-only"]) == 1


def test_cli_changed_only_scopes_the_report(tmp_path, capsys):
    import subprocess

    root = make_tree(tmp_path, {
        "kubetpu/cli/old.py": textwrap.dedent(TWO_URLOPEN),
        "kubetpu/cli/clean.py": "x = 1\n",
    })
    env_git = ["git", "-C", root, "-c", "user.email=t@t", "-c",
               "user.name=t"]
    subprocess.run(["git", "-C", root, "init", "-q"], check=True)
    subprocess.run(env_git + ["add", "-A"], check=True)
    subprocess.run(env_git + ["commit", "-qm", "seed"], check=True)
    # untouched tree: the committed violations exist but nothing changed,
    # so --changed-only passes (the full run still fails)
    assert lint_main(["--root", root, "--no-baseline", "--rules", "KTP002",
                      "kubetpu"]) == 1
    capsys.readouterr()
    assert lint_main(["--root", root, "--no-baseline", "--changed-only",
                      "--rules", "KTP002", "kubetpu"]) == 0
    capsys.readouterr()
    # a NEW (untracked) violating file is in the changed set and fails
    (tmp_path / "kubetpu/cli/fresh.py").write_text(
        textwrap.dedent(TWO_URLOPEN))
    assert lint_main(["--root", root, "--no-baseline", "--changed-only",
                      "--rules", "KTP002", "kubetpu"]) == 1
    out = capsys.readouterr().out
    assert "fresh.py" in out and "old.py" not in out


def test_cli_changed_only_reroots_when_project_is_a_git_subdir(tmp_path,
                                                               capsys):
    # git prints toplevel-relative paths; findings are lint-root-relative
    # — when the project is vendored a level below the checkout root the
    # changed set must be re-rooted or the gate silently passes
    import subprocess

    subprocess.run(["git", "-C", str(tmp_path), "init", "-q"], check=True)
    root = make_tree(tmp_path / "vendor" / "proj",
                     {"kubetpu/cli/clean.py": "x = 1\n"})
    env_git = ["git", "-C", str(tmp_path), "-c", "user.email=t@t", "-c",
               "user.name=t"]
    subprocess.run(env_git + ["add", "-A"], check=True)
    subprocess.run(env_git + ["commit", "-qm", "seed"], check=True)
    (tmp_path / "vendor/proj/kubetpu/cli/fresh.py").write_text(
        textwrap.dedent(TWO_URLOPEN))
    assert lint_main(["--root", root, "--no-baseline", "--changed-only",
                      "--rules", "KTP002", "kubetpu"]) == 1
    assert "fresh.py" in capsys.readouterr().out


# -- request_text (the migration the lint forced) ----------------------------


def test_request_text_rides_the_shared_client():
    from kubetpu.obs.exporter import MetricsServer
    from kubetpu.obs.registry import Registry, default_registry
    from kubetpu.wire.httpcommon import NO_RETRY, request_text

    reg = Registry()
    reg.counter("kubetpu_widget_total").inc(3)
    server = MetricsServer({"replica0": reg})
    server.start()
    try:
        before = default_registry().counter(
            "kubetpu_wire_requests_total").value
        text = request_text(server.address + "/metrics", timeout=5,
                            retry=NO_RETRY)
        assert 'kubetpu_widget_total' in text
        # the scrape rode the shared client: the wire counter moved
        after = default_registry().counter(
            "kubetpu_wire_requests_total").value
        assert after == before + 1
    finally:
        server.shutdown()


# -- the meta-test: this repo lints clean ------------------------------------


def test_hot_closure_covers_kernel_dispatch_and_ops_lints_clean():
    """Round-15 pins: (a) the KTP001 barrier-leg closure reaches the new
    kernel dispatch fns — the paged server's per-step kernel bookkeeping
    and the speculative server's per-gamma round-leg fetch both run
    inside step(), so a host sync sneaking into either fails lint at the
    line; (b) `kubetpu/ops/` (the Pallas kernel family the dispatch
    hands off to) lints clean with ZERO baseline entries — new kernel
    code may never ride in on a ratchet budget."""
    from kubetpu.analysis.core import load_project
    from kubetpu.analysis.rules_device import hot_closure

    project = load_project(REPO_ROOT, ["kubetpu"])
    quals = {qual.split(".")[-1] if "." in qual else qual
             for _, qual, _ in hot_closure(project).values()}
    assert "_note_kernel_step" in quals, sorted(quals)
    assert "_round_leg" in quals, sorted(quals)
    res = run_lint(REPO_ROOT, ["kubetpu/ops"])
    assert [f.render() for f in res.active] == []
    baseline = baseline_mod.load_baseline(
        os.path.join(REPO_ROOT, baseline_mod.DEFAULT_BASELINE))
    assert not [k for k in baseline["counts"]
                if k.startswith("kubetpu/ops/")], baseline["counts"]


def test_migration_legs_are_barrier_legs():
    """Round-16 pin: the live-migration legs (snapshot/restore and
    their freeze/finish bookkeeping) are classified BARRIER legs —
    architecturally allowed to sync/upload (the handoff's device gather
    and page upload), and the KTP001 closure traversal stops at them.
    If one ever becomes reachable from step() WITHOUT barrier status,
    its np.asarray/device_get calls would fail lint at the line; this
    test keeps the classification explicit instead of incidental."""
    from kubetpu.analysis.core import load_project
    from kubetpu.analysis.rules_device import HOT_BARRIERS, hot_closure

    for leg in ("snapshot_slot", "restore_slot", "freeze_slot",
                "unfreeze_slot", "finish_migrated", "migratable_rids",
                "cancel_expired"):
        assert leg in HOT_BARRIERS, leg
    project = load_project(REPO_ROOT, ["kubetpu"])
    quals = {qual.split(".")[-1] if "." in qual else qual
             for _, qual, _ in hot_closure(project).values()}
    # barrier status means NOT in the step closure — the designed syncs
    # in snapshot/restore never read as hot-path syncs
    assert "snapshot_slot" not in quals
    assert "restore_slot" not in quals


def test_disagg_handoff_legs_are_barrier_legs(tmp_path):
    """Round-17 pin: the disaggregated-handoff legs the prefill
    streamer polls — the mid-prefill page-span gather
    (``snapshot_pages`` / ``_gather_page_span``) and the progress probe
    (``prefill_progress``) — are classified KTP001 BARRIER legs: their
    device gathers run on the handoff loop thread between steps, by
    design, and the closure traversal stops at them. The fixture pair
    proves the classification does real work: the same device sync is
    CLEAN behind the barrier name and VIOLATING behind a non-barrier
    one."""
    from kubetpu.analysis.core import load_project
    from kubetpu.analysis.rules_device import HOT_BARRIERS, hot_closure

    for leg in ("snapshot_pages", "_gather_page_span",
                "prefill_progress"):
        assert leg in HOT_BARRIERS, leg
    project = load_project(REPO_ROOT, ["kubetpu"])
    quals = {qual.split(".")[-1] if "." in qual else qual
             for _, qual, _ in hot_closure(project).values()}
    assert "snapshot_pages" not in quals
    assert "_gather_page_span" not in quals
    # violating: the SAME span gather reachable from step() under a
    # non-barrier name charges the step with its sync
    res = lint(tmp_path, {"kubetpu/jobs/paged.py": """
        class Server:
            def step(self):
                return self._stream_kv(0, 0, 2)

            def _stream_kv(self, rid, lo, hi):
                return np.asarray(self.k_pages[:, lo:hi])
        """}, rules=["KTP001"])
    assert codes(res) == ["KTP001"]
    # clean: behind the barrier classification the traversal stops —
    # the designed gather never reads as a hot-path sync
    res = lint(tmp_path / "clean", {"kubetpu/jobs/paged.py": """
        class Server:
            def step(self):
                return self.snapshot_pages(0, 0, 2)

            def snapshot_pages(self, rid, lo, hi):
                return np.asarray(self.k_pages[:, lo:hi])
        """}, rules=["KTP001"])
    assert res.active == []


def test_adapter_hot_load_legs_are_barrier_legs():
    """Round-22 pin: the multi-LoRA adapter legs — ``load_adapter``
    (one host->device factor upload into the packed stack) and
    ``evict_adapter`` (directory bookkeeping) — are classified KTP001
    BARRIER legs: they run on the wire thread between steps, never
    inside one, and the closure traversal stops at them. The per-step
    adapter-id upload rides the ``_dev`` cache instead, so neither may
    ever become reachable from ``step()``."""
    from kubetpu.analysis.core import load_project
    from kubetpu.analysis.rules_device import HOT_BARRIERS, hot_closure

    for leg in ("load_adapter", "evict_adapter"):
        assert leg in HOT_BARRIERS, leg
    project = load_project(REPO_ROOT, ["kubetpu"])
    quals = {qual.split(".")[-1] if "." in qual else qual
             for _, qual, _ in hot_closure(project).values()}
    assert "load_adapter" not in quals
    assert "evict_adapter" not in quals


def test_repo_lints_clean_against_committed_baseline():
    """`make lint` green is a merge gate; this pins it in tier-1. Any
    new violation of KTP001–KTP006 in kubetpu/ or scripts/ fails here
    at the offending path:line unless it carries a justified inline
    disable or the (shrink-only) baseline covers it."""
    bl_path = os.path.join(REPO_ROOT, baseline_mod.DEFAULT_BASELINE)
    baseline = baseline_mod.load_baseline(bl_path)
    res = run_lint(REPO_ROOT, ["kubetpu", "scripts"], baseline=baseline)
    assert [f.render() for f in res.active] == []
    # the ratchet only ever shrinks: every budgeted finding must still
    # exist, otherwise the baseline is stale and must be regenerated
    assert baseline_mod.stale_keys(res.findings, baseline) == {}
