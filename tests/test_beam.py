"""Beam search: greedy equivalence at K=1, exact score accounting,
ordering, EOS pinning, and mesh execution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, forward, init_params, make_mesh
from kubetpu.jobs.beam import make_beam_search
from kubetpu.jobs.decode import make_generate

CFG = ModelConfig(vocab=32, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                  max_seq=64)


def _setup(seed=0, b=2, s=5):
    params = init_params(jax.random.PRNGKey(seed), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (b, s), 1, CFG.vocab)
    return params, prompt


def _recompute_score(params, full, s_prompt, eos_id=None):
    """Teacher-forced sum of log-probs of the generated part, stopping at
    (and including) the first EOS — the invariant the search maintains."""
    logits = forward(params, full[:, :-1], CFG)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    out = []
    for row_lp, row_tok in zip(np.asarray(logp), np.asarray(full)):
        total, done = 0.0, False
        for pos in range(s_prompt, full.shape[1]):
            if done:
                break
            tok = row_tok[pos]
            total += float(row_lp[pos - 1, tok])
            if eos_id is not None and tok == eos_id:
                done = True
        out.append(total)
    return np.array(out)


def test_beam_one_is_greedy():
    params, prompt = _setup()
    gen = make_generate(CFG)  # temperature 0 = greedy
    want = gen(params, prompt, jax.random.PRNGKey(0), 8)
    beam = make_beam_search(CFG, beam_size=1)
    got, scores = beam(params, prompt, 8)
    assert got.shape == (2, 1, prompt.shape[1] + 8)
    np.testing.assert_array_equal(np.asarray(got[:, 0]), np.asarray(want))
    np.testing.assert_allclose(
        np.asarray(scores[:, 0]),
        _recompute_score(params, got[:, 0], prompt.shape[1]),
        rtol=1e-4, atol=1e-4,
    )


def test_beam_scores_exact_and_sorted():
    params, prompt = _setup()
    beam = make_beam_search(CFG, beam_size=4)
    seqs, scores = beam(params, prompt, 6)
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()  # best-first
    for j in range(4):  # every beam's score is its true sum of log-probs
        np.testing.assert_allclose(
            s[:, j],
            _recompute_score(params, seqs[:, j], prompt.shape[1]),
            rtol=1e-4, atol=1e-4,
        )
    # beams are distinct hypotheses
    flat = {tuple(np.asarray(seqs[0, j]).tolist()) for j in range(4)}
    assert len(flat) == 4


def test_beam_beats_or_matches_greedy():
    params, prompt = _setup()
    greedy = make_beam_search(CFG, beam_size=1)
    wide = make_beam_search(CFG, beam_size=4)
    _, s1 = greedy(params, prompt, 6)
    _, s4 = wide(params, prompt, 6)
    assert (np.asarray(s4[:, 0]) >= np.asarray(s1[:, 0]) - 1e-5).all()


def test_beam_eos_pins_finished():
    params, prompt = _setup()
    eos = 3
    beam = make_beam_search(CFG, beam_size=4, eos_id=eos)
    seqs, scores = beam(params, prompt, 10)
    s_p = prompt.shape[1]
    arr = np.asarray(seqs)
    for bi in range(arr.shape[0]):
        for j in range(arr.shape[1]):
            gen = arr[bi, j, s_p:]
            where = np.where(gen == eos)[0]
            if len(where):
                # everything after the first EOS is EOS (pinned beam)
                assert (gen[where[0]:] == eos).all(), gen
    # scores still exact under pinning (frozen at first EOS)
    np.testing.assert_allclose(
        np.asarray(scores[:, 0]),
        _recompute_score(params, seqs[:, 0], s_p, eos_id=eos),
        rtol=1e-4, atol=1e-4,
    )


def test_beam_length_penalty_ranks_by_normalized_score():
    params, prompt = _setup()
    beam = make_beam_search(CFG, beam_size=3, length_penalty=0.6)
    _, scores = beam(params, prompt, 6)
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()


def test_beam_runs_on_mesh():
    mesh = make_mesh({"dp": 2, "tp": 2})
    params, prompt = _setup()
    beam = make_beam_search(CFG, beam_size=2, mesh=mesh)
    seqs, scores = beam(params, prompt, 4)
    assert seqs.shape == (2, 2, prompt.shape[1] + 4)
    assert np.isfinite(np.asarray(scores)).all()


def test_beam_size_validation():
    with pytest.raises(ValueError):
        make_beam_search(CFG, beam_size=0)
