"""Round-17: disaggregated prefill/decode serving.

The tentpole contract, on the real stack (CPU jax, tiny model): a
routed prompt admits on a PREFILL replica, its completed page-aligned
KV spans stream to the assigned DECODE replica while later chunks are
still computing, the stream hands off on first token, and the decode
replica emits every token — token-exact vs a quiet colocated run, with
warm decode-side prefix pages never crossing the wire, the pipelining
visible in the overlap counters, and all-"both" fleets degrading to
exactly the pre-Round-17 behavior.
"""

import numpy as np
import pytest

import jax

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.paged import PagedDecodeServer
from kubetpu.obs import disagg_slos
from kubetpu.obs.slo import SloEngine
from kubetpu.router import ReplicaServer, RouterServer
from kubetpu.router.migration import assemble_spans, span_name
from kubetpu.wire.httpcommon import request_json, request_text

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8
MAX_NEW = 10


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def make_server(params, kv_int8=False, cache_pages=16):
    return PagedDecodeServer(
        CFG, params, n_slots=4, max_seq=128, max_new_tokens=MAX_NEW,
        page_size=PS, prefill_budget=16, kv_int8=kv_int8,
        prefix_cache_pages=cache_pages)


def family_prompts(n, fam_tokens=40):
    fam = [(i * 5) % 60 + 1 for i in range(fam_tokens)]
    return [fam + [i + 1] for i in range(n)]


def quiet_run(params, prompts, kv_int8=False, sampling=None):
    direct = make_server(params, kv_int8=kv_int8)
    out = []
    for p in prompts:
        rid = direct.enqueue(p, sampling=sampling)
        direct.drain()
        out.append(direct.pop_result(rid))
    return out


# -- serving-layer legs -------------------------------------------------------


def test_snapshot_pages_matches_full_snapshot(params):
    """The streaming gather is byte-identical to the full snapshot's
    view of the same pages — spans + tail reassemble into exactly what
    a monolithic Round-16 snapshot ships."""
    srv = make_server(params, cache_pages=0)
    rid = srv.enqueue([(i * 3) % 60 + 1 for i in range(40)])
    while len(srv._emitted.get(rid, [])) < 2:
        srv.step()
    full = srv.snapshot_slot(rid)
    n_live = int(full["n_live_pages"])
    assert n_live >= 3
    early = srv.snapshot_pages(rid, 0, 2)
    tail = srv.snapshot_slot(rid, from_page=2, allow_frozen=False)
    for field in ("k", "v"):
        np.testing.assert_array_equal(early[field],
                                      full["pages"][field][:, :2])
        np.testing.assert_array_equal(tail["pages"][field],
                                      full["pages"][field][:, 2:])
    # span reassembly (the decode-side stitch) round-trips
    spans = {span_name(f, 0): early[f] for f in ("k", "v")}
    spans.update({span_name(f, 2): tail["pages"][f] for f in ("k", "v")})
    stitched = assemble_spans(spans, 0)
    for field in ("k", "v"):
        np.testing.assert_array_equal(stitched[field],
                                      full["pages"][field])
    # a gap refuses — never restore holes
    with pytest.raises(ValueError):
        assemble_spans({span_name("k", 1): early["k"]}, 0)
    srv.drain()
    srv.check_invariants()


def test_prefill_progress_only_mid_prefill(params):
    srv = make_server(params, cache_pages=0)
    rid = srv.enqueue([1] * 40)
    assert srv.prefill_progress(rid) is None        # still queued
    srv.step()
    prog = srv.prefill_progress(rid)
    assert prog is not None and 0 < prog[0] < prog[1] == 40
    assert prog[0] % PS == 0                        # page-aligned
    srv.drain()
    assert srv.prefill_progress(rid) is None        # finished


# -- the wire topology --------------------------------------------------------


@pytest.fixture()
def disagg_fleet(params):
    """router + 1 prefill + 1 decode replica over real paged servers."""
    made = {}

    def build(kv_int8=False, roles=("prefill", "decode")):
        replicas = []
        for i, role in enumerate(roles):
            rep = ReplicaServer(make_server(params, kv_int8=kv_int8),
                                f"d{role}{i}", role=role,
                                idle_wait=0.002)
            rep.start()
            replicas.append(rep)
        router = RouterServer(load_refresh_s=0.1)
        router.start()
        for rep in replicas:
            router.register_replica(rep.address)
        made["fleet"] = (router, replicas)
        return router, replicas

    yield build
    router, replicas = made["fleet"]
    router.shutdown()
    for rep in replicas:
        rep.shutdown(graceful=False)


def _drive(router, prompts, tag, sampling=None):
    bodies = []
    for i, p in enumerate(prompts):
        req = {"prompt": p, "timeout": 60.0}
        if sampling is not None:
            req["sampling"] = sampling
        bodies.append(request_json(
            router.address + "/generate", req,
            idempotency_key=f"disagg-{tag}-{i}", timeout=60.0))
    return bodies


def test_disagg_token_parity_and_pipelining(params, disagg_fleet):
    """The tentpole: routed tokens byte-equal a quiet colocated run;
    every request admits ONCE (on the prefill replica), restores once
    (on the decode replica), and some KV bytes shipped before prefill
    finished — the pipelining the overlap gauge proves."""
    prompts = family_prompts(4)
    expected = quiet_run(params, prompts)
    router, (pre, dec) = disagg_fleet()
    bodies = _drive(router, prompts, "parity")
    for body, want in zip(bodies, expected):
        assert body["tokens"] == want
        assert body["replica"] == dec.name    # decode emitted the stream
    committed = int(pre.server.obs.counter(
        "kubetpu_handoffs_total", result="committed").value)
    assert committed == len(prompts)
    assert len(pre.server.events.events(kind="admit")) == len(prompts)
    assert len(dec.server.events.events(kind="admit")) == 0
    assert (len(dec.server.events.events(kind="migrate_in"))
            == len(prompts))
    # pipelining: early KV bytes shipped while later chunks computed
    assert pre._handoff_early_bytes > 0
    assert pre._handoff_bytes > pre._handoff_early_bytes
    streamed = int(pre.server.obs.counter(
        "kubetpu_handoff_pages_streamed_total").value)
    assert streamed > 0
    pre.server.check_invariants()
    dec.server.check_invariants()
    # the obs surface: role series, handoff ledger + overlap on the
    # federated scrape; the cli summary renders the disagg section;
    # disagg_slos' handoff-success ratio reads healthy
    text = router.metrics_text()
    assert 'kubetpu_serving_role{role="prefill"' in text
    assert 'kubetpu_handoffs_total{result="committed"' in text
    from kubetpu.cli.obs import render_summary

    out = render_summary(text, "router")
    assert "disagg    roles prefill=1  decode=1  both=0" in out
    assert "committed=4" in out
    engine = SloEngine(disagg_slos(itl_p99_s=60.0, handoff_success=0.9))
    results = engine.evaluate(text)
    assert results["disagg_handoff_success"]["ok"] is True


def test_disagg_seeded_sampling_parity(params, disagg_fleet):
    """Sampled streams survive the handoff exactly: the raw request key
    ships with the snapshot, so the decode replica draws what an
    unmigrated run would have drawn."""
    prompts = family_prompts(2)
    sampling = {"temperature": 0.8, "top_k": 8}
    expected = quiet_run(params, prompts, sampling=sampling)
    router, (pre, dec) = disagg_fleet()
    bodies = _drive(router, prompts, "seeded", sampling=sampling)
    for body, want in zip(bodies, expected):
        assert body["tokens"] == want


def test_disagg_kv_int8_ships_quantized(params, disagg_fleet):
    """kv_int8 pools hand off disaggregated too — the spans carry the
    quantized quadruple as stored, and greedy decode stays exact."""
    prompts = family_prompts(2)
    expected = quiet_run(params, prompts, kv_int8=True)
    router, (pre, dec) = disagg_fleet(kv_int8=True)
    bodies = _drive(router, prompts, "int8")
    for body, want in zip(bodies, expected):
        assert body["tokens"] == want
    assert int(pre.server.obs.counter(
        "kubetpu_handoffs_total", result="committed").value) == 2


def test_disagg_warm_prefix_pages_never_cross_the_wire(params,
                                                       disagg_fleet):
    """The begin-phase hint: once the decode replica's radix tree holds
    a family's prefix (published at the first stream's retire), later
    family members ship only the uncached suffix — matched pages map
    read-only from the local cache instead of crossing the wire."""
    prompts = family_prompts(3)
    expected = quiet_run(params, prompts)
    router, (pre, dec) = disagg_fleet()
    bodies = _drive(router, prompts, "warm")
    for body, want in zip(bodies, expected):
        assert body["tokens"] == want
    remapped = int(dec.server.obs.counter(
        "kubetpu_migration_pages_remapped_total").value)
    assert remapped > 0
    pre.server.check_invariants()
    dec.server.check_invariants()


def test_dense_prefill_replica_degrades_not_crashes(params):
    """A DENSE (non-paged) server behind a prefill role has no
    shippable page view: the handoff must ABORT per stream (the base
    ``snapshot_slot`` stub's NotImplementedError — its signature must
    accept the handoff keywords, or the TypeError would kill the
    handoff loop thread and wedge the frozen stream forever) and the
    request completes LOCALLY, token-exact."""
    from kubetpu.jobs.serving import DecodeServer

    def make_dense():
        return DecodeServer(CFG, params, n_slots=2, max_seq=128,
                            max_new_tokens=6, prefill_budget=16)

    prompt = [(i * 3) % 60 + 1 for i in range(24)]
    direct = make_dense()
    rid = direct.enqueue(prompt)
    direct.drain()
    want = direct.pop_result(rid)
    pre = ReplicaServer(make_dense(), "dense-pre", role="prefill",
                        idle_wait=0.002)
    dec = ReplicaServer(make_server(params), "dense-dec", role="decode",
                        idle_wait=0.002)
    router = RouterServer(load_refresh_s=0.1)
    pre.start()
    dec.start()
    router.start()
    try:
        router.register_replica(pre.address)
        router.register_replica(dec.address)
        body = request_json(router.address + "/generate",
                            {"prompt": prompt, "timeout": 30.0},
                            idempotency_key="dense-degrade",
                            timeout=30.0)
        assert body["tokens"] == want
        assert body["replica"] == "dense-pre"    # served locally
        assert int(pre.server.obs.counter(
            "kubetpu_handoffs_total", result="aborted").value) == 1
        assert pre._handoff_thread.is_alive()    # the loop survived
    finally:
        router.shutdown()
        pre.shutdown(graceful=False)
        dec.shutdown(graceful=False)


def test_all_both_fleet_degrades_to_colocated(params, disagg_fleet):
    """The opt-in contract: with no dedicated roles the router never
    names a decode target and zero handoffs happen — Round-14/16
    behavior exactly."""
    prompts = family_prompts(2)
    expected = quiet_run(params, prompts)
    router, (r0, r1) = disagg_fleet(roles=("both", "both"))
    bodies = _drive(router, prompts, "coloc")
    for body, want in zip(bodies, expected):
        assert body["tokens"] == want
    for rep in (r0, r1):
        assert rep.events.events(kind="handoff_intent") == []
        assert int(rep.server.obs.counter(
            "kubetpu_handoffs_total", result="committed").value) == 0
