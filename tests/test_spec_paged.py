"""Speculative decoding over the paged KV pool (Round 10): greedy output
must be token-identical to ``PagedDecodeServer``'s — across f32 and
kv_int8 pools, cold and prefix-cache-hit admissions, chunked and
monolithic prefill — the pool accounting oracle must hold after every
speculative storm, and the adaptive-gamma controller must converge (down
under a disagreeing draft, pinned at gamma_max under self-draft).

Shape discipline: tests share ``max_seq=64``/``gamma_max`` values on
purpose — the compiled round legs are cached per (cfgs, page_size,
kv_int8, gamma, draft length), so aligned shapes keep this file's
compile bill to one set of rounds per pool dtype."""

import jax
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.paged import PagedDecodeServer
from kubetpu.jobs.spec_serving import PagedSpeculativeDecodeServer

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
DCFG = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=32)


@pytest.fixture(scope="module")
def params():
    return (init_params(jax.random.PRNGKey(0), CFG),
            init_params(jax.random.PRNGKey(7), DCFG))


def _spec(params, **kw):
    t, d = params
    kw.setdefault("page_size", 8)
    kw.setdefault("max_seq", 64)
    return PagedSpeculativeDecodeServer(CFG, DCFG, t, d, **kw)


def _staggered(server, prompts):
    ra = server.submit(prompts[0])
    server.step()
    rb = server.submit(prompts[1])
    server.drain()
    rc = server.submit(prompts[2])
    server.drain()
    return [server.result(r) for r in (ra, rb, rc)]


@pytest.mark.slow
def test_paged_spec_matches_plain_paged_greedy_staggered(params):
    """Same tokens as PagedDecodeServer for staggered requests crossing
    page boundaries mid-decode — speculation through the pool must be
    invisible in the output stream.
    Slow: the kv_int8 staggered variant keeps the same tier-1 parity
    path through the pool (plus spec-check's seeded storms)."""
    t, _d = params
    prompts = [[3, 14, 15, 9, 2, 6], [26, 5], [35, 8, 9, 7, 9, 3, 2, 1, 4]]
    plain = PagedDecodeServer(CFG, t, n_slots=2, max_seq=64,
                              max_new_tokens=12, page_size=8)
    spec = _spec(params, n_slots=2, max_new_tokens=12, gamma_max=3)
    got = _staggered(spec, prompts)
    assert got == _staggered(plain, prompts)
    assert spec.mean_tokens_per_round() >= 1.0
    spec.check_invariants()
    assert spec.pages_in_use() == 0


def test_paged_spec_self_draft_hits_the_ceiling(params):
    """Target as its own draft: total agreement, so every round emits
    gamma_max+1 tokens, gamma never leaves gamma_max, and the round
    count is exactly the ceiling — regression for both the draft-cache
    hole and an adaptive controller that would walk gamma down under
    full agreement."""
    t, _d = params
    srv = PagedSpeculativeDecodeServer(CFG, CFG, t, t, n_slots=1,
                                       max_seq=64, max_new_tokens=31,
                                       page_size=8, n_pages=8, gamma_max=2)
    rid = srv.submit([3, 14, 15, 9])
    rounds = 0
    while not srv.finished(rid):
        srv.step()
        rounds += 1
    # 30 post-first tokens at exactly 3/round = 10 rounds, no decay slack
    assert rounds == 10, rounds
    assert srv.mean_tokens_per_round() == 3.0
    assert srv.slot_gammas() == [2]
    plain = PagedDecodeServer(CFG, t, n_slots=1, max_seq=64,
                              max_new_tokens=31, page_size=8, n_pages=8)
    rp = plain.submit([3, 14, 15, 9])
    plain.drain()
    assert srv.result(rid) == plain.result(rp)


def test_adaptive_gamma_converges_down_on_disagreeing_draft(params):
    """A random-init draft (near-zero agreement with the target) must
    walk every serving slot's gamma down to 1 within a few rounds — the
    low-agreement stream stops buying verify bandwidth it never
    converts. Output stays exact regardless (greedy verification)."""
    srv = _spec(params, n_slots=1, max_new_tokens=24, gamma_max=3)
    rid = srv.submit([5, 9, 3, 1, 7, 2])
    srv.drain()
    assert srv.finished(rid)
    assert srv.slot_gammas() == [1]
    # acceptance counters: proposed > 0, accepted <= proposed
    text = srv.metrics_text()
    assert "kubetpu_spec_rounds_total" in text
    proposed = srv._c_spec_proposed.value
    accepted = srv._c_spec_accepted.value
    assert proposed > 0 and 0 <= accepted <= proposed
    # a NEW request on the same slot starts optimistic again
    rid2 = srv.submit([1, 2, 3])
    assert srv.slot_gammas() == [3]
    srv.drain()
    assert srv.finished(rid2)


def test_paged_spec_kv_int8_matches_plain_int8_pool(params):
    """kv_int8 pool: verify-chunk writes quantize with the same
    per-token scales a one-token decode would use, so the speculative
    int8 server matches the plain int8 paged server EXACTLY."""
    t, _d = params
    prompts = [[3, 14, 15, 9, 2, 6], [26, 5, 1], [7, 9, 2, 8, 4, 6, 1, 3, 5]]
    plain = PagedDecodeServer(CFG, t, n_slots=2, max_seq=64,
                              max_new_tokens=10, page_size=8, kv_int8=True)
    spec = _spec(params, n_slots=2, max_new_tokens=10,
                 kv_int8=True, gamma_max=2)
    assert _staggered(spec, prompts) == _staggered(plain, prompts)
    spec.check_invariants()


def test_paged_spec_chunked_and_prefix_hit_parity(params):
    """Chunked admission + shared-prefix radix-cache hits: the matched
    prefix skips BOTH the target's and the draft's prefill, and the
    warm (hit) output is token-identical to the cold plain server's —
    f32 and kv_int8."""
    t, _d = params
    sys_p = [(i * 5) % 60 + 1 for i in range(24)]      # 3 full pages
    tails = [[7, 8], [9, 1], [11, 2], [13, 4]]

    def run(server):
        outs = []
        for tl in tails:
            rid = server.enqueue(sys_p + tl)
            server.drain()
            outs.append(server.pop_result(rid))
        return outs

    for int8 in (False, True):
        plain = PagedDecodeServer(CFG, t, n_slots=2, max_seq=64,
                                  max_new_tokens=8, page_size=8,
                                  kv_int8=int8)
        spec = _spec(params, n_slots=2, max_new_tokens=8,
                     prefill_budget=8, prefix_cache_pages=8,
                     kv_int8=int8, gamma_max=2 if int8 else 3)
        assert run(spec) == run(plain), f"kv_int8={int8}"
        stats = spec.prefix_cache_stats()
        assert stats["requests_hit"] >= 2      # the hit path actually ran
        assert stats["prefill_tokens_saved"] > 0
        spec.check_invariants()


def test_paged_spec_storm_keeps_pool_invariants(params):
    """A mixed speculative storm — chunked admissions, prefix families,
    pool churn, queue pressure — must leave the accounting oracle clean
    after every drain and return every non-tree page."""
    srv = _spec(params, n_slots=2, max_new_tokens=6,
                prefill_budget=8, prefix_cache_pages=8, gamma_max=3)
    fam_a = [(i * 5) % 60 + 1 for i in range(16)]
    fam_b = [(i * 11) % 60 + 1 for i in range(16)]
    waves = [
        [fam_a + [1], fam_b + [2], [9, 9, 9]],
        [fam_a + [3], fam_b + [4], fam_a + [5], [1] * 20],
        [fam_b + [6], [2] * 9, fam_a + [7]],
    ]
    rids = []
    for wave in waves:
        rids.extend(srv.enqueue(p) for p in wave)
        srv.drain()
        srv.check_invariants()
    assert all(srv.finished(r) for r in rids)
    stats = srv.metrics_summary()
    assert stats["admission_stall"]["count"] == len(rids)
    assert srv._c_spec_rounds.value > 0


def test_paged_spec_unaligned_max_seq_chunked_parity(params):
    """A NON-page-aligned max_seq whose final chunk bucket rounds past
    ``max_seq + gamma_max``: the draft cache spans the target's table
    width, so the chunk's padded write fits it outright — regression for
    the clamp-shifted draft write that silently misaligned draft KV
    (output stayed exact; acceptance and the compile cache degraded)."""
    t, _d = params
    plain = PagedDecodeServer(CFG, t, n_slots=1, max_seq=57,
                              max_new_tokens=6, page_size=16, n_pages=4)
    spec = PagedSpeculativeDecodeServer(
        CFG, CFG, t, t, n_slots=1, max_seq=57, max_new_tokens=6,
        page_size=16, n_pages=4, prefill_budget=16, gamma_max=4)
    assert spec._draft_len > 57 + 4          # spans the padded table
    prompt = [(i * 7) % 60 + 1 for i in range(50)]
    rp, rs = plain.enqueue(prompt), spec.enqueue(prompt)
    plain.drain(), spec.drain()
    assert spec.result(rs) == plain.result(rp)
    # self-draft + in-range draft rows: acceptance stays at the ceiling
    assert spec.mean_tokens_per_round() == 5.0
    spec.check_invariants()


@pytest.mark.parametrize("kv_int8", [False, True])
def test_paged_spec_kernel_storm_parity(params, kv_int8):
    """Round-15: PagedSpeculativeDecodeServer(use_kernel=True) — the
    verify chunk runs the fused Pallas chunk kernel (in-kernel int8
    dequant included) — is greedy token-exact vs the plain gather-core
    PagedDecodeServer across a chunked + prefix-cache-hit storm, with
    the pool oracle clean after every drain and kernel rounds actually
    counted."""
    t, d = params
    fam = [(i * 5) % 60 + 1 for i in range(16)]
    prompts = [fam + [x] for x in (1, 2, 3)] + [[26, 5], [63] * 3]

    def run(server, check=False):
        outs = []
        for wave in (prompts[:3], prompts[3:]):
            rids = [server.enqueue(p) for p in wave]
            server.drain()
            outs.extend(server.pop_result(r) for r in rids)
            if check:
                server.check_invariants()
        return outs

    ref = run(PagedDecodeServer(CFG, t, n_slots=2, max_seq=64,
                                max_new_tokens=8, page_size=8,
                                kv_int8=kv_int8))
    spec = _spec(params, n_slots=2, max_new_tokens=8, gamma_max=3,
                 kv_int8=kv_int8, prefill_budget=8, prefix_cache_pages=8,
                 use_kernel=True, interpret=True)
    assert run(spec, check=True) == ref
    assert spec._c_spec_rounds.value > 0
    assert spec._c_kernel_steps.value > 0
    assert spec.prefix_cache_stats()["requests_hit"] >= 1


def test_paged_spec_rejects_sampling_window_and_bad_gamma(params):
    import dataclasses

    t, d = params
    srv = _spec(params, n_slots=1, max_new_tokens=4)
    with pytest.raises(ValueError):
        srv.submit([1, 2], sampling={"temperature": 1.0})
    with pytest.raises(ValueError):
        PagedSpeculativeDecodeServer(
            CFG, dataclasses.replace(DCFG, vocab=32), t, d)
    # the windowed refusal SURVIVES Round-15 (the kernel lifts the plain
    # paged window refusal, not this one) and must say exactly why:
    # ring aliasing vs the verify chunk's overshoot writes
    with pytest.raises(NotImplementedError,
                       match="ring table aliases logical pages"):
        PagedSpeculativeDecodeServer(
            dataclasses.replace(CFG, window=8), DCFG, t, d)
    with pytest.raises(NotImplementedError, match="overshoot"):
        PagedSpeculativeDecodeServer(
            dataclasses.replace(CFG, window=8), DCFG, t, d,
            use_kernel=True, interpret=True)
    with pytest.raises(ValueError):
        PagedSpeculativeDecodeServer(CFG, DCFG, t, d, gamma_max=0)


@pytest.mark.slow
def test_paged_spec_warmup_then_serve(params):
    """warmup() compiles draft buckets + every adaptive gamma's round and
    leaves the server fully serviceable (queue admission included).
    Slow: warmup exists to pay compile cost up front, so the test is
    compile-bound by construction (spec-check covers the serve path)."""
    srv = _spec(params, n_slots=2, max_seq=32, max_new_tokens=3,
                prefill_budget=8, gamma_max=2)
    srv.warmup()
    rids = [srv.enqueue([i + 1, i + 2]) for i in range(3)]
    srv.drain()
    assert all(srv.finished(r) for r in rids)
    srv.check_invariants()
    assert srv.pages_in_use() == 0
