"""BASELINE config 5 over the REAL wire: one agent process backed by the
native `tpuinfo --fake v5e-8` probe, one by `gpuinfo --fake titan8`, both
under one controller — topology-aware co-scheduling of two device classes
with per-class env injection, every boundary a real process or exec
(VERDICT r2 weak #5: the in-process schedsim config never crossed the
wire)."""

import json
import os
import subprocess
import sys

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.plugintypes import ResourceGPU, ResourceTPU
from kubetpu.wire.controller import ControllerServer, pod_to_json

from test_controller import _get, _post

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def native_binaries():
    # unconditional make: a stale prebuilt binary (from before a .cc
    # change) would otherwise run and fail confusingly; make no-ops when
    # the artifacts are fresh
    subprocess.run(["make", "-C", REPO, "tpuinfo", "gpuinfo"], check=True,
                   capture_output=True)


def spawn_agent(extra, env):
    import selectors

    proc = subprocess.Popen(
        [sys.executable, "-m", "kubetpu.cli.agent", "--serve", "--port", "0",
         *extra],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, cwd=REPO,
        text=True, env=env,
    )
    # bounded wait for the hello line; on crash/hang, surface stderr
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    if not sel.select(timeout=30):
        proc.kill()
        _, err = proc.communicate()
        raise AssertionError(f"agent never printed its hello line; stderr:\n{err[-800:]}")
    line = proc.stdout.readline()
    if not line.strip():
        _, err = proc.communicate()
        raise AssertionError(f"agent exited at startup; stderr:\n{err[-800:]}")
    hello = json.loads(line)
    return proc, hello["listening"], hello["node"]


@pytest.mark.slow
def test_heterogeneous_cluster_over_the_wire(native_binaries):
    env = {**os.environ, "KUBETPU_WIRE_TOKEN": ""}
    procs = []
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    try:
        tpu_proc, tpu_url, tpu_name = spawn_agent(
            ["--native", "--fake", "v5e-8", "--name", "tpu0"], env,
        )
        procs.append(tpu_proc)
        gpu_proc, gpu_url, gpu_name = spawn_agent(
            ["--device-class", "gpu", "--fake", "titan8", "--name", "gpu0"], env,
        )
        procs.append(gpu_proc)
        for url in (tpu_url, gpu_url):
            _post(controller.address + "/nodes", {"url": url})

        # TPU pod lands on the tpuinfo-backed node with the libtpu env
        tpod = PodInfo(name="tjob", running_containers={
            "main": ContainerInfo(requests={ResourceTPU: 4})})
        tout = _post(controller.address + "/pods", {"pod": pod_to_json(tpod)})
        assert tout["placements"][0]["node"] == "tpu0"
        tenv = tout["placements"][0]["containers"]["main"]["env"]
        assert tenv["TPU_VISIBLE_DEVICES"].count(",") == 3

        # GPU pod lands on the gpuinfo-backed node with the NVIDIA env
        gpod = PodInfo(name="gjob", running_containers={
            "main": ContainerInfo(requests={ResourceGPU: 4})})
        gout = _post(controller.address + "/pods", {"pod": pod_to_json(gpod)})
        assert gout["placements"][0]["node"] == "gpu0"
        genv = gout["placements"][0]["containers"]["main"]["env"]
        uuids = genv["NVIDIA_VISIBLE_DEVICES"].split(",")
        assert len(uuids) == 4 and all(u.startswith("GPU-") for u in uuids)

        status = _get(controller.address + "/status")
        assert status["nodes"]["tpu0"]["pods"] == ["tjob"]
        assert status["nodes"]["gpu0"]["pods"] == ["gjob"]
    finally:
        controller.shutdown()
        for p in procs:
            p.kill()
            p.wait(timeout=10)
