"""Continuous batching: staggered requests through the slot batch must each
produce exactly the same tokens as a dedicated plain greedy decode — slot
sharing, reuse, and uneven positions must be invisible to every request."""

import jax
import pytest
import numpy as np

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.decode import make_generate
from kubetpu.jobs.serving import DecodeServer

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)


def plain_greedy(params, prompt, steps):
    out = make_generate(CFG)(
        params,
        jax.numpy.asarray([prompt], jax.numpy.int32),
        jax.random.PRNGKey(0),
        steps,
    )
    return [int(x) for x in np.asarray(out)[0]]


def test_staggered_requests_match_dedicated_decode():
    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=6)

    prompts = {
        "a": [3, 14, 15, 9],
        "b": [26, 5],
        "c": [35, 8, 9, 7, 9],
    }
    ra = server.submit(prompts["a"])
    server.step()                       # a advances alone
    rb = server.submit(prompts["b"])    # b joins mid-flight
    rc_try = server.submit(prompts["c"])
    assert rc_try is None               # both slots busy
    server.drain()                      # a and b finish

    rc = server.submit(prompts["c"])    # c reuses a freed slot
    assert rc is not None
    server.drain()

    for rid, key in ((ra, "a"), (rb, "b"), (rc, "c")):
        assert server.finished(rid)
        assert server.result(rid) == plain_greedy(params, prompts[key], 6)


def test_slot_isolation_under_concurrency():
    """Two requests decoding simultaneously in adjacent slots must not
    influence each other (cache bleed would flip tokens)."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=4, max_seq=64, max_new_tokens=5)
    p1, p2 = [1, 2, 3], [60, 61, 62, 63]
    r1 = server.submit(p1)
    r2 = server.submit(p2)
    server.drain()
    assert server.result(r1) == plain_greedy(params, p1, 5)
    assert server.result(r2) == plain_greedy(params, p2, 5)


def test_eos_frees_slot_early():
    params = init_params(jax.random.PRNGKey(0), CFG)
    # find a token the model actually emits so EOS triggers organically
    probe = DecodeServer(CFG, params, n_slots=1, max_seq=64, max_new_tokens=3)
    rid = probe.submit([5, 6])
    probe.drain()
    eos = probe.result(rid)[-1]

    server = DecodeServer(CFG, params, n_slots=1, max_seq=64,
                          max_new_tokens=50, eos_id=eos)
    rid = server.submit([5, 6])
    server.drain()
    assert server.finished(rid)
    assert server.result(rid)[-1] == eos
    assert len(server.result(rid)) < 2 + 50  # stopped before the length cap
    assert not server.active.any()  # slot freed


def test_prompt_too_long_rejected():
    import pytest

    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=1, max_seq=16, max_new_tokens=8)
    with pytest.raises(ValueError):
        server.submit(list(range(12)))


def test_pop_result_evicts_bookkeeping():
    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=1, max_seq=64, max_new_tokens=4)
    rid = server.submit([7, 8])
    import pytest

    with pytest.raises(KeyError):
        server.pop_result(rid)      # not finished yet
    server.drain()
    tokens = server.pop_result(rid)
    assert tokens == plain_greedy(params, [7, 8], 4)
    with pytest.raises(KeyError):
        server.pop_result(rid)      # evicted


@pytest.mark.slow
def test_bucketed_prefill_exact_for_same_bucket_lengths():
    """Prompt lengths 5, 6, 7 all pad to the 8-bucket; each must still
    match its dedicated greedy decode exactly (pads never influence real
    positions: causal masks forward, overwrite-before-read in decode).
    Slow: three dedicated-reference decodes back to back; warmup +
    parity tests keep the bucket path pinned in tier-1."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=3, max_seq=64, max_new_tokens=4)
    prompts = [[11, 3, 5, 60, 2], [1, 2, 3, 4, 5, 6], [9, 8, 7, 6, 5, 4, 3]]
    rids = [server.submit(p) for p in prompts]
    server.drain()
    for rid, p in zip(rids, prompts):
        assert server.result(rid) == plain_greedy(params, p, 4)


def test_enqueue_admits_at_step_boundary_without_blocking():
    """The non-blocking admission path: enqueue never blocks the caller,
    queued requests enter free slots at the next step boundary, active
    streams keep emitting meanwhile, and every request still matches its
    dedicated greedy decode exactly."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=5)
    server.warmup()  # no live request pays a compile

    pa, pb, pc = [3, 14, 15], [26, 5], [35, 8, 9, 7]
    ra = server.submit(pa)
    # both further requests are queued instantly — no free-slot check, no
    # prefill on the caller's clock
    rb = server.enqueue(pb)
    rc = server.enqueue(pc)
    assert server.queued() == 2
    assert not server.finished(rb)

    out = server.step()      # admits b (one slot free), advances a and b
    assert ra in out and rb in out
    assert server.queued() == 1  # c still waits: both slots busy
    server.drain()           # c admitted when a slot frees; all complete

    for rid, p in ((ra, pa), (rb, pb), (rc, pc)):
        assert server.finished(rid)
        assert server.result(rid) == plain_greedy(params, p, 5)

    stats = server.metrics_summary()
    assert stats["admission_stall"]["count"] == 3  # a (submit), b, c
    assert stats["step"]["count"] >= 5
    assert stats["admission_stall"]["p50_ms"] >= 0


@pytest.mark.slow
def test_warmup_precompiles_every_bucket():
    """After warmup, admissions hit cached executables: no admission may
    take compile-scale time (compiles are >100x a cached dispatch)."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=2, max_seq=32, max_new_tokens=3)
    server.warmup()
    # buckets 1..32 are warm: time admissions across three bucket sizes
    for p in ([4], [4, 5, 6], [1] * 9):
        server.submit(p)
        server.drain()
    stats = server.metrics_summary()["admission_stall"]
    assert stats["count"] == 3
    # a compile on this config costs seconds; warmed dispatch is ms-scale
    assert stats["p99_ms"] < 1000


def test_mesh_sharded_server_matches_unsharded():
    """Multi-chip serving: DecodeServer over a {dp:2, tp:2} mesh must emit
    exactly the unsharded server's greedy tokens, with params tensor-
    parallel and the KV cache sharded (slots on dp, kv heads on tp)."""
    from kubetpu.jobs import make_mesh

    params = init_params(jax.random.PRNGKey(0), CFG)
    mesh = make_mesh({"dp": 2, "tp": 2})
    prompts = {"a": [3, 14, 15, 9], "b": [26, 5]}

    def run(server):
        rids = {k: server.submit(p) for k, p in prompts.items()}
        server.drain()
        return {k: server.result(r) for k, r in rids.items()}

    plain = run(DecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=6))
    sharded_server = DecodeServer(CFG, params, n_slots=2, max_seq=64,
                                  max_new_tokens=6, mesh=mesh)
    assert "tp" in str(sharded_server.k_cache.sharding.spec)
    assert sharded_server.params["blocks"]["wq"].sharding.spec != ()
    sharded = run(sharded_server)
    assert plain == sharded


def test_per_request_sampling_applies_per_slot():
    """Two concurrent requests with different sampling settings share one
    compiled step: a temperature=3 request truncated to top_k=1 must emit
    exactly the greedy stream (truncated argmax == argmax), proving the
    slot's own settings — not the server default, not its neighbor's —
    drove its draw."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = {"a": [3, 14, 15, 9], "b": [26, 5]}

    ref = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=6)
    ra, rb = ref.submit(prompts["a"]), ref.submit(prompts["b"])
    ref.drain()
    greedy = {"a": ref.result(ra), "b": ref.result(rb)}

    srv = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=6)
    sa = srv.submit(prompts["a"], sampling={"temperature": 3.0, "top_k": 1})
    sb = srv.submit(prompts["b"])   # server default: greedy
    srv.drain()
    assert srv.result(sa) == greedy["a"]
    assert srv.result(sb) == greedy["b"]

    # an actually-stochastic request stays in-vocab and finite-length
    srv2 = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=6)
    sc = srv2.submit(prompts["a"], sampling={"temperature": 1.0, "top_p": 0.9})
    srv2.drain()
    toks = srv2.result(sc)
    assert len(toks) == len(prompts["a"]) + 6
    assert all(0 <= t < CFG.vocab for t in toks)

    import pytest as _pytest
    with _pytest.raises(ValueError):
        srv2.submit(prompts["a"], sampling={"temp": 1.0})  # unknown key


def test_sampling_override_falsy_values_and_validation():
    """top_k=0 / top_p=1.0 explicitly DISABLE the server-default filter;
    bad values raise instead of silently corrupting the distribution."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    greedy_ref = DecodeServer(CFG, params, n_slots=1, max_seq=64,
                              max_new_tokens=4)
    rg = greedy_ref.submit([3, 14, 15, 9])
    greedy_ref.drain()

    # server default top_k=5; the request turns the filter OFF (top_k=0)
    # at temperature 0 -> still exact greedy (argmax needs no filter)
    srv = DecodeServer(CFG, params, n_slots=1, max_seq=64, max_new_tokens=4,
                       top_k=5)
    r = srv.submit([3, 14, 15, 9], sampling={"top_k": 0, "top_p": 1.0})
    srv.drain()
    assert srv.result(r) == greedy_ref.result(rg)

    import pytest as _pytest
    for bad in ({"temperature": -1.0}, {"top_p": -0.5}, {"top_p": 0.0},
                {"top_k": -2}):
        with _pytest.raises(ValueError):
            srv.submit([1, 2], sampling=bad)
    with _pytest.raises(ValueError):
        DecodeServer(CFG, params, n_slots=1, max_seq=64, max_new_tokens=4,
                     temperature=-0.5)


def test_result_logprobs_parallel_and_consistent():
    """Every emitted token carries its raw-distribution logprob: list
    parallel to the emitted stream, non-positive, identical across the
    dense and paged servers (same math, different memory layout)."""
    from kubetpu.jobs.paged import PagedDecodeServer

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = [3, 14, 15, 9]

    servers = {
        "dense": DecodeServer(CFG, params, n_slots=2, max_seq=64,
                              max_new_tokens=6),
        "paged": PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                                   max_new_tokens=6, page_size=8),
    }
    lps = {}
    for tag, srv in servers.items():
        rid = srv.submit(prompt)
        srv.step()          # exercise the deferred/step path too
        rid2 = srv.enqueue([26, 5])
        srv.drain()
        emitted = srv.result(rid)[len(prompt):]
        lp = srv.result_logprobs(rid)
        assert len(lp) == len(emitted) == 6
        assert all(x <= 0.0 for x in lp)
        assert len(srv.result_logprobs(rid2)) == len(srv.result(rid2)) - 2
        lps[tag] = lp
    np.testing.assert_allclose(lps["dense"], lps["paged"], rtol=1e-4,
                               atol=1e-5)


def test_spec_server_logprobs_match_dense():
    from kubetpu.jobs.spec_serving import SpeculativeDecodeServer

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = [3, 14, 15, 9]
    dense = DecodeServer(CFG, params, n_slots=1, max_seq=64, max_new_tokens=6)
    rd = dense.submit(prompt)
    dense.drain()
    spec = SpeculativeDecodeServer(CFG, CFG, params, params, n_slots=1,
                                   max_seq=64, max_new_tokens=6, gamma=3)
    rs = spec.submit(prompt)
    spec.drain()
    assert spec.result(rs) == dense.result(rd)
    np.testing.assert_allclose(spec.result_logprobs(rs),
                               dense.result_logprobs(rd), rtol=1e-3,
                               atol=1e-4)


def test_cancel_queued_active_and_finished():
    """cancel() drops a queued request, frees an active slot mid-decode
    (partial tokens stay readable; the neighbor stream is unaffected and
    the slot is reusable), and returns False for finished/unknown ids."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    srv = DecodeServer(CFG, params, n_slots=1, max_seq=64, max_new_tokens=8)

    ra = srv.submit([3, 14, 15, 9])
    rq = srv.enqueue([26, 5])
    srv.step()
    assert srv.cancel(rq) is True          # still queued -> dropped
    assert srv.finished(rq) and srv.queued() == 0

    srv.step()
    partial = list(srv.result(ra))
    assert srv.cancel(ra) is True          # active -> slot freed
    assert srv.finished(ra) and not srv.active.any()
    assert srv.result(ra) == partial       # tokens so far retained
    assert srv.cancel(ra) is False         # already finished

    # freed slot serves a new request; its stream matches a fresh server
    rc = srv.submit([7, 7])
    srv.drain()
    fresh = DecodeServer(CFG, params, n_slots=1, max_seq=64, max_new_tokens=8)
    rf = fresh.submit([7, 7])
    fresh.drain()
    assert srv.result(rc) == fresh.result(rf)


def test_cancel_releases_paged_pool_pages():
    from kubetpu.jobs.paged import PagedDecodeServer

    params = init_params(jax.random.PRNGKey(0), CFG)
    srv = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=8, page_size=8)
    rid = srv.submit([3, 14, 15, 9])
    srv.step()
    assert srv.pages_in_use() > 0
    assert srv.cancel(rid) is True
    assert srv.pages_in_use() == 0         # pool fully reclaimed


def test_kv_int8_server_matches_bf16_server():
    """DecodeServer(kv_int8=True): the serving cache in int8 — greedy
    tokens exactly match the bf16-cache server across a staggered
    admit/retire lifecycle (the layout-blind legs contract, round 5)."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[3, 14, 15, 9], [26, 5], [7, 7, 7, 2, 1]]

    def run(server):
        ra = server.submit(prompts[0])
        server.step()
        rb = server.submit(prompts[1])
        server.drain()
        rc = server.submit(prompts[2])
        server.drain()
        return [server.result(r) for r in (ra, rb, rc)]

    dense_srv = DecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=8)
    q8_srv = DecodeServer(CFG, params, n_slots=2, max_seq=64,
                          max_new_tokens=8, kv_int8=True)
    assert run(dense_srv) == run(q8_srv)
    # and the resident cache is ~half: int8 values + thin f32 scales
    dense_b = sum(x.nbytes for x in jax.tree.leaves(dense_srv.cache))
    q8_b = sum(x.nbytes for x in jax.tree.leaves(q8_srv.cache))
    assert q8_b < 0.6 * dense_b
    # the dense-array introspection properties refuse on the int8 layout
    import pytest as _pytest

    with _pytest.raises(AttributeError):
        _ = q8_srv.k_cache


def test_queue_ttl_expires_waiting_requests():
    """Graceful degradation under overload: a queued request past its TTL
    is expired (finished EMPTY, reason counted) instead of waiting forever
    behind a full slot batch — active requests are untouched."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=1, max_seq=64, max_new_tokens=4)
    active = server.submit([1, 2, 3])          # occupies the only slot
    doomed = server.enqueue([4, 5], ttl=0.0)   # expires at the next step
    patient = server.enqueue([6, 7])           # no TTL: waits as long as needed
    server.step()
    assert server.finished(doomed)
    assert server.expire_reason(doomed) == "queue_ttl"
    assert server.result(doomed) == [4, 5]     # prompt only, nothing emitted
    assert server.expire_reason(active) is None
    assert server.metrics_summary()["queue_expired"]["count"] == 1
    # pop drops ALL bookkeeping for the expired request, reason included
    assert server.pop_result(doomed) == [4, 5]
    assert server.expire_reason(doomed) is None
    server.drain()
    # the patient request took the freed slot and decoded normally (token
    # exactness is pinned elsewhere; this test is about the lifecycle)
    assert server.finished(patient) and server.expire_reason(patient) is None
    assert len(server.result(patient)) == 2 + 4   # prompt + max_new_tokens
    assert len(server.result(active)) == 3 + 4


def test_queue_ttl_server_default_applies_to_enqueue():
    """A server-level queue_ttl covers every enqueue that doesn't override
    it; ttl applies only while QUEUED — an admitted request never expires."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=2, max_seq=64,
                          max_new_tokens=3, queue_ttl=0.0)
    rid = server.enqueue([1, 2])     # free slot: admitted at the next step
    # admitted-before-expiry ONLY if admission happens at the same step the
    # deadline is checked: expiry runs first, so ttl=0 with a free slot
    # still expires (deterministic semantics: the deadline is checked at
    # the step boundary BEFORE admission)
    server.step()
    assert server.finished(rid) and server.expire_reason(rid) == "queue_ttl"
    # an explicit generous ttl overrides the server default and survives
    r2 = server.enqueue([3, 4], ttl=60.0)
    server.drain()
    assert server.finished(r2) and server.expire_reason(r2) is None
    assert len(server.result(r2)) == 2 + 3        # decoded, not expired


def test_steady_state_step_uploads_no_slot_state(monkeypatch):
    """Hot-loop upload cache (Round 10): once serving reaches steady
    state, step() must issue ZERO ``jnp.asarray`` uploads — the active
    mask, request keys and per-slot sampling settings live in device-
    resident mirrors invalidated only by admission/retire/sampling
    changes. Greedy output exactness is pinned by every parity test;
    this pins the absence of the per-step re-upload."""
    import jax.numpy as jnp

    params = init_params(jax.random.PRNGKey(0), CFG)
    server = DecodeServer(CFG, params, n_slots=2, max_seq=64,
                          max_new_tokens=30)
    server.submit([1, 2, 3, 4])
    server.step()                      # post-admission: mirrors warm
    calls = []
    real = jnp.asarray

    def counting(x, *a, **k):
        calls.append(np.shape(x))
        return real(x, *a, **k)

    monkeypatch.setattr(jnp, "asarray", counting)
    for _ in range(3):
        server.step()
    monkeypatch.undo()
    assert calls == [], f"steady-state step re-uploaded host state: {calls}"
    # an admission dirties the mirrors: the NEXT step re-uploads once,
    # then goes quiet again
    server.submit([7, 8])
    monkeypatch.setattr(jnp, "asarray", counting)
    server.step()
    uploads_after_admit = len(calls)
    calls.clear()
    server.step()
    monkeypatch.undo()
    assert uploads_after_admit > 0
    assert calls == []
    server.drain()
