"""Shared-prefix KV reuse (Round-9): the radix tree's structural
contracts, token-EXACT greedy parity through a prefix-cache hit vs the
cold path (f32 and kv_int8 pools), the structural copy-on-write rule
(shared pages are never written), LRU eviction under budget pressure,
and the pool accounting oracle after every storm."""

import jax
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.paged import PagedDecodeServer
from kubetpu.jobs.prefix_cache import RadixPrefixCache

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _sys_prompt(n, seed=5):
    return [(i * seed) % (CFG.vocab - 4) + 1 for i in range(n)]


# -- radix tree unit contracts ------------------------------------------------


def test_tree_match_insert_roundtrip():
    t = RadixPrefixCache(page_size=4, max_pages=16)
    toks = list(range(1, 13))                    # 3 full pages
    consumed = t.insert(toks, [10, 11, 12])
    assert consumed == {10, 11, 12}
    assert t.total_pages == 3
    m, pages, node = t.match(toks + [99, 98])    # longer query, same prefix
    assert m == 12 and pages == [10, 11, 12] and node is not None
    # partial-page tail is not matchable
    m, pages, _ = t.match(toks[:6])
    assert m == 4 and pages == [10]
    t.check()


def test_tree_split_on_mid_node_divergence():
    t = RadixPrefixCache(page_size=2, max_pages=16)
    t.insert([1, 2, 3, 4, 5, 6], [7, 8, 9])
    # diverges after page 1 (tokens [1,2]): the node must split at the
    # page boundary and both branches stay matchable
    consumed = t.insert([1, 2, 30, 40], [7, 5])
    assert consumed == {5}                       # page [1,2] already owned
    assert t.total_pages == 4
    m, pages, _ = t.match([1, 2, 3, 4, 5, 6])
    assert m == 6 and pages == [7, 8, 9]
    m, pages, _ = t.match([1, 2, 30, 40])
    assert m == 4 and pages == [7, 5]
    assert t.n_nodes() == 3                      # shared page + two suffixes
    t.check()


def test_tree_insert_respects_budget():
    t = RadixPrefixCache(page_size=2, max_pages=2)
    consumed = t.insert([1, 2, 3, 4, 5, 6], [7, 8, 9])
    assert consumed == {7, 8}                    # truncated to the budget
    assert t.total_pages == 2
    t.check()


def test_tree_lru_eviction_order_and_pin_protection():
    t = RadixPrefixCache(page_size=2, max_pages=16)
    t.insert([1, 2], [0])
    t.insert([3, 4], [1])
    t.insert([5, 6], [2])
    # touch branch [1,2]: it becomes most-recent; [3,4] is now LRU
    _, _, node12 = t.match([1, 2])
    t.pin(node12)
    freed = t.evict(1)
    assert freed == [1]                          # LRU unpinned leaf first
    freed = t.evict(2)
    assert freed == [2]                          # pinned [1,2] survives
    assert t.total_pages == 1
    t.release(node12)
    assert t.evict(1) == [0]
    t.check()


def test_tree_evict_walks_up_freed_branches():
    t = RadixPrefixCache(page_size=2, max_pages=16)
    t.insert([1, 2, 3, 4], [0, 1])
    t.insert([1, 2, 5, 6], [0, 2])               # splits: [1,2] -> two leaves
    assert t.n_nodes() == 3
    freed = t.evict(3)
    # leaves evict first, which exposes the shared parent as a leaf
    assert set(freed) == {0, 1, 2}
    assert t.total_pages == 0 and t.n_nodes() == 0
    t.check()


def test_tree_clear_returns_everything():
    t = RadixPrefixCache(page_size=2, max_pages=16)
    t.insert([1, 2, 3, 4], [4, 5])
    t.insert([9, 8], [6])
    assert sorted(t.clear()) == [4, 5, 6]
    assert t.total_pages == 0
    m, pages, node = t.match([1, 2, 3, 4])
    assert m == 0 and pages == [] and node is None


# -- server integration: parity, COW, accounting ------------------------------


def _run_seq(server, prompts):
    outs = []
    for p in prompts:
        rid = server.submit(p)
        assert rid is not None
        server.drain()
        outs.append(server.result(rid))
    return outs


def test_hit_parity_exact_f32(params):
    """Greedy decode through a prefix-cache HIT is token-exact vs the
    cold path — monolithic and chunked admission."""
    sys = _sys_prompt(20)
    prompts = [sys + t for t in ([7, 8], [9, 3, 1], [11], [9, 3, 2])]
    cold = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=8, page_size=PS)
    ref = _run_seq(cold, prompts)

    warm = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=8, page_size=PS,
                             prefix_cache_pages=16)
    assert _run_seq(warm, prompts) == ref
    warm.check_invariants()
    stats = warm.prefix_cache_stats()
    assert stats["requests_hit"] >= len(prompts) - 1
    assert stats["prefill_tokens_saved"] >= (len(prompts) - 1) * 16

    chunked = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                                max_new_tokens=8, page_size=PS,
                                prefill_budget=PS, prefix_cache_pages=16)
    rids = [chunked.enqueue(p) for p in prompts]
    chunked.drain()
    assert [chunked.result(r) for r in rids] == ref
    chunked.check_invariants()
    assert chunked.prefix_cache_stats()["requests_hit"] >= 1


def test_hit_parity_exact_kv_int8(params):
    """The same exactness through the int8 pool: the hit path reads the
    publisher's quantized pages, the cold path re-quantizes identical
    values — bit-identical either way."""
    sys = _sys_prompt(20, seed=7)
    prompts = [sys + t for t in ([3, 4, 5], [6], [2, 9])]
    cold = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=8, page_size=PS, kv_int8=True)
    ref = _run_seq(cold, prompts)
    warm = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=8, page_size=PS, kv_int8=True,
                             prefix_cache_pages=16)
    assert _run_seq(warm, prompts) == ref
    warm.check_invariants()
    assert warm.prefix_cache_stats()["requests_hit"] >= 2


def test_cow_boundary_page_never_written(params):
    """A prompt FULLY covered by the cache still re-prefills its final
    page into a private page (the last token must be forwarded to sample)
    — and the shared pages' bytes are untouched by the whole second
    request (the structural copy-on-write pin)."""
    ps = PS
    prompt = _sys_prompt(3 * ps)          # exactly 3 full pages
    server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                               max_new_tokens=6, page_size=ps,
                               prefix_cache_pages=16)
    r0 = server.submit(prompt)
    server.drain()
    ref = server.result(r0)
    server.check_invariants()
    tree_pages = sorted(server._prefix_cache.owned_pages())
    assert len(tree_pages) == 3           # the whole prompt is published
    before = np.asarray(server.k_pages)[:, tree_pages].copy()

    r1 = server.submit(prompt)            # full-coverage hit
    # capped one page short: pages 0-1 mapped shared, page 2 recomputed
    assert max(server._slot_shared) == 2
    server.drain()
    assert server.result(r1) == ref       # token-exact with itself
    server.check_invariants()
    after = np.asarray(server.k_pages)[:, tree_pages]
    np.testing.assert_array_equal(before, after)
    stats = server.prefix_cache_stats()
    assert stats["requests_hit"] == 1
    # matched all 3 pages, mapped only 2 (the COW cap)
    assert stats["hit_tokens"] == 3 * ps
    assert stats["prefill_tokens_saved"] == 2 * ps


def test_concurrent_slots_share_pages(params):
    """Two live slots mapping the SAME shared pages simultaneously:
    tokens match the cold run, refcounts track both pins, and the pages
    survive until the last reader retires."""
    sys = _sys_prompt(2 * PS)
    pa, pb = sys + [5, 6, 7], sys + [9, 1]
    cold = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=8, page_size=PS)
    ca = cold.submit(pa)
    cold.drain()
    cb = cold.submit(pb)
    cold.drain()
    ref = [cold.result(ca), cold.result(cb)]

    warm = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=8, page_size=PS,
                             prefix_cache_pages=16)
    seed = warm.submit(sys + [2])         # publish the prefix
    warm.drain()
    ra, rb = warm.submit(pa), warm.submit(pb)   # both map the shared pages
    pinned = [n for n in warm._prefix_cache.nodes() if n.refcount]
    assert pinned and sum(n.refcount for n in pinned) == 2
    warm.check_invariants()               # oracle holds MID-FLIGHT too
    warm.drain()
    assert [warm.result(ra), warm.result(rb)] == ref
    warm.check_invariants()
    assert all(n.refcount == 0 for n in warm._prefix_cache.nodes())
    warm.pop_result(seed)


# -- eviction under pressure (satellite) --------------------------------------


def test_eviction_under_budget_pressure_lru_and_no_leaks(params):
    """Storm DISTINCT prompts past ``prefix_cache_pages``: the tree stays
    within budget, evicts in LRU order, leaks no refcounts, and the pool
    oracle holds after every retirement."""
    budget = 4
    server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                               max_new_tokens=4, page_size=PS,
                               n_pages=24, prefix_cache_pages=budget)
    prompts = [_sys_prompt(2 * PS, seed=s) + [s] for s in (3, 7, 11, 13, 17)]
    for p in prompts:
        rid = server.submit(p)
        server.drain()
        server.pop_result(rid)
        server.check_invariants()
        assert server._prefix_cache.total_pages <= budget
        assert all(n.refcount == 0 for n in server._prefix_cache.nodes())
    # the LAST storm prompts must be resident (LRU evicted the oldest)
    m, _, _ = server._prefix_cache.match(prompts[-1])
    assert m == 2 * PS
    m0, _, _ = server._prefix_cache.match(prompts[0])
    assert m0 == 0
    assert server.prefix_cache_stats()["evicted_pages"] > 0


def test_admission_reclaims_tree_pages_instead_of_deadlocking(params):
    """A pool sized so a request CANNOT be admitted while the tree holds
    its budget: admission must evict reclaimable tree pages and proceed —
    never park forever behind the cache's own hoard."""
    ps = PS
    # pool 8 pages; worst case for a 17-token prompt + 8 new = 26 tokens
    # = 4 pages; tree budget 6 — after one request publishes 2 pages and
    # a second DISTINCT branch publishes 2 more, free pages (4) cannot
    # cover a fresh worst case alone once a third branch lands
    server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                               max_new_tokens=8, page_size=ps,
                               n_pages=8, prefix_cache_pages=6)
    outs = []
    for s in (3, 7, 11, 13):
        p = _sys_prompt(2 * ps, seed=s) + [s]
        rid = server.submit(p)
        assert rid is not None, "admission parked behind reclaimable pages"
        server.drain()
        outs.append(server.pop_result(rid))
        server.check_invariants()
    # the queue path reclaims too
    rid = server.enqueue(_sys_prompt(2 * ps, seed=19) + [1])
    server.drain()
    assert server.finished(rid)
    server.check_invariants()


def test_warmup_flushes_tree_and_serving_continues(params):
    server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=32,
                               max_new_tokens=3, page_size=PS,
                               prefix_cache_pages=8)
    rid = server.submit(_sys_prompt(PS) + [2, 3])
    server.drain()
    server.pop_result(rid)
    assert server._prefix_cache.total_pages > 0
    server.warmup()                       # idle: flush + precompile
    assert server._prefix_cache.total_pages == 0
    server.check_invariants()
    rid = server.submit(_sys_prompt(PS) + [2, 3])
    server.drain()
    assert server.finished(rid)
    server.check_invariants()


def test_overlap_composes_with_prefix_reuse(params):
    """overlap=True (emission lags one step; retirement — and therefore
    PUBLICATION — happens while a dispatched step is still in flight):
    the stray in-flight write for a retiring slot lands past its prompt
    pages, so donated pages stay clean — tokens must still match the
    cold path exactly."""
    sys = _sys_prompt(2 * PS, seed=9)
    prompts = [sys + [t] for t in (5, 6, 7, 8)]
    cold = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=6, page_size=PS)
    ref = _run_seq(cold, prompts)
    warm = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=6, page_size=PS,
                             prefill_budget=PS, overlap=True,
                             prefix_cache_pages=16)
    rids = [warm.enqueue(p) for p in prompts]
    warm.drain()
    assert [warm.result(r) for r in rids] == ref
    warm.check_invariants()
    assert warm.prefix_cache_stats()["requests_hit"] >= 1


def test_prefix_cache_refuses_windowed_configs(params):
    import dataclasses

    wcfg = dataclasses.replace(CFG, window=8)
    with pytest.raises(ValueError, match="window"):
        PagedDecodeServer(wcfg, params, n_slots=2, max_seq=64,
                          max_new_tokens=8, page_size=PS,
                          prefix_cache_pages=8)


def test_metrics_exposed_on_serving_registry(params):
    server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                               max_new_tokens=4, page_size=PS,
                               prefix_cache_pages=8)
    sys = _sys_prompt(2 * PS)
    for tail in ([1], [2], [3]):
        rid = server.submit(sys + tail)
        server.drain()
        server.pop_result(rid)
    text = server.metrics_text()
    for series in ("kubetpu_prefix_hit_tokens_total",
                   "kubetpu_prefill_tokens_saved_total",
                   'kubetpu_prefix_requests_total{result="hit"}',
                   'kubetpu_prefix_requests_total{result="miss"}',
                   "kubetpu_prefix_tree_pages",
                   "kubetpu_prefix_evicted_pages_total"):
        assert series in text, f"missing {series}"
    from kubetpu.obs.registry import validate_prometheus_text

    assert validate_prometheus_text(text) == []
