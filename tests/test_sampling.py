"""Sampling (temperature / top-k / nucleus): filter semantics against
numpy references, and the serving integration — greedy default stays
token-exact (pinned elsewhere), stochastic samplers stay inside their
truncated support."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.sampling import apply_top_k, apply_top_p, make_sampler


def test_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 2.9]])
    sample = make_sampler(0.0)
    out = sample(logits, jax.random.PRNGKey(0))
    assert out.tolist() == [1, 0]


def test_top_k_masks_below_threshold():
    logits = jnp.asarray([5.0, 4.0, 3.0, 2.0, 1.0])
    masked = np.asarray(apply_top_k(logits, 2))
    assert masked[0] == 5.0 and masked[1] == 4.0
    assert all(m <= -1e29 for m in masked[2:])


def test_top_k_draws_stay_in_support():
    logits = jnp.asarray([2.0, 1.9, 1.8, -1.0, -2.0, -3.0])
    sample = make_sampler(1.0, top_k=3)
    keys = jax.random.split(jax.random.PRNGKey(1), 200)
    draws = {int(sample(logits, k)) for k in keys}
    assert draws <= {0, 1, 2} and len(draws) > 1


def test_top_p_keeps_nucleus_and_boundary_token():
    # probs ~ [0.6, 0.3, 0.06, ...]: p=0.8 keeps token0 (0.6 < 0.8) and
    # token1 (the boundary crosser); token2 onward must be cut
    logits = jnp.log(jnp.asarray([0.60, 0.30, 0.06, 0.03, 0.01]))
    masked = np.asarray(apply_top_p(logits, 0.8))
    assert masked[0] > -1e29 and masked[1] > -1e29
    assert all(m <= -1e29 for m in masked[2:])


def test_top_p_one_is_identity():
    logits = jnp.asarray([1.0, 0.5, -0.5])
    np.testing.assert_array_equal(np.asarray(apply_top_p(logits, 1.0)),
                                  np.asarray(logits))


def test_top_p_always_keeps_top_token():
    # a spiked distribution with tiny p must still keep the argmax
    logits = jnp.asarray([10.0, 0.0, -5.0])
    sample = make_sampler(1.0, top_p=0.01)
    keys = jax.random.split(jax.random.PRNGKey(2), 50)
    draws = {int(sample(logits, k)) for k in keys}
    assert draws == {0}


def test_sampler_batched_shapes():
    logits = jax.random.normal(jax.random.PRNGKey(3), (4, 7, 32))
    sample = make_sampler(0.7, top_k=5, top_p=0.9)
    out = sample(logits, jax.random.PRNGKey(4))
    assert out.shape == (4, 7) and out.dtype == jnp.int32


def test_invalid_params_rejected():
    with pytest.raises(ValueError):
        make_sampler(-1.0)
    with pytest.raises(ValueError):
        apply_top_k(jnp.zeros((3,)), 0)
    with pytest.raises(ValueError):
        apply_top_p(jnp.zeros((3,)), 0.0)


def test_generate_with_sampling_is_seeded_and_valid():
    from kubetpu.jobs.decode import make_generate

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray([[3, 14, 15]], jnp.int32)
    gen = make_generate(cfg, temperature=0.9, top_k=8, top_p=0.95)
    a = np.asarray(gen(params, prompt, jax.random.PRNGKey(7), 12))
    b = np.asarray(gen(params, prompt, jax.random.PRNGKey(7), 12))
    c = np.asarray(gen(params, prompt, jax.random.PRNGKey(8), 12))
    np.testing.assert_array_equal(a, b)     # seeded: reproducible
    assert (a != c).any()                   # different seed: different path
    assert ((a >= 0) & (a < cfg.vocab)).all()


def test_serving_with_sampler_runs_and_differs_from_greedy():
    from kubetpu.jobs.serving import DecodeServer

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    greedy = DecodeServer(cfg, params, n_slots=2, max_seq=64, max_new_tokens=8)
    warm = DecodeServer(cfg, params, n_slots=2, max_seq=64, max_new_tokens=8,
                        temperature=1.3, top_k=16, seed=5)
    prompt = [5, 6, 7]
    rg = greedy.submit(prompt)
    greedy.drain()
    rw = warm.submit(prompt)
    warm.drain()
    g, w = greedy.result(rg), warm.result(rw)
    assert len(g) == len(w) == len(prompt) + 8
    assert all(0 <= t < cfg.vocab for t in w)
    assert g != w                           # hot sampling took another path


def test_per_row_filters_match_static_filters():
    """apply_top_k_rows/apply_top_p_rows with uniform settings must equal
    the static per-call filters; 0 / >=1 disable per row."""
    from kubetpu.jobs.sampling import (
        apply_top_k, apply_top_k_rows, apply_top_p, apply_top_p_rows,
    )

    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
    np.testing.assert_allclose(
        np.asarray(apply_top_k_rows(logits, jnp.full((4,), 3, jnp.int32))),
        np.asarray(apply_top_k(logits, 3)))
    np.testing.assert_allclose(
        np.asarray(apply_top_p_rows(logits, jnp.full((4,), 0.7))),
        np.asarray(apply_top_p(logits, 0.7)), rtol=1e-6)
    # disabled rows pass through untouched
    np.testing.assert_allclose(
        np.asarray(apply_top_k_rows(logits, jnp.zeros((4,), jnp.int32))),
        np.asarray(logits))
    np.testing.assert_allclose(
        np.asarray(apply_top_p_rows(logits, jnp.ones((4,)))),
        np.asarray(logits))
    # mixed rows: each row obeys ITS setting
    mixed = apply_top_k_rows(logits, jnp.asarray([0, 1, 3, 16], jnp.int32))
    np.testing.assert_allclose(np.asarray(mixed[0]), np.asarray(logits[0]))
    assert (np.asarray(mixed[1]) <= -1e29).sum() == 15  # only argmax survives


def test_slot_sampler_greedy_rows_are_exact_argmax():
    from kubetpu.jobs.sampling import make_slot_sampler

    sampler = make_slot_sampler()
    logits = jax.random.normal(jax.random.PRNGKey(1), (6, 32))
    toks = sampler(logits, jax.random.PRNGKey(2),
                   jnp.zeros((6,)), jnp.zeros((6,), jnp.int32), jnp.ones((6,)))
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, axis=-1)))
