"""Test configuration.

JAX-dependent tests run on a virtual 8-device CPU mesh (multi-chip TPU
hardware is unavailable in CI; sharding semantics are identical), so the env
must be set before any ``import jax`` — hence here, at conftest import time.
The environment may pin JAX to a hardware platform via a sitecustomize that
updates jax.config directly, so the config is re-forced after import too.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: spawns real OS processes / long end-to-end flows"
    )
