"""Test configuration.

JAX-dependent tests run on a virtual 8-device CPU mesh (multi-chip TPU
hardware is unavailable in CI; sharding semantics are identical), so the env
must be set before any ``import jax`` — hence here, at conftest import time.
The environment may pin JAX to a hardware platform via a sitecustomize that
updates jax.config directly, so the config is re-forced after import too.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: spawns real OS processes / long end-to-end flows"
    )
    config.addinivalue_line(
        "markers", "chaos: seeded fault-injection soaks over the wire stack"
    )


import pytest  # noqa: E402


@pytest.fixture(scope="session")
def trained_small():
    """ONE briefly-trained small model shared by every quality-contract
    test (int8 caches, paged pools): (cfg, params, data). The int8
    exactness contracts need trained weights — an untrained model's
    near-argmax ties flip under rounding — and training once per SESSION
    instead of per module saves ~50 s per extra copy."""
    import jax as _jax

    from kubetpu.jobs import ModelConfig, init_state, make_mesh, make_train_step
    from kubetpu.jobs.data import SyntheticCorpus

    cfg = ModelConfig(vocab=64, d_model=64, n_layers=2, n_heads=4, d_ff=128,
                      max_seq=128)
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1})
    # ONE generator, 8 distinct batches (test_distill.py's idiom) — a
    # fresh .batches(...) per element restarts the stream and every
    # "batch" is the identical first batch
    batches = SyntheticCorpus(cfg.vocab, seed=3,
                              skew=[0.85, 0.05, 0.05, 0.05]).batches(
                                  8, 32, seed=5)
    data = [next(batches) for _ in range(8)]
    state, opt = init_state(_jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt, use_ring=False)
    for i in range(150):
        state, _ = step(state, *data[i % 8])
    return cfg, state.params, data
