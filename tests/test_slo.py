"""Round-11 SLO engine: burn-rate math against hand-computed windows,
multi-window firing/recovery semantics, SLI resolution over live
registries vs federated exposition text, windowed-percentile recovery,
and the acceptance pin — an injected latency fault (``wire/faults.py``)
driving a declared TTFT objective into fast-burn violation, then
recovering when the fault is removed.

All evaluation clocks are SYNTHETIC (``evaluate(now=...)``): the window
math must be testable without sleeping."""

import time

import pytest

from kubetpu.obs.registry import Registry
from kubetpu.obs.slo import (
    BURN_THRESHOLD,
    Objective,
    SloEngine,
    fleet_slos,
    serving_slos,
)

# -- objective declaration ----------------------------------------------------


def test_objective_validation():
    with pytest.raises(ValueError):
        Objective("x", metric="m", threshold=1.0, op="==")
    with pytest.raises(ValueError):
        Objective("x", metric="m", threshold=1.0, target=1.0)
    with pytest.raises(ValueError):
        Objective("x", metric="m", threshold=1.0, reduce="median")
    with pytest.raises(ValueError):
        Objective("x", metric="m", threshold=1.0, percentile=100)
    with pytest.raises(ValueError):
        SloEngine([Objective("a", metric="m", threshold=1),
                   Objective("a", metric="m", threshold=2)])


def test_good_comparison_directions():
    ceil = Objective("lat", metric="m", threshold=0.25)            # "<="
    floor = Objective("pages", metric="m", threshold=4, op=">=")
    assert ceil.good(0.25) and not ceil.good(0.26)
    assert floor.good(4) and not floor.good(3.9)


# -- burn-rate math vs hand-computed windows ----------------------------------


def test_burn_rate_hand_computed_windows():
    """target=0.9 -> error budget 0.1. Feed a scripted verdict sequence
    at synthetic times and check both windows against hand arithmetic:
    burn = bad_fraction / 0.1."""
    obj = Objective("q", metric="m", threshold=10.0, target=0.9)
    eng = SloEngine([obj], fast_window=100.0, slow_window=1000.0,
                    burn_threshold=8.0)    # reachable at budget 0.1
    # value 20 violates (<= 10 is good), value 5 is good
    script = [(0, 5), (10, 20), (20, 20), (30, 5), (40, 20)]
    for t, v in script:
        res = eng.evaluate(source=[("m", {}, float(v))], now=float(t))["q"]
    # at t=40 all five verdicts are inside both windows: 3 bad / 5
    assert res["burn_fast"] == pytest.approx((3 / 5) / 0.1)
    assert res["burn_slow"] == pytest.approx((3 / 5) / 0.1)
    # advance: at t=125 the fast window (t > 25) holds only t=30 good,
    # t=40 bad and the new good one -> 1 bad / 3; slow window has 4 bad/7
    res = eng.evaluate(source=[("m", {}, 5.0)], now=125.0)["q"]
    assert res["burn_fast"] == pytest.approx((1 / 3) / 0.1)
    assert res["burn_slow"] == pytest.approx((3 / 6) / 0.1)


def test_burn_window_eviction_at_slow_horizon():
    obj = Objective("q", metric="m", threshold=1.0, target=0.5)
    eng = SloEngine([obj], fast_window=10.0, slow_window=100.0,
                    burn_threshold=1.5)    # reachable at budget 0.5
    eng.evaluate(source=[("m", {}, 9.0)], now=0.0)       # bad
    res = eng.evaluate(source=[("m", {}, 0.0)], now=150.0)["q"]
    # the t=0 bad verdict fell off the slow ring entirely
    assert res["burn_slow"] == 0.0 and res["burn_fast"] == 0.0


def test_firing_needs_both_windows_and_recovers_fast():
    """The multiwindow rule: a sustained violation fires (both windows
    over threshold); the moment the fast window goes good again, firing
    clears even while the slow window still remembers the incident."""
    obj = Objective("q", metric="m", threshold=1.0, target=0.99)
    eng = SloEngine([obj], fast_window=60.0, slow_window=3600.0)
    t = 0.0
    for _ in range(10):                      # 10 min of total violation
        res = eng.evaluate(source=[("m", {}, 5.0)], now=t)["q"]
        t += 60.0
    assert res["burn_fast"] == pytest.approx(100.0)      # 1.0 / 0.01
    assert res["burn_slow"] == pytest.approx(100.0)
    assert res["firing"] and res["ok"] is False
    # recovery: good evaluations refill the fast window
    for _ in range(3):
        res = eng.evaluate(source=[("m", {}, 0.5)], now=t)["q"]
        t += 30.0
    assert res["burn_fast"] < BURN_THRESHOLD
    assert not res["firing"]
    assert res["burn_slow"] > BURN_THRESHOLD   # the hour still remembers


# -- SLI resolution -----------------------------------------------------------


def test_ratio_and_reduce_over_sample_list():
    samples = [
        ("kubetpu_nodes", {"state": "healthy"}, 3.0),
        ("kubetpu_nodes", {"state": "suspect"}, 1.0),
        ("kubetpu_serving_pages_free", {"component": "a"}, 12.0),
        ("kubetpu_serving_pages_free", {"component": "b"}, 2.0),
    ]
    avail = fleet_slos(min_healthy_fraction=0.9)[0]
    floor = serving_slos(min_free_pages=4)[0]
    eng = SloEngine([avail, floor])
    out = eng.evaluate(source=samples, now=0.0)
    assert out["node_availability"]["value"] == pytest.approx(0.75)
    assert out["node_availability"]["ok"] is False
    # min-reduce reports the WORST replica across the federated scrape
    assert out["pool_free_pages"]["value"] == 2.0
    assert out["pool_free_pages"]["ok"] is False


def test_ratio_zero_denominator_is_total_violation_not_absent():
    """All nodes evicted: kubetpu_nodes still renders (zeros), the
    availability ratio is 0/0 — that must read 0% available and burn,
    never 'no data'. The worst outage cannot be the silent one."""
    samples = [("kubetpu_nodes", {"state": "healthy"}, 0.0),
               ("kubetpu_nodes", {"state": "suspect"}, 0.0)]
    eng = SloEngine(fleet_slos(min_healthy_fraction=0.9))
    res = eng.evaluate(source=samples, now=0.0)["node_availability"]
    assert res["value"] == 0.0 and res["ok"] is False
    assert res["burn_fast"] > 0
    # the series itself being gone is still absent, though
    res = eng.evaluate(source=[("other", {}, 1.0)],
                       now=1.0)["node_availability"]
    assert res["value"] is None


def test_absent_series_yields_no_verdict():
    eng = SloEngine([Objective("q", metric="missing", threshold=1.0)])
    res = eng.evaluate(source=[("other", {}, 1.0)], now=0.0)["q"]
    assert res["value"] is None and res["ok"] is None
    assert res["burn_fast"] == 0.0 and not res["firing"]
    # degraded scrape text (unparseable) degrades to absent, not a crash
    res = eng.evaluate(source="not prometheus {{{", now=1.0)["q"]
    assert res["value"] is None


def test_percentile_from_exposition_text_nearest_quantile():
    """Against federated TEXT only rendered quantiles exist — the
    engine picks the nearest one (documented degradation)."""
    reg = Registry()
    h = reg.histogram("kubetpu_serving_latency_seconds", op="ttft")
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    obj = serving_slos(ttft_p95_s=0.25)[0]         # p95 -> nearest is 0.99
    eng = SloEngine([obj])
    res = eng.evaluate(source=reg.render(), now=0.0)["ttft_p95"]
    assert res["value"] == pytest.approx(0.3)
    assert res["ok"] is False


def test_percentile_over_federated_scrape_judges_worst_replica():
    """A federated scrape carries one summary per replica; a latency
    ceiling must judge the WORST one — a degraded replica can't hide
    behind a healthy sibling that happens to parse first."""
    samples = [
        ("kubetpu_serving_latency_seconds",
         {"op": "ttft", "component": "a", "quantile": "0.99"}, 0.1),
        ("kubetpu_serving_latency_seconds",
         {"op": "ttft", "component": "b", "quantile": "0.99"}, 2.0),
    ]
    obj = serving_slos(ttft_p95_s=0.25)[0]
    eng = SloEngine([obj])
    res = eng.evaluate(source=samples, now=0.0)["ttft_p95"]
    assert res["value"] == pytest.approx(2.0)
    assert res["ok"] is False


def test_windowed_percentile_recovers_on_live_registry():
    """The naive-snapshot trap: a cumulative reservoir's p95 never
    forgets an incident. Against a LIVE registry the engine windows the
    reservoir by per-evaluation cursors, so once the bad samples age out
    of the fast window the SLI recovers."""
    reg = Registry()
    h = reg.histogram("kubetpu_serving_latency_seconds", op="ttft")
    obj = serving_slos(ttft_p95_s=0.25)[0]
    eng = SloEngine([obj], registry=reg, fast_window=100.0)
    for _ in range(20):
        h.observe(0.5)                              # the incident
    assert eng.evaluate(now=0.0)["ttft_p95"]["ok"] is False
    for _ in range(20):
        h.observe(0.01)                             # healthy again
    # within the same fast window the bad samples still dominate p95
    assert eng.evaluate(now=50.0)["ttft_p95"]["ok"] is False
    # past the window only the post-t=0 observations (the healthy ones,
    # bracketed by the t=0 cursor) are in view
    res = eng.evaluate(now=140.0)["ttft_p95"]
    assert res["value"] == pytest.approx(0.01)
    assert res["ok"] is True
    # and a window with NO bracketed observations reads ABSENT (no
    # verdict), never "0.0 = perfect"
    res = eng.evaluate(now=400.0)["ttft_p95"]
    assert res["value"] is None and res["ok"] is None


# -- gauge export -------------------------------------------------------------


def test_slo_gauges_render_on_bound_registry():
    reg = Registry()
    reg.gauge("kubetpu_serving_pages_free").set(2)
    eng = SloEngine(serving_slos(min_free_pages=4), registry=reg)
    eng.evaluate(now=0.0)
    text = reg.render()
    assert 'kubetpu_slo_value{slo="pool_free_pages"} 2' in text
    assert 'kubetpu_slo_threshold{slo="pool_free_pages"} 4' in text
    assert 'kubetpu_slo_ok{slo="pool_free_pages"} 0' in text
    assert 'kubetpu_slo_burn_rate{slo="pool_free_pages",window="fast"}' in text
    assert 'kubetpu_slo_burn_rate{slo="pool_free_pages",window="slow"}' in text
    assert 'kubetpu_slo_firing{slo="pool_free_pages"}' in text
    assert 'kubetpu_slo_evaluations_total{slo="pool_free_pages"} 1' in text
    assert 'kubetpu_slo_violations_total{slo="pool_free_pages"} 1' in text
    # cold start with a totally-violating gauge: fires immediately (no
    # history of health to hold the page back)
    assert eng.firing() == ["pool_free_pages"]
    assert 'kubetpu_slo_data{slo="pool_free_pages"} 1' in text
    # when the SLI goes absent the data bit flips so the frozen value/ok
    # gauges read as stale, not as fresh health — and cli.obs says so
    from kubetpu.cli.obs import render_slo

    eng2 = SloEngine(serving_slos(ttft_p95_s=0.25), registry=Registry())
    eng2.registry.histogram("unrelated")
    eng2.evaluate(now=0.0)
    text2 = eng2.registry.render()
    assert 'kubetpu_slo_data{slo="ttft_p95"} 0' in text2
    assert "no data" in render_slo(text2, "replica")
    # an unreachable burn threshold is a loud config error, not a
    # silently dead page
    with pytest.raises(ValueError):
        SloEngine(serving_slos(min_free_pages=4, target=0.9))


def test_maybe_evaluate_throttles():
    reg = Registry()
    reg.gauge("kubetpu_serving_pages_free").set(9)
    eng = SloEngine(serving_slos(min_free_pages=4), registry=reg)
    eng.maybe_evaluate(interval=30.0)
    eng.maybe_evaluate(interval=30.0)     # inside the interval: skipped
    assert ("kubetpu_slo_evaluations_total"
            '{slo="pool_free_pages"} 1') in reg.render().replace("\n", "")


# -- the acceptance pin: fault-driven TTFT burn + recovery --------------------


def test_injected_latency_fault_fires_ttft_slo_then_recovers():
    """A seeded ``wire/faults.py`` delay on the agent's wire route drives
    a client-observed TTFT objective into fast-burn violation within one
    evaluation window; clearing the injector recovers it. The TTFT
    histogram is the serving-shaped series, the engine runs over the
    live registry (windowed percentiles), and ``cli.obs slo`` renders
    the firing state."""
    from kubetpu.cli.obs import render_slo
    from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
    from kubetpu.wire.faults import FaultInjector, RoutePolicy
    from kubetpu.wire.httpcommon import request_json
    from kubetpu.wire.server import NodeAgentServer

    # thresholds sized for loaded CI boxes: a healthy local HTTP round
    # trip stays well under 150 ms even throttled; the injected 400 ms
    # delay clears it by design, not by luck
    inj = FaultInjector(seed=7, routes={
        "/nodeinfo": RoutePolicy(delay=1.0, delay_s=0.4)})
    agent = NodeAgentServer(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-16")),
        "slo-h0", faults=inj)
    agent.start()
    reg = Registry()
    hist = reg.histogram("kubetpu_serving_latency_seconds", op="ttft")
    eng = SloEngine(serving_slos(ttft_p95_s=0.15),
                    registry=reg, fast_window=10.0, slow_window=100.0)

    def observe_ttft(n):
        for _ in range(n):
            t0 = time.perf_counter()
            request_json(agent.address + "/nodeinfo")
            hist.observe(time.perf_counter() - t0)

    try:
        t = 0.0
        observe_ttft(4)                      # every call eats the delay
        for _ in range(4):                   # one evaluation window of bad
            res = eng.evaluate(now=t)["ttft_p95"]
            t += 2.5
        assert res["value"] >= 0.4 and res["ok"] is False
        assert res["burn_fast"] >= BURN_THRESHOLD
        assert res["firing"], res
        text = reg.render()
        assert 'kubetpu_slo_firing{slo="ttft_p95"} 1' in text
        assert "FIRING" in render_slo(text, "replica")

        inj.clear()                          # the network heals
        observe_ttft(6)
        t += 10.0                            # past the fast window
        for _ in range(4):
            res = eng.evaluate(now=t)["ttft_p95"]
            t += 2.5
        assert res["value"] < 0.15 and res["ok"] is True
        assert res["burn_fast"] < BURN_THRESHOLD
        assert not res["firing"], res
        text = reg.render()
        assert 'kubetpu_slo_firing{slo="ttft_p95"} 0' in text
        assert "FIRING" not in render_slo(text, "replica")
    finally:
        agent.shutdown()
