"""Tiered KV cache (Round-19): HBM -> host DRAM -> peer replica.

The tier's whole contract is that it only moves WHERE cached KV lives,
never what a request computes: every path here is judged against the
cold (reuse-off) server token-for-token. Spill (LRU victims gathered to
host buffers instead of dropped), fill (host buffers uploaded back and
promoted before prefill starts), and the cross-replica fetch (a cold
replica adopting a peer's exported span over the wire) each get a
parity leg plus their accounting proofs; the fault paths (dark peer,
receded coverage) must degrade to cold prefill, never corrupt."""

import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.paged import PagedDecodeServer
from kubetpu.router import ReplicaServer
from kubetpu.wire.httpcommon import request_json

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8
BUDGET = 4          # HBM tree pages: two 2-page families fill it exactly


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def fam(seed):
    """One 2-page shared-prefix family head."""
    return [(i * seed) % 60 + 1 for i in range(2 * PS)]


def make(params, host=1 << 22, budget=BUDGET, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_seq", 64)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("page_size", PS)
    return PagedDecodeServer(CFG, params, prefix_cache_pages=budget,
                             host_tier_bytes=host, **kw)


def run(server, prompts):
    rids = [server.enqueue(p) for p in prompts]
    server.drain()
    return [server.pop_result(r) for r in rids]


def spill_storm(server):
    """famA warms, famB+famC evict it (budget 4 holds two families) —
    famA's pages land in the host tier — then famA returns. Returns the
    request list (run one wave at a time so LRU order is deterministic)
    and the outputs."""
    waves = [[fam(5) + [1], fam(5) + [2]],
             [fam(7) + [1], fam(11) + [1]],
             [fam(5) + [3], fam(5) + [4]]]
    outs = []
    for wave in waves:
        outs.extend(run(server, wave))
        server.check_invariants()
    return [p for w in waves for p in w], outs


def cold_reference(params, prompts, **kw):
    cold = make(params, host=0, budget=0, **kw)
    return run(cold, prompts)


# -- spill -> fill token exactness --------------------------------------------


@pytest.mark.parametrize("prefill_budget", [0, PS],
                         ids=["monolithic", "chunked"])
def test_spill_fill_token_exact_f32(params, prefill_budget):
    """LRU victims spill to host instead of dropping; the returning
    family fills them back and decodes token-exactly vs cold — for both
    monolithic and chunked prefill."""
    warm = make(params, prefill_budget=prefill_budget)
    prompts, got = spill_storm(warm)
    ref = cold_reference(params, prompts, prefill_budget=prefill_budget)
    assert got == ref
    ts = warm.tier_stats()
    assert ts["spills"]["host"] > 0, "storm never spilled"
    assert ts["fills"]["host"] > 0, "returning family never filled"
    assert ts["tokens_saved"]["host"] > 0, "host tier saved nothing"
    warm.check_invariants()


def test_spill_fill_token_exact_kv_int8(params):
    """The int8 path: spilled buffers hold the quantized pairs AS
    STORED (int8 codes + f32 scales — never dequantized), and a fill
    restores bit-identical pages: parity vs the cold int8 server."""
    warm = make(params, kv_int8=True, prefill_budget=PS)
    # warm famA, then force its spill so we can inspect the buffers
    run(warm, [fam(5) + [1]])
    run(warm, [fam(7) + [1], fam(11) + [1]])
    hosts = warm._prefix_cache.host_nodes()
    assert hosts, "famA never spilled"
    for node in hosts:
        assert set(node.host) == {"k_q", "k_s", "v_q", "v_s"}
        assert node.host["k_q"].dtype == np.int8
        assert node.host["k_s"].dtype == np.float32
    prompts = [fam(5) + [2], fam(5) + [3]]
    got = run(warm, prompts)
    ref = cold_reference(params, prompts, kv_int8=True, prefill_budget=PS)
    assert got == ref
    assert warm.tier_stats()["fills"]["host"] > 0
    warm.check_invariants()


def test_fill_under_pool_pressure_no_deadlock(params):
    """A fill that must RECLAIM pool pages for its own upload (pool
    sized so free pages alone can't host the promoted span) completes
    without deadlock and stays token-exact."""
    # n_pages just above the two slots' worst case: the fill's upload
    # has to push other cached pages out to make room
    need = -(-(2 * PS + 1 + 6 + 1) // PS)     # pages per slot
    warm = make(params, n_pages=2 * need + BUDGET, prefill_budget=PS)
    prompts, got = spill_storm(warm)
    ref = cold_reference(params, prompts, prefill_budget=PS)
    assert got == ref
    warm.check_invariants()


def test_warmup_drops_host_tier(params):
    """``warmup()`` flushes BOTH tiers — a stale host buffer surviving
    a weight swap would fill poisoned KV — and the next visit re-warms
    from cold, token-exactly."""
    warm = make(params, prefill_budget=PS)
    spill_storm(warm)
    assert warm._prefix_cache.host_bytes > 0
    warm.warmup()
    assert warm._prefix_cache.host_bytes == 0
    assert warm._prefix_cache.host_nodes() == []
    assert warm._prefix_cache.total_pages == 0
    prompts = [fam(5) + [1], fam(5) + [2]]
    got = run(warm, prompts)
    ref = cold_reference(params, prompts, prefill_budget=PS)
    assert got == ref
    assert warm.prefix_cache_stats()["requests_hit"] > 0
    warm.check_invariants()


# -- invariants ---------------------------------------------------------------


def test_host_tier_invariants(params):
    """The tree oracle holds mid-storm: host bytes within budget, every
    node owns its span in exactly one tier, and the per-node byte
    ledger is exact."""
    warm = make(params, host=1 << 20, prefill_budget=PS)
    spill_storm(warm)
    tree = warm._prefix_cache
    tree.check()
    assert tree.host_bytes <= warm.host_tier_bytes
    for node in tree.nodes():
        assert not (node.pages and node.host is not None)
    for node in tree.host_nodes():
        assert node.host_bytes == sum(a.nbytes for a in node.host.values())


def test_tiny_host_budget_degrades_to_drop(params):
    """A budget too small for any span: eviction degrades to the
    pre-Round-19 drop (no spill), and nothing breaks."""
    warm = make(params, host=16, prefill_budget=PS)
    prompts, got = spill_storm(warm)
    ref = cold_reference(params, prompts, prefill_budget=PS)
    assert got == ref
    assert warm.tier_stats()["spills"]["host"] == 0
    assert warm._prefix_cache.host_bytes == 0
    warm.check_invariants()


def test_inject_refuses_hole_and_replays_idempotently(params):
    """``inject_prefix`` refuses a span whose from_page is ahead of
    local coverage (the receded-coverage hole), and a replayed inject
    of an adopted span commits nothing twice."""
    a = make(params)
    b = make(params)
    prompt = fam(5)
    run(a, [prompt + [1]])
    span = a.export_prefix_span(prompt)
    assert span is not None and span["n_pages"] == 2
    # hole: b covers nothing, span claims to start at page 1
    tail = a.export_prefix_span(prompt, from_page=1)
    assert b.inject_prefix(prompt[:tail["matched_tokens"]], tail["pages"],
                           from_page=1) == 0
    # clean adopt, then replay
    assert b.inject_prefix(prompt[:span["matched_tokens"]],
                           span["pages"]) == 2
    assert b.inject_prefix(prompt[:span["matched_tokens"]],
                           span["pages"]) == 0
    got = run(b, [prompt + [1]])
    assert got == cold_reference(params, [prompt + [1]])
    b.check_invariants()


# -- the wire leg -------------------------------------------------------------


@pytest.fixture()
def replicas(params):
    made = []

    def build(n=2, **server_kw):
        reps = []
        for i in range(n):
            rep = ReplicaServer(make(params, **server_kw), f"tier{i}",
                                idle_wait=0.002)
            rep.start()
            reps.append(rep)
        made.extend(reps)
        return reps

    yield build
    for rep in made:
        rep.shutdown(graceful=False)


def _counter(rep, name, **want):
    text = rep.server.metrics_text()
    for line in text.splitlines():
        if line.startswith(name) and all(
                f'{k}="{v}"' in line for k, v in want.items()):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def test_peer_fetch_wire_parity(params, replicas):
    """A cold replica handed a ``prefix_peer`` pulls the span over
    /prefix_fetch before admission and decodes token-exactly; the
    exporter stays read-only (its own storm keeps passing)."""
    ra, rb = replicas()
    prompt = fam(5) + [1]
    ref = cold_reference(params, [prompt, fam(5) + [2]])
    warm_a = request_json(ra.address + "/generate", {"prompt": prompt},
                          idempotency_key="t-a", timeout=30)
    assert warm_a["tokens"] == ref[0]
    body = request_json(
        rb.address + "/generate",
        {"prompt": fam(5) + [2], "prefix_peer": ra.address},
        idempotency_key="t-b", timeout=30)
    assert body["tokens"] == ref[1]
    assert _counter(rb, "kubetpu_peer_prefix_fetch_total",
                    result="hit") == 1
    assert _counter(ra, "kubetpu_peer_prefix_export_total",
                    result="hit") == 1
    assert rb.server.tier_stats()["tokens_saved"]["peer"] > 0
    ra.server.check_invariants()
    rb.server.check_invariants()


def test_peer_fetch_dark_peer_degrades(params, replicas):
    """A dark peer (nothing listening) costs the retry deadline at
    worst and the request cold-prefills token-exactly."""
    (rb,) = replicas(n=1)
    prompt = fam(7) + [1]
    ref = cold_reference(params, [prompt])
    body = request_json(
        rb.address + "/generate",
        {"prompt": prompt, "prefix_peer": "http://127.0.0.1:9"},
        idempotency_key="t-dark", timeout=30)
    assert body["tokens"] == ref[0]
    assert _counter(rb, "kubetpu_peer_prefix_fetch_total",
                    result="degraded") == 1
    rb.server.check_invariants()


def test_peer_fetch_miss_and_skip(params, replicas):
    """A peer with nothing cached answers 404 (counted as miss, cold
    prefill); a LOCALLY covered prompt never fetches at all."""
    ra, rb = replicas()
    prompt = fam(11) + [1]
    ref = cold_reference(params, [prompt])
    body = request_json(
        rb.address + "/generate",
        {"prompt": prompt, "prefix_peer": ra.address},
        idempotency_key="t-miss", timeout=30)
    assert body["tokens"] == ref[0]
    assert _counter(rb, "kubetpu_peer_prefix_fetch_total",
                    result="miss") == 1
    # now covered locally: the same family again must not re-fetch
    request_json(rb.address + "/generate",
                 {"prompt": fam(11) + [2], "prefix_peer": ra.address},
                 idempotency_key="t-miss2", timeout=30)
    assert _counter(rb, "kubetpu_peer_prefix_fetch_total",
                    result="miss") == 1
    assert _counter(rb, "kubetpu_peer_prefix_fetch_total",
                    result="hit") == 0
