"""Decode/KV-cache tests: cached incremental decoding must agree with the
full batched forward."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs import ModelConfig, forward, init_params, make_mesh
from kubetpu.jobs.decode import init_kv_cache, make_generate, prefill

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)


def test_prefill_logits_match_forward():
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, CFG.vocab)
    k_cache, v_cache = init_kv_cache(CFG, 2, 12)
    logits, _, _ = prefill(CFG, params, tokens, k_cache, v_cache)
    full = forward(params, tokens, CFG)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, -1]), rtol=2e-4, atol=2e-5
    )


def test_greedy_generate_matches_rescoring():
    """Each greedily-generated token must be the argmax of the full forward
    over the sequence so far — the cache introduces no drift."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, CFG.vocab)
    gen = make_generate(CFG)
    out = gen(params, prompt, jax.random.PRNGKey(2), 6)
    assert out.shape == (2, 11)
    assert np.array_equal(np.asarray(out[:, :5]), np.asarray(prompt))
    seq = np.asarray(out)
    for t in range(5, 11):
        logits = forward(params, jnp.asarray(seq[:, :t]), CFG)
        expected = np.argmax(np.asarray(logits[:, -1]), axis=-1)
        np.testing.assert_array_equal(seq[:, t], expected)


def test_generate_on_mesh():
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2})
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 4), 0, CFG.vocab)
    gen = make_generate(CFG, mesh=mesh)
    out = gen(params, prompt, jax.random.PRNGKey(2), 4)
    assert out.shape == (4, 8)


def test_sampled_generate_runs():
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, CFG.vocab)
    gen = make_generate(CFG, temperature=1.0)
    out = gen(params, prompt, jax.random.PRNGKey(2), 5)
    assert out.shape == (2, 9)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < CFG.vocab).all()


def test_bfloat16_generate():
    # bf16 configs must generate: prefill and per-token logits both f32 so
    # the decode scan carry is dtype-stable
    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                      dtype=jnp.bfloat16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, cfg.vocab)
    out = make_generate(cfg)(params, prompt, jax.random.PRNGKey(2), 4)
    assert out.shape == (2, 8)


def test_capacity_moe_prefill_matches_training_forward():
    # prefill must use the SAME dispatch mode as training (capacity), not a
    # divergent copy
    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                      n_experts=4, moe_capacity_factor=1.5)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab)
    k_cache, v_cache = init_kv_cache(cfg, 2, 12)
    logits, _, _ = prefill(cfg, params, tokens, k_cache, v_cache)
    full = forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, -1], np.float32), rtol=2e-4, atol=2e-5
    )


GQA_CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                      n_kv_heads=2)


def test_gqa_equals_mha_when_groups_is_heads():
    """n_kv_heads == n_heads must be bit-identical to the MHA default: same
    init (same RNG consumption), same forward."""
    cfg_mha = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    cfg_kv4 = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                          n_kv_heads=4)
    p1 = init_params(jax.random.PRNGKey(0), cfg_mha)
    p2 = init_params(jax.random.PRNGKey(0), cfg_kv4)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
    np.testing.assert_array_equal(
        np.asarray(forward(p1, tokens, cfg_mha)),
        np.asarray(forward(p2, tokens, cfg_kv4)),
    )


def test_gqa_cache_is_kv_heads_sized():
    k_cache, v_cache = init_kv_cache(GQA_CFG, 2, 16)
    assert k_cache.shape == (2, 2, 16, 2, 8)  # H_kv == 2, not H == 4


def test_gqa_prefill_matches_forward():
    params = init_params(jax.random.PRNGKey(0), GQA_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, GQA_CFG.vocab)
    k_cache, v_cache = init_kv_cache(GQA_CFG, 2, 12)
    logits, _, _ = prefill(GQA_CFG, params, tokens, k_cache, v_cache)
    full = forward(params, tokens, GQA_CFG)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, -1]), rtol=2e-4, atol=2e-5
    )


@pytest.mark.slow
def test_gqa_greedy_generate_matches_rescoring():
    """The grouped cached-attention decode path must agree with the full
    forward — for GQA (2 groups) and MQA (n_kv_heads=1)."""
    for n_kv in (2, 1):
        cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                          n_kv_heads=n_kv)
        params = init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, cfg.vocab)
        out = make_generate(cfg)(params, prompt, jax.random.PRNGKey(2), 6)
        seq = np.asarray(out)
        for t in range(5, 11):
            logits = forward(params, jnp.asarray(seq[:, :t]), cfg)
            np.testing.assert_array_equal(
                seq[:, t], np.argmax(np.asarray(logits[:, -1]), axis=-1)
            )


def test_generate_on_mesh_matches_single_device():
    """The sharded-cache decode (batch on dp, kv heads on tp) must emit the
    exact same greedy tokens as the unsharded path."""
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2})
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 4), 0, CFG.vocab)
    out_mesh = make_generate(CFG, mesh=mesh)(params, prompt, jax.random.PRNGKey(2), 5)
    out_plain = make_generate(CFG)(params, prompt, jax.random.PRNGKey(2), 5)
    np.testing.assert_array_equal(np.asarray(out_mesh), np.asarray(out_plain))


def test_ring_prefill_matches_dense():
    """Long-context prefill over sp (ring attention filling the decode
    cache) must produce the same cache and logits as the dense prefill."""
    from kubetpu.jobs import make_ring_attention

    mesh = make_mesh({"dp": 1, "sp": 4, "tp": 2})
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, CFG.vocab)

    k1, v1 = init_kv_cache(CFG, 2, 40)
    ring = make_ring_attention(mesh)
    logits_r, k1, v1 = jax.jit(
        lambda p, t, k, v: prefill(CFG, p, t, k, v, attn_fn=ring)
    )(params, tokens, k1, v1)

    k2, v2 = init_kv_cache(CFG, 2, 40)
    logits_d, k2, v2 = prefill(CFG, params, tokens, k2, v2)
    np.testing.assert_allclose(np.asarray(logits_r), np.asarray(logits_d),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(k1), np.asarray(k2),
                               rtol=2e-5, atol=2e-6)

    # the ring-prefilled cache decodes identically from there on
    from kubetpu.jobs.decode import forward_chunk

    nxt = jnp.argmax(logits_r, axis=-1).astype(jnp.int32)
    lr, _, _ = forward_chunk(CFG, params, nxt[:, None], k1, v1, 32)
    ld, _, _ = forward_chunk(CFG, params, nxt[:, None], k2, v2, 32)
    np.testing.assert_allclose(np.asarray(lr), np.asarray(ld),
                               rtol=2e-4, atol=2e-5)


def test_windowed_decode_matches_windowed_forward():
    """cfg.window bands the cache read: KV-cached chunk logits must equal
    the teacher-forced windowed forward at every position."""
    import dataclasses

    from kubetpu.jobs.decode import forward_chunk, init_kv_cache
    from kubetpu.jobs.model import forward as full_forward

    cfg = dataclasses.replace(CFG, window=5)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0, cfg.vocab)
    kc, vc = init_kv_cache(cfg, 2, 24)
    got, _kc, _vc = forward_chunk(cfg, params, tokens, kc, vc, 0)
    want = full_forward(params, tokens, cfg)  # default attn honors window
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # and the window genuinely changes the result vs full attention
    full = full_forward(params, tokens, dataclasses.replace(CFG, window=0))
    assert not np.allclose(np.asarray(want), np.asarray(full), atol=1e-3)


def test_windowed_generate_runs_past_window():
    """Generation longer than the window stays finite and well-formed (the
    band keeps sliding; early cache rows fall out of every later read)."""
    import dataclasses

    from kubetpu.jobs.decode import make_generate

    cfg = dataclasses.replace(CFG, window=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    gen = make_generate(cfg)
    out = gen(params, jnp.array([[1, 2, 3]]), jax.random.PRNGKey(0), 16)
    assert out.shape == (1, 19)
    assert int(out.max()) < cfg.vocab and int(out.min()) >= 0


def test_rolling_generate_token_exact_vs_dense_cache():
    """The O(window) ring cache must generate the EXACT tokens of the
    O(max_seq) dense cache on a windowed config, across generations long
    enough to wrap the ring several times — and from prompts both shorter
    and longer than the window."""
    import dataclasses

    from kubetpu.jobs.decode import make_generate, make_rolling_generate

    cfg = dataclasses.replace(CFG, window=5)
    params = init_params(jax.random.PRNGKey(0), cfg)
    dense = make_generate(cfg)
    ring = make_rolling_generate(cfg)
    for prompt in (jnp.array([[1, 2, 3]]),                 # shorter than W
                   jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]])):  # longer than W
        want = dense(params, prompt, jax.random.PRNGKey(0), 20)
        got = ring(params, prompt, jax.random.PRNGKey(0), 20)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_rolling_generate_requires_window():
    import pytest

    from kubetpu.jobs.decode import make_rolling_generate

    with pytest.raises(ValueError):
        make_rolling_generate(CFG)  # window == 0


def test_rolling_generate_with_int8_params():
    """The ring path serves quantized weights too: prefill dequantizes the
    whole tree (training forward knows nothing of QTensors), the decode
    loop per layer — greedy output matches the bf16 rolling path within
    quantization error (and runs at all, the regression this pins)."""
    import dataclasses

    from kubetpu.jobs.decode import make_rolling_generate
    from kubetpu.jobs.quant import quantize_params

    cfg = dataclasses.replace(CFG, window=5)
    params = init_params(jax.random.PRNGKey(0), cfg)
    qparams = quantize_params(params)
    ring = make_rolling_generate(cfg)
    out = ring(qparams, jnp.array([[1, 2, 3]]), jax.random.PRNGKey(0), 12)
    assert out.shape == (1, 15)
    assert int(out.max()) < cfg.vocab
