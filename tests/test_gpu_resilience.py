"""Preemption + defragmentation for tree (GPU) nodes — the capabilities
VERDICT r1 flagged as TPU-only. Victim selection is by structural fill
(scalar count is exact for tree fill, which spills across NVLink groups);
defrag's "perfect" target is a whole level-1 (socket) group.
"""

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster, SchedulingError
from kubetpu.core import group_scheduler
from kubetpu.core.cluster import PriorityKey
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.device.nvidia import new_fake_nvidia_gpu_manager
from kubetpu.device.nvidia.types import (
    GpuInfo, GpusInfo, MemoryInfo, PciInfo, TopologyInfo, VersionInfo,
)
from kubetpu.plugintypes import ResourceGPU, ResourceTPU


def gpu_mgr():
    """8-GPU two-socket box: pairs NVLinked (link 5) within a socket ->
    gpugrp0 pairs, one gpugrp1 group per socket of 4 (the TITAN X fixture
    shape, nvidia_gpu_manager_test.go:16)."""
    bus = [f"0000:{i:02X}:00.0" for i in range(8)]
    gpus = []
    for i in range(8):
        socket = i // 4
        topo = [
            TopologyInfo(bus_id=bus[j], link=5 if j // 2 == i // 2 else 3)
            for j in range(socket * 4, socket * 4 + 4)
            if j != i
        ]
        gpus.append(GpuInfo(id=f"GPU{i:02d}", model="Fake", path=f"/dev/nvidia{i}",
                            memory=MemoryInfo(global_mib=12238),
                            pci=PciInfo(bus_id=bus[i], bandwidth=15760),
                            topology=topo))
    info = GpusInfo(version=VersionInfo(driver="fake", cuda=""), gpus=gpus)
    return new_fake_nvidia_gpu_manager(info, "v", "d")


def gpu_pod(name, n, prio=None):
    p = PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceGPU: n})},
    )
    if prio is not None:
        p.requests[PriorityKey] = prio
    return p


def test_gpu_preemption_evicts_lower_priority():
    cluster = Cluster()
    cluster.register_node("g0", device=gpu_mgr())
    cluster.schedule(gpu_pod("low1", 4))
    cluster.schedule(gpu_pod("low2", 4))

    placed, evicted = cluster.schedule_preempting(gpu_pod("high", 4, prio=10))
    assert placed.node_name == "g0"
    assert len(evicted) == 1 and evicted[0].name in ("low1", "low2")
    assert "high" in cluster.nodes["g0"].pods
    assert not any(c.allocate_from for c in evicted[0].running_containers.values())


def test_gpu_preemption_refuses_equal_priority():
    cluster = Cluster()
    cluster.register_node("g0", device=gpu_mgr())
    cluster.schedule(gpu_pod("a", 8, prio=5))
    try:
        cluster.schedule_preempting(gpu_pod("b", 4, prio=5))
        assert False, "equal priority must not preempt"
    except SchedulingError:
        pass
    assert "a" in cluster.nodes["g0"].pods


def test_gpu_preemption_evicts_minimum_set():
    cluster = Cluster()
    cluster.register_node("g0", device=gpu_mgr())
    for i in range(4):
        cluster.schedule(gpu_pod(f"low{i}", 2, prio=i))
    placed, evicted = cluster.schedule_preempting(gpu_pod("high", 2, prio=10))
    assert [p.name for p in evicted] == ["low0"]  # cheapest victim first


def test_gpu_defrag_plan_and_execute():
    cluster = Cluster()
    cluster.register_node("n0", device=gpu_mgr())
    cluster.register_node("n1", device=gpu_mgr())
    # n0: four 2-GPU pods fill both sockets (a,b -> socket 0; c,d -> 1);
    # release one pod per socket -> each socket has 2 free, no socket has 4.
    for nm in ("a", "b", "c", "d"):
        cluster.schedule(gpu_pod(nm, 2), lambda n: n == "n0")
    cluster.release("b")
    cluster.release("d")
    # n1: a 6-GPU pod leaves 2 free (no socket with 4 free there either)
    cluster.schedule(gpu_pod("big6", 6), lambda n: n == "n1")

    plan = cluster.defrag_plan(4, device="gpu")
    assert plan is not None and len(plan) == 1
    assert plan[0].from_node == "n0"  # destination may be n1 OR back on n0
    # (the source node is a valid destination outside the opened group)

    moved, pending = cluster.execute_defrag(plan, pending=gpu_pod("quad", 4))
    assert pending is not None and pending.node_name == "n0"
    # the pending pod's 4 GPUs all landed within ONE socket group
    held = group_scheduler.held_cards(pending, "gpu")
    assert len(held) == 4
    assert len({group_scheduler.cards_group(k) for k in held}) == 1
    # the migrated pod is placed somewhere and did not re-take that group
    assert moved[0].node_name in ("n0", "n1")
    if moved[0].node_name == "n0":
        moved_groups = {
            group_scheduler.cards_group(k)
            for k in group_scheduler.held_cards(moved[0], "gpu")
        }
        assert moved_groups.isdisjoint(
            {group_scheduler.cards_group(k) for k in held}
        )


def test_gpu_defrag_intra_node():
    """Single-node cross-socket defrag: the source node itself is a valid
    re-placement destination (no second node exists)."""
    cluster = Cluster()
    cluster.register_node("n0", device=gpu_mgr())
    for nm in ("a", "b", "c", "d"):
        cluster.schedule(gpu_pod(nm, 2))
    cluster.release("b")
    cluster.release("d")  # each socket: 2 held, 2 free
    plan = cluster.defrag_plan(4, device="gpu")
    assert plan is not None and len(plan) == 1 and plan[0].to_node == "n0"
    moved, pending = cluster.execute_defrag(plan, pending=gpu_pod("quad", 4))
    held = group_scheduler.held_cards(pending, "gpu")
    assert len({group_scheduler.cards_group(k) for k in held}) == 1


def test_gpu_defrag_noop_and_infeasible():
    cluster = Cluster()
    cluster.register_node("n0", device=gpu_mgr())
    assert cluster.defrag_plan(4, device="gpu") == []  # already fits
    # fill the node completely: no migrations can open a group, and no
    # destination has room
    cluster.schedule(gpu_pod("all", 8))
    assert cluster.defrag_plan(4, device="gpu") is None


def test_mixed_cluster_preemption_ignores_wrong_class_nodes():
    """A GPU preemptor must not evict TPU pods (and vice versa): the only
    eligible node is the one whose class can satisfy the request."""
    cluster = Cluster()
    cluster.register_node("g0", device=gpu_mgr())
    cluster.register_node(
        "t0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    tpu_low = PodInfo(
        name="tpu-low",
        running_containers={"main": ContainerInfo(requests={ResourceTPU: 8})},
    )
    cluster.schedule(tpu_low)
    cluster.schedule(gpu_pod("gpu-low", 8))

    placed, evicted = cluster.schedule_preempting(gpu_pod("gpu-high", 4, prio=10))
    assert placed.node_name == "g0"
    assert [p.name for p in evicted] == ["gpu-low"]
    assert "tpu-low" in cluster.nodes["t0"].pods  # untouched


def test_preemption_skips_noncontributing_victims():
    """A victim that frees none of the needed device class (e.g. a CPU-only
    pod) must not be evicted, whatever its priority."""
    cluster = Cluster()
    cluster.register_node("g0", device=gpu_mgr())
    cpu_only = PodInfo(
        name="cpu-only",
        running_containers={"main": ContainerInfo(requests={})},
    )
    cluster.schedule(cpu_only)  # prio 0, holds no devices
    cluster.schedule(gpu_pod("gpu-low", 8, prio=1))

    placed, evicted = cluster.schedule_preempting(gpu_pod("high", 4, prio=10))
    assert [p.name for p in evicted] == ["gpu-low"]
    assert "cpu-only" in cluster.nodes["g0"].pods  # innocent bystander kept
