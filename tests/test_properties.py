"""Property-based tests (hypothesis) for the load-bearing invariants:
the translation grammar (any advertised node shape must accept any
satisfiable request) and the mesh contiguity score bounds."""

from hypothesis import given, settings, strategies as st

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster, SchedulingError
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.plugintypes.mesh import TOPOLOGIES, contiguity_score, find_contiguous_block

TOPO_NAMES = ["v5e-4", "v5e-8", "v5e-16", "v4-8"]


@settings(max_examples=40, deadline=None)
@given(
    topo_name=st.sampled_from(TOPO_NAMES),
    taken=st.sets(st.integers(min_value=0, max_value=7), max_size=8),
    n=st.integers(min_value=0, max_value=16),
)
def test_find_block_respects_free_set_and_score_bounds(topo_name, taken, n):
    topo = TOPOLOGIES[topo_name]
    all_coords = set(topo.coords())
    taken_coords = {topo.index_coord(i % topo.num_chips) for i in taken}
    free = all_coords - taken_coords
    got = find_contiguous_block(set(free), n, topo)
    if n > len(free):
        assert got is None
        return
    assert got is not None
    coords, score = got
    assert len(coords) == n
    assert len(set(coords)) == n          # no duplicates
    assert set(coords) <= free            # never places on taken chips
    assert 0.0 <= score <= 1.0


@settings(max_examples=30, deadline=None)
@given(
    sizes=st.lists(st.integers(min_value=1, max_value=8), min_size=1, max_size=6),
)
def test_scheduler_accepts_any_satisfiable_sequence(sizes):
    """Any sequence of pod sizes whose running total fits the host must all
    schedule; the first overflowing pod must raise — the grammar/fill path
    can never wedge in between."""
    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    free = 8
    for i, n in enumerate(sizes):
        pod = PodInfo(
            name=f"p{i}",
            running_containers={"m": ContainerInfo(requests={ResourceTPU: n})},
        )
        if n <= free:
            placed = cluster.schedule(pod)
            assert len(placed.running_containers["m"].allocate_from) == n
            free -= n
        else:
            try:
                cluster.schedule(pod)
                assert False, f"pod of {n} chips fit with only {free} free"
            except SchedulingError:
                pass
    assert cluster.nodes["n0"].info.allocatable[ResourceTPU] == free


@settings(max_examples=30, deadline=None)
@given(
    chips=st.sets(st.integers(min_value=0, max_value=15), min_size=1, max_size=16),
)
def test_contiguity_score_bounds_any_subset(chips):
    topo = TOPOLOGIES["v5e-16"]
    coords = {topo.index_coord(i) for i in chips}
    s = contiguity_score(coords, topo)
    assert 0.0 <= s <= 1.0
