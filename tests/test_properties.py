"""Property-based tests (hypothesis) for the load-bearing invariants:
the translation grammar (any advertised node shape must accept any
satisfiable request) and the mesh contiguity score bounds."""

import pytest

# hypothesis is an optional dev dependency: where it isn't installed the
# module must SKIP, not collection-error (tier-1 runs with
# --continue-on-collection-errors, but an error still hides every test
# in this file from the pass/fail accounting)
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st  # noqa: E402

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster, SchedulingError
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.plugintypes.mesh import TOPOLOGIES, contiguity_score, find_contiguous_block

TOPO_NAMES = ["v5e-4", "v5e-8", "v5e-16", "v4-8"]


@settings(max_examples=40, deadline=None)
@given(
    topo_name=st.sampled_from(TOPO_NAMES),
    taken=st.sets(st.integers(min_value=0, max_value=7), max_size=8),
    n=st.integers(min_value=0, max_value=16),
)
def test_find_block_respects_free_set_and_score_bounds(topo_name, taken, n):
    topo = TOPOLOGIES[topo_name]
    all_coords = set(topo.coords())
    taken_coords = {topo.index_coord(i % topo.num_chips) for i in taken}
    free = all_coords - taken_coords
    got = find_contiguous_block(set(free), n, topo)
    if n > len(free):
        assert got is None
        return
    assert got is not None
    coords, score = got
    assert len(coords) == n
    assert len(set(coords)) == n          # no duplicates
    assert set(coords) <= free            # never places on taken chips
    assert 0.0 <= score <= 1.0


@settings(max_examples=30, deadline=None)
@given(
    sizes=st.lists(st.integers(min_value=1, max_value=8), min_size=1, max_size=6),
)
def test_scheduler_accepts_any_satisfiable_sequence(sizes):
    """Any sequence of pod sizes whose running total fits the host must all
    schedule; the first overflowing pod must raise — the grammar/fill path
    can never wedge in between."""
    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    free = 8
    for i, n in enumerate(sizes):
        pod = PodInfo(
            name=f"p{i}",
            running_containers={"m": ContainerInfo(requests={ResourceTPU: n})},
        )
        if n <= free:
            placed = cluster.schedule(pod)
            assert len(placed.running_containers["m"].allocate_from) == n
            free -= n
        else:
            try:
                cluster.schedule(pod)
                assert False, f"pod of {n} chips fit with only {free} free"
            except SchedulingError:
                pass
    assert cluster.nodes["n0"].info.allocatable[ResourceTPU] == free


@settings(max_examples=30, deadline=None)
@given(
    chips=st.sets(st.integers(min_value=0, max_value=15), min_size=1, max_size=16),
)
def test_contiguity_score_bounds_any_subset(chips):
    topo = TOPOLOGIES["v5e-16"]
    coords = {topo.index_coord(i) for i in chips}
    s = contiguity_score(coords, topo)
    assert 0.0 <= s <= 1.0


@settings(max_examples=40, deadline=None)
@given(
    topo_name=st.sampled_from(TOPO_NAMES),
    taken=st.sets(st.integers(min_value=0, max_value=15), max_size=12),
    n=st.integers(min_value=1, max_value=16),
)
def test_perfect_block_is_perfect(topo_name, taken, n):
    """find_perfect_block never lies: any block it returns has exactly n
    distinct free coords AND contiguity exactly 1.0; and whenever it finds
    one, find_contiguous_block must score 1.0 too (it tries perfect
    first)."""
    from kubetpu.plugintypes.mesh import find_perfect_block

    topo = TOPOLOGIES[topo_name]
    all_coords = set(topo.coords())
    taken_coords = {topo.index_coord(i % topo.num_chips) for i in taken}
    free = all_coords - taken_coords
    block = find_perfect_block(set(free), n, topo)
    if block is None:
        return
    assert len(block) == n and len(set(block)) == n
    assert set(block) <= free
    assert contiguity_score(block, topo) == 1.0
    got = find_contiguous_block(set(free), n, topo)
    assert got is not None and got[1] == 1.0


@settings(max_examples=25, deadline=None)
@given(
    ops=st.lists(
        st.tuples(st.booleans(), st.integers(min_value=1, max_value=8)),
        min_size=1, max_size=20,
    ),
)
def test_accounting_invariants_under_random_churn(ops):
    """Any schedule/release sequence keeps the books exact: per node,
    free + chips held by placed pods == capacity, and no advertised value
    ever goes negative."""
    cluster = Cluster()
    for i in range(2):
        cluster.register_node(
            f"n{i}", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
        )
    live = []
    counter = 0
    for is_schedule, size in ops:
        if is_schedule or not live:
            pod = PodInfo(
                name=f"c{counter}",
                running_containers={"m": ContainerInfo(requests={ResourceTPU: size})},
            )
            counter += 1
            try:
                placed = cluster.schedule(pod)
                live.append(placed.name)
            except SchedulingError:
                pass
        else:
            cluster.release(live.pop(size % len(live)))
        for node in cluster.nodes.values():
            held = sum(
                len(p.running_containers["m"].allocate_from)
                for p in node.pods.values()
            )
            assert node.info.allocatable[ResourceTPU] + held == 8
            assert all(v >= 0 for v in node.info.allocatable.values())


@settings(max_examples=25, deadline=None)
@given(
    lows=st.lists(
        st.tuples(st.integers(min_value=1, max_value=4),
                  st.integers(min_value=0, max_value=5)),
        min_size=1, max_size=4,
    ),
    high_size=st.integers(min_value=1, max_value=8),
    high_prio=st.integers(min_value=0, max_value=10),
)
def test_preemption_never_drops_pods(lows, high_size, high_prio):
    """Whatever the sizes/priorities, every pod is either placed, evicted
    (returned to the caller), or the preemptor raises — nothing vanishes."""
    from kubetpu.core.cluster import PriorityKey

    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    placed_lows = []
    for i, (size, prio) in enumerate(lows):
        pod = PodInfo(
            name=f"low{i}",
            running_containers={"m": ContainerInfo(requests={ResourceTPU: size})},
        )
        pod.requests[PriorityKey] = prio
        try:
            cluster.schedule(pod)
            placed_lows.append(pod.name)
        except SchedulingError:
            pass

    high = PodInfo(
        name="high",
        running_containers={"m": ContainerInfo(requests={ResourceTPU: high_size})},
    )
    high.requests[PriorityKey] = high_prio
    try:
        placed, evicted = cluster.schedule_preempting(high)
        survivors = set(cluster.nodes["n0"].pods)
        assert "high" in survivors
        accounted = (survivors - {"high"}) | {p.name for p in evicted}
    except SchedulingError:
        accounted = set(cluster.nodes["n0"].pods)
    assert accounted == set(placed_lows)  # every low pod placed or evicted


@settings(max_examples=40, deadline=None)
@given(
    requests=st.dictionaries(
        st.text(alphabet="abc/0123", min_size=1, max_size=12),
        st.integers(min_value=0, max_value=1 << 30),
        max_size=6,
    ),
    name=st.text(max_size=10),
)
def test_wire_codec_round_trips_any_pod(requests, name):
    import json as json_lib

    from kubetpu.wire import pod_info_from_json, pod_info_to_json

    pod = PodInfo(
        name=name,
        requests=dict(requests),
        running_containers={"m": ContainerInfo(requests=dict(requests))},
    )
    wire = json_lib.loads(json_lib.dumps(pod_info_to_json(pod)))
    back = pod_info_from_json(wire)
    assert back.name == name
    assert back.requests == requests
    assert back.running_containers["m"].requests == requests
