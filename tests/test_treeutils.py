"""Port of the reference tree test (gpuplugintypes/typeutils_test.go:7-34):
ordered insertion must keep children in descending order, verified by
structural compare against a hand-written expected tree."""

from kubetpu.plugintypes import (
    SortedTreeNode,
    add_node_to_sorted_tree_node,
    add_to_sorted_tree_node,
    add_to_sorted_tree_node_with_score,
    compare_tree_node,
    format_tree_node,
)


def test_sorted_tree_node_descending_insert():
    root = SortedTreeNode(val=10)
    child0 = add_to_sorted_tree_node(root, 4)
    child1 = add_to_sorted_tree_node(root, 8)
    add_to_sorted_tree_node(child0, 3)
    add_to_sorted_tree_node(child0, 1)
    add_to_sorted_tree_node(child1, 1)
    add_to_sorted_tree_node(child1, 4)
    add_to_sorted_tree_node(child1, 3)

    expected = SortedTreeNode(
        val=10,
        children=[
            SortedTreeNode(val=8, children=[
                SortedTreeNode(val=4), SortedTreeNode(val=3), SortedTreeNode(val=1)]),
            SortedTreeNode(val=4, children=[
                SortedTreeNode(val=3), SortedTreeNode(val=1)]),
        ],
    )
    assert compare_tree_node(root, expected)


def test_score_breaks_ties():
    root = SortedTreeNode(val=4)
    add_to_sorted_tree_node_with_score(root, 2, 0.5)
    add_to_sorted_tree_node_with_score(root, 2, 0.9)
    add_to_sorted_tree_node_with_score(root, 2, 0.1)
    assert [c.score for c in root.children] == [0.9, 0.5, 0.1]


def test_add_node_keeps_subtree():
    root = SortedTreeNode(val=8)
    sub = SortedTreeNode(val=4, children=[SortedTreeNode(val=2)])
    add_node_to_sorted_tree_node(root, sub)
    add_node_to_sorted_tree_node(root, SortedTreeNode(val=6))
    assert root.children[0].val == 6
    assert root.children[1].children[0].val == 2


def test_compare_tree_node_none_and_shape():
    assert compare_tree_node(None, None)
    assert not compare_tree_node(SortedTreeNode(val=1), None)
    a = SortedTreeNode(val=2, children=[SortedTreeNode(val=1)])
    b = SortedTreeNode(val=2, children=[SortedTreeNode(val=1), SortedTreeNode(val=1)])
    assert not compare_tree_node(a, b)


def test_format_tree_node_indents():
    root = SortedTreeNode(val=2, children=[SortedTreeNode(val=1)])
    assert format_tree_node(root) == "2\n   1"
