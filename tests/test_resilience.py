"""Failure-recovery and concurrency tests.

- Elastic recovery: node failure evicts pods which reschedule elsewhere
  (SURVEY.md §5.3: the reference degrades gracefully within a node and
  leaves cross-node recovery to the core — kubetpu ships the core).
- Threading stress: the scheduler-side caches are documented as
  single-threaded-only in the reference (unsynchronized package globals,
  SURVEY.md §5.2); kubetpu made them locked instances — prove it under
  concurrent add/remove/query.
- Round-7 fault tolerance: the controller's circuit-breaker health state
  machine (suspect nodes recover with ZERO reschedules; dead nodes still
  evict), idempotent re-allocate under injected connection resets, retry
  absorption of transient 5xx, and graceful drain/shutdown.
"""

import threading
import urllib.error

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster, SchedulingError
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.scheduler.treecache import NodeTreeCache
from kubetpu.wire import (
    ControllerServer,
    FaultInjector,
    NodeAgentServer,
    RemoteDevice,
    RoutePolicy,
)
from kubetpu.wire.controller import pod_to_json
from kubetpu.wire.httpcommon import RetryPolicy, request_json


def tpu_pod(name, chips):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})},
    )


def test_fail_node_evicts_and_reschedules():
    cluster = Cluster()
    for i in range(2):
        cluster.register_node(
            f"n{i}", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
        )
    placed = cluster.schedule(tpu_pod("job", 4))
    victim = placed.node_name
    survivor = "n1" if victim == "n0" else "n0"

    evicted = cluster.fail_node(victim)
    assert [p.name for p in evicted] == ["job"]
    assert victim not in cluster.nodes
    # evicted pods are schedulable as-is
    replaced = cluster.schedule(evicted[0])
    assert replaced.node_name == survivor
    assert len(replaced.running_containers["main"].allocate_from) == 4


def test_fail_node_unknown_and_empty():
    cluster = Cluster()
    assert cluster.fail_node("ghost") == []
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    assert cluster.fail_node("n0") == []
    assert not cluster.nodes


def test_gang_reschedule_after_failure():
    cluster = Cluster()
    for h in range(8):
        cluster.register_node(
            f"h{h}", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=h))
        )
    placed = cluster.schedule_gang([tpu_pod(f"w{i}", 8) for i in range(4)])
    victim = placed[0].node_name
    evicted = cluster.fail_node(victim)
    assert len(evicted) == 1
    # rescheduling the evicted worker lands on a free host
    again = cluster.schedule(evicted[0])
    assert again.node_name != victim


def _node_res(i):
    # alternate between two topology shapes
    shape = {"A": {"0": [0, 1], "1": [2, 3]}} if i % 2 else {"A": {"0": [0, 1, 2, 3]}}
    out = {}
    for g1, g0s in shape.items():
        for g0, devs in g0s.items():
            for d in devs:
                out[f"resource/group/tpugrp1/{g1}/tpugrp0/{g0}/tpu/{d}/cards"] = 1
    return out


def test_treecache_threading_stress():
    cache = NodeTreeCache("tpugrp", "cards", levels=1)
    errors = []

    def worker(tid):
        try:
            for i in range(200):
                name = f"node-{tid}-{i % 10}"
                cache.add_resources(name, _node_res(i))
                cache.find_best_tree(2)
                if i % 3 == 0:
                    cache.remove_node(name)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    # cache still coherent: at most 2 distinct shapes survive
    assert len(cache.shapes()) <= 2


def test_cluster_concurrent_schedule_release():
    """Concurrent scheduling against one cluster must never double-book a
    chip. The Cluster itself serializes via per-call locking in the caches;
    here threads race schedule/release cycles."""
    cluster = Cluster()
    for i in range(4):
        cluster.register_node(
            f"n{i}", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
        )
    lock = threading.Lock()  # serialize cluster mutations as the core loop would
    errors = []

    def worker(tid):
        try:
            for i in range(25):
                name = f"pod-{tid}-{i}"
                with lock:
                    try:
                        cluster.schedule(tpu_pod(name, 2))
                    except SchedulingError:
                        continue
                with lock:
                    cluster.release(name)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for node in cluster.nodes.values():
        assert node.info.allocatable[ResourceTPU] == 8
        assert not node.pods


def test_preemption_evicts_lower_priority():
    from kubetpu.core.cluster import PriorityKey

    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    # fill with two low-priority pods
    low1 = tpu_pod("low1", 4)
    low2 = tpu_pod("low2", 4)
    cluster.schedule(low1)
    cluster.schedule(low2)

    # high-priority 4-chip pod: evicts exactly one victim
    high = tpu_pod("high", 4)
    high.requests[PriorityKey] = 10
    placed, evicted = cluster.schedule_preempting(high)
    assert placed.node_name == "n0"
    assert len(evicted) == 1 and evicted[0].name in ("low1", "low2")
    assert "high" in cluster.nodes["n0"].pods
    # evicted pod is schedulable form (no stale placement)
    assert not any(
        c.allocate_from for c in evicted[0].running_containers.values()
    )


def test_preemption_refuses_equal_priority():
    from kubetpu.core.cluster import PriorityKey

    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    a = tpu_pod("a", 8)
    a.requests[PriorityKey] = 5
    cluster.schedule(a)
    b = tpu_pod("b", 4)
    b.requests[PriorityKey] = 5  # equal, not higher
    try:
        cluster.schedule_preempting(b)
        assert False, "equal priority must not preempt"
    except SchedulingError:
        pass
    assert "a" in cluster.nodes["n0"].pods  # victim untouched


def test_preemption_no_eviction_when_fits():
    from kubetpu.core.cluster import PriorityKey

    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    cluster.schedule(tpu_pod("low", 4))
    high = tpu_pod("high", 2)
    high.requests[PriorityKey] = 10
    placed, evicted = cluster.schedule_preempting(high)
    assert evicted == []  # fits without touching anyone
    assert "low" in cluster.nodes["n0"].pods


def test_preemption_evicts_minimum_set():
    from kubetpu.core.cluster import PriorityKey

    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    for i in range(4):
        p = tpu_pod(f"low{i}", 2)
        p.requests[PriorityKey] = i  # priorities 0..3
    
        cluster.schedule(p)
    high = tpu_pod("high", 2)
    high.requests[PriorityKey] = 10
    placed, evicted = cluster.schedule_preempting(high)
    assert len(evicted) == 1
    assert evicted[0].name == "low0"  # cheapest victim first


def _fragment_node(cluster, node_name, keep_coords):
    """Schedule 8 single-chip pods on a v5e-8 node, then release those whose
    chip landed outside keep_coords — leaving exactly keep_coords occupied."""
    placed = {}
    for i in range(8):
        p = cluster.schedule(tpu_pod(f"frag{i}", 1), lambda n: n == node_name)
        _t, coords = cluster.pod_chip_coords(p)
        placed[coords[0]] = p.name
    for coord, pname in placed.items():
        if coord not in keep_coords:
            cluster.release(pname)
    return placed


def test_defrag_plan_and_execute():
    cluster = Cluster()
    for i in range(2):
        cluster.register_node(
            f"n{i}", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
        )
    # fragment n0: occupied at (0,1) and (1,2) -> free 6 chips but no 2x3/3x2
    # ... and specifically no contiguous 6-block
    occupied = {(0, 1), (1, 2)}
    _fragment_node(cluster, "n0", occupied)
    # fill n1 partially so re-placement is non-trivial but possible
    cluster.schedule(tpu_pod("n1pod", 4), lambda n: n == "n1")

    from kubetpu.plugintypes.mesh import TOPOLOGIES, find_perfect_block

    st_free = {c for c in TOPOLOGIES["v5e-8"].coords() if c not in occupied}
    # 6 free chips but no 2x3/3x2/1x6 rectangle: fragmented
    assert find_perfect_block(st_free, 6, TOPOLOGIES["v5e-8"]) is None

    plan = cluster.defrag_plan(6)
    assert plan, plan  # non-empty migration list
    assert all(m.from_node == "n0" for m in plan)
    assert all(m.to_node in ("n0", "n1") for m in plan)  # intra-node moves allowed

    moved, placed = cluster.execute_defrag(plan, pending=tpu_pod("big6", 6))
    assert all(p.node_name in ("n0", "n1") for p in moved)
    # the pending pod got the opened perfect block
    assert placed.node_name == "n0"
    assert cluster.gang_contiguity([placed]) == 1.0
    # nobody was dropped: both fragments and the n1 pod still exist
    all_pods = {p for n in cluster.nodes.values() for p in n.pods}
    assert {"big6", "n1pod"} <= all_pods
    assert len(all_pods) == 2 + len(moved)


def test_defrag_plan_empty_when_fits():
    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    assert cluster.defrag_plan(4) == []


def test_defrag_plan_none_when_capacity_short():
    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    cluster.schedule(tpu_pod("a", 6))
    assert cluster.defrag_plan(4) is None  # only 2 free anywhere, no 2nd node


def test_preemption_rollback_when_other_dimension_rejects():
    """The geometric feasibility pre-check is TPU-only: when the pinned
    schedule after eviction is rejected on another dimension (the pod also
    wants GPUs the node lacks), the already-evicted victims must be restored
    with their chips, never dropped (ADVICE r1 medium)."""
    from kubetpu.core.cluster import PriorityKey
    from kubetpu.plugintypes import ResourceGPU

    cluster = Cluster()
    cluster.register_node(
        "n0", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    cluster.schedule(tpu_pod("low", 8))

    greedy = PodInfo(
        name="greedy",
        running_containers={
            "main": ContainerInfo(requests={ResourceTPU: 8, ResourceGPU: 1})
        },
    )
    greedy.requests[PriorityKey] = 10
    try:
        cluster.schedule_preempting(greedy)
        assert False, "must not place a pod whose GPU leg can never fit"
    except SchedulingError:
        pass
    assert "low" in cluster.nodes["n0"].pods  # victim restored
    assert cluster.nodes["n0"].info.allocatable[ResourceTPU] == 0  # chips held


# -- Round-7: circuit breaker, idempotency, retry, graceful drain ------------


def _breaker_stack(dead_after=3, **kw):
    """One live agent + controller with the default (multi-miss) breaker."""
    agent = NodeAgentServer(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")),
        "n0", faults=FaultInjector(seed=0),
    )
    agent.start()
    controller = ControllerServer(poll_interval=3600, dead_after=dead_after,
                                  **kw)
    controller.start()
    controller.register_agent(agent.address)
    return controller, agent


def test_breaker_suspect_recovers_without_reschedule():
    """A transient blackout shorter than dead_after: pods stay placed, the
    node is health-cordoned while suspect, and recovery (probation ->
    healthy) lifts the cordon — zero evictions, zero reschedules."""
    controller, agent = _breaker_stack()
    try:
        out = controller._submit({"pod": pod_to_json(tpu_pod("job", 4))})
        assert out["placements"][0]["node"] == "n0"
        agent.faults.set_default(RoutePolicy(drop=1.0))  # total blackout
        for _ in range(2):  # < dead_after=3
            result = controller.poll_once()
            assert result["failed_nodes"] == []
            assert result["rescheduled"] == []
        with controller._lock:
            assert controller._health_state("n0") == "suspect"
            assert "n0" in controller.cluster.cordoned   # no NEW work
            assert "job" in controller.cluster.nodes["n0"].pods  # pods kept
        agent.faults.clear()
        controller.poll_once()
        with controller._lock:
            assert controller._health_state("n0") == "probation"
            assert "n0" in controller.cluster.cordoned   # still proving itself
        controller.poll_once()
        with controller._lock:
            assert controller._health_state("n0") == "healthy"
            assert "n0" not in controller.cluster.cordoned
            assert "job" in controller.cluster.nodes["n0"].pods
        assert controller.cluster.check_invariants() == []
    finally:
        controller.shutdown()
        agent.shutdown()


def test_breaker_dead_node_still_evicts():
    """dead_after consecutive misses must still trip the breaker: the node
    is failed and its pods reschedule (here: pend — no other node)."""
    controller, agent = _breaker_stack()
    try:
        controller._submit({"pod": pod_to_json(tpu_pod("job", 4))})
        agent.shutdown()  # real death, not a blip
        results = [controller.poll_once() for _ in range(3)]
        assert results[0]["failed_nodes"] == results[1]["failed_nodes"] == []
        assert results[2]["failed_nodes"] == ["n0"]
        assert "n0" not in controller.cluster.nodes
        assert controller.pending_pods == ["job"]  # evicted, awaiting capacity
    finally:
        controller.shutdown()


def test_breaker_operator_cordon_survives_recovery():
    """Recovery must lift only the cordon the BREAKER placed: a node the
    operator cordoned before/while suspect stays cordoned after it heals."""
    controller, agent = _breaker_stack()
    try:
        with controller._lock:
            controller.cluster.cordon("n0")  # operator's own cordon
        agent.faults.set_default(RoutePolicy(drop=1.0))
        controller.poll_once()
        with controller._lock:
            assert controller._health_state("n0") == "suspect"
        agent.faults.clear()
        controller.poll_once()
        controller.poll_once()
        with controller._lock:
            assert controller._health_state("n0") == "healthy"
            assert "n0" in controller.cluster.cordoned  # operator's, untouched
    finally:
        controller.shutdown()
        agent.shutdown()


def test_idempotent_reallocate_under_connection_reset():
    """The ISSUE's double-allocation window: the agent processes /allocate
    but the response dies mid-write (injected partial). The client retry
    must be REPLAYED from the dedup window — the device allocates once."""
    agent = NodeAgentServer(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")), "n0",
        faults=FaultInjector(
            seed=3, routes={"/allocate": RoutePolicy(partial=1.0, times=1)}),
    )
    agent.start()
    try:
        cluster = Cluster()
        cluster.register_remote_node(agent.address)
        cluster.schedule(tpu_pod("p", 4))
        result = cluster.allocate("p")
        env = next(iter(result.values()))[2]
        assert env["TPU_VISIBLE_DEVICES"].count(",") == 3
        assert agent.counters["allocate_requests"] == 1  # executed ONCE
        assert agent.counters["allocate_replays"] == 1   # retry replayed
    finally:
        agent.shutdown()


def test_retry_absorbs_transient_5xx_and_drops():
    """A couple of injected 503s/drops on the probe route must cost a
    backoff, not an AgentUnreachable: the call succeeds within its retry
    budget."""
    agent = NodeAgentServer(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")), "n0",
        faults=FaultInjector(
            seed=1, routes={"/nodeinfo": RoutePolicy(error=1.0, times=2)}),
    )
    agent.start()
    try:
        dev = RemoteDevice(
            agent.address,
            retry=RetryPolicy(attempts=4, base_delay=0.01, deadline=10.0),
        )
        dev.start()
        from kubetpu.api.types import new_node_info

        info = new_node_info("n0")
        dev.update_node_info(info)  # 2 injected 503s, then success
        assert info.capacity.get(ResourceTPU) == 8
        assert agent.faults.counts.get("error") == 2
    finally:
        agent.shutdown()


def test_agent_graceful_drain_and_shutdown():
    """drain(): liveness keeps answering (flagged), reads work, mutating
    work is refused 503; graceful shutdown finishes cleanly."""
    agent = NodeAgentServer(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")), "n0")
    agent.start()
    try:
        dev = RemoteDevice(agent.address)
        dev.start()
        agent.drain()
        health = request_json(agent.address + "/healthz")
        assert health["ok"] and health["draining"]
        # reads still served
        assert request_json(agent.address + "/nodeinfo")["capacity"]
        # mutating work refused with a retryable status
        pod = tpu_pod("p", 1)
        with pytest.raises(urllib.error.HTTPError) as e:
            request_json(
                agent.address + "/allocate",
                {"pod": pod_to_json(pod), "container": "main"},
            )
        assert e.value.code == 503
    finally:
        agent.shutdown()  # graceful default: waits for in-flight work


def test_controller_drain_server_refuses_new_work():
    controller, agent = _breaker_stack()
    try:
        controller.drain_server()
        health = request_json(controller.address + "/healthz")
        assert health["ok"] and health["draining"]
        assert request_json(controller.address + "/status")["nodes"]  # reads ok
        with pytest.raises(urllib.error.HTTPError) as e:
            request_json(controller.address + "/pods",
                         {"pod": pod_to_json(tpu_pod("p", 1))})
        assert e.value.code == 503
    finally:
        controller.shutdown()
        agent.shutdown()


def test_breaker_counts_consecutive_misses_only():
    """dead_after counts CONSECUTIVE misses: a flapping node (miss, ok,
    miss, ok, ...) must never accumulate toward suspect or dead — each
    clean probe zeroes the streak, whatever the thresholds."""
    controller, agent = _breaker_stack(dead_after=3, suspect_after=2)
    try:
        controller._submit({"pod": pod_to_json(tpu_pod("job", 4))})
        for _ in range(4):  # 4x (miss, ok) = 4 non-consecutive misses
            agent.faults.set_default(RoutePolicy(drop=1.0))
            result = controller.poll_once()
            assert result["failed_nodes"] == []
            agent.faults.clear()
            controller.poll_once()
        with controller._lock:
            # never even reached suspect_after=2 consecutively
            assert controller._health_state("n0") == "healthy"
            assert "n0" not in controller.cluster.cordoned
            assert "job" in controller.cluster.nodes["n0"].pods
    finally:
        controller.shutdown()
        agent.shutdown()


def test_keyed_replay_served_while_draining():
    """A keyed retry of an ALREADY-COMMITTED allocate must get its replay
    even mid-drain (replay mutates nothing; refusing it would leak the
    committed chips when the caller rolls back). New work still 503s."""
    agent = NodeAgentServer(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")), "n0")
    agent.start()
    try:
        cluster = Cluster()
        cluster.register_remote_node(agent.address)
        placed = cluster.schedule(tpu_pod("p", 2))
        from kubetpu.wire.codec import pod_info_to_json

        body = {"pod": pod_info_to_json(
            cluster.nodes["n0"].pods["p"]), "container": "main"}
        out = request_json(agent.address + "/allocate", body,
                           idempotency_key="k-drain")
        agent.drain()
        # committed key: replayed verbatim despite draining
        again = request_json(agent.address + "/allocate", body,
                             idempotency_key="k-drain")
        assert again == out
        assert agent.counters["allocate_requests"] == 1
        assert agent.counters["allocate_replays"] == 1
        # new work: refused with the retryable draining status
        with pytest.raises(urllib.error.HTTPError) as e:
            request_json(agent.address + "/allocate", body,
                         idempotency_key="k-fresh")
        assert e.value.code == 503
        assert agent.counters["allocate_requests"] == 1  # never executed
    finally:
        agent.shutdown()


def test_reregister_resets_breaker_state():
    """Re-registering an agent at the same URL (idempotent path) proves it
    alive: the miss streak resets and the health cordon lifts — a freshly
    verified node must not sit one blip from eviction."""
    controller, agent = _breaker_stack()
    try:
        agent.faults.set_default(RoutePolicy(drop=1.0))
        controller.poll_once()
        controller.poll_once()  # 2 misses: one short of dead_after=3
        with controller._lock:
            assert controller._health_state("n0") == "suspect"
        agent.faults.clear()
        assert controller.register_agent(agent.address) == "n0"
        with controller._lock:
            assert controller._health_state("n0") == "healthy"
            assert "n0" not in controller.cluster.cordoned
        # one fresh blip must NOT evict (streak restarted)
        agent.faults.set_default(RoutePolicy(drop=1.0))
        result = controller.poll_once()
        assert result["failed_nodes"] == []
        assert result["suspect_nodes"] == ["n0"]
    finally:
        controller.shutdown()
        agent.shutdown()


def test_keyed_submit_classifies_dead_agent_as_503_until_restart():
    """Round-20 retry-classification pin: a keyed submit whose agent
    wire leg dies at the CONNECTION level (the agent was hard-killed)
    must surface as 503 infra-transient — never a deterministic 500,
    which would poison the client's idempotent retry budget. After the
    agent restarts at the SAME address (the kill-then-restart window:
    refused turns into reset/torn responses as the port rebinds), the
    SAME keyed submit must succeed cleanly — the rolled-back first
    attempt left no placement behind."""
    from kubetpu.wire.httpcommon import NO_RETRY

    agent = NodeAgentServer(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")), "n0")
    agent.start()
    host, port = agent.address.rsplit("//", 1)[1].rsplit(":", 1)
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    agent2 = None
    try:
        controller.register_agent(agent.address)
        agent.shutdown(graceful=False)  # SIGKILL analog: port goes dark

        body = {"pod": pod_to_json(tpu_pod("p-503", 4))}
        with pytest.raises(urllib.error.HTTPError) as e:
            request_json(controller.address + "/pods", body,
                         idempotency_key="k-503", retry=NO_RETRY)
        assert e.value.code == 503  # retryable infra verdict, not 500
        # all-or-nothing: the rolled-back submit left nothing placed
        assert "p-503" not in controller.cluster.nodes["n0"].pods
        assert "p-503" not in controller.pending_pods

        agent2 = NodeAgentServer(
            new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8")), "n0",
            host=host, port=int(port))
        agent2.start()
        out = request_json(controller.address + "/pods", body,
                           idempotency_key="k-503")
        assert out["placements"][0]["pod"] == "p-503"
        assert "p-503" in controller.cluster.nodes["n0"].pods
    finally:
        controller.shutdown()
        if agent2 is not None:
            agent2.shutdown()
