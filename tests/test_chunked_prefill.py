"""Chunked prefill under a token budget + the double-buffered host loop.

The contract the tentpole rests on: a server admitting prompts in bounded
chunks interleaved with decode steps (``prefill_budget > 0``) must be
TOKEN-EXACT against the monolithic-prefill server — greedy and seeded
sampling, dense and paged, windowed and unwindowed — because the chunks
write bit-identical cache contents and the sampling keys are
request-deterministic (position-keyed, never stream-keyed). The overlap
loop (dispatch step N+1 before materializing step N) must change WHEN
tokens surface, never WHICH tokens."""

import jax
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.paged import PagedDecodeServer
from kubetpu.jobs.serving import DecodeServer

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)

PROMPTS = [[3, 14, 15, 9, 2, 6, 5], [26, 5],
           [(i * 7) % 60 + 1 for i in range(19)]]


KW = dict(n_slots=2, max_seq=64, max_new_tokens=6)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def mono_dense(params):
    """The monolithic dense reference run, shared by every parity test
    (one server, one set of compiles)."""
    return run_schedule(DecodeServer(CFG, params, **KW))


def run_schedule(server, prompts=PROMPTS, sampling=None, interleave=2):
    """Enqueue prompts staggered across live steps, then drain — the
    mixed-load shape (prompts arriving mid-decode) chunking exists for."""
    rids = []
    for p in prompts:
        rids.append(server.enqueue(p, sampling=sampling))
        for _ in range(interleave):
            server.step()
    server.drain()
    return [server.result(r) for r in rids]


@pytest.mark.parametrize("budget", [1, 3])
def test_chunked_greedy_token_exact_vs_monolithic(params, mono_dense, budget):
    """Greedy parity across chunk budgets: a budget of one token
    (maximal chunking) and a non-power-of-two budget (grid flooring +
    the padded final tail)."""
    chunked = DecodeServer(CFG, params, prefill_budget=budget, **KW)
    assert run_schedule(chunked) == mono_dense


def test_chunked_seeded_sampling_token_exact_vs_monolithic(params,
                                                           mono_dense):
    """Seeded stochastic sampling is chunking-invariant: the key for a
    request's token at position q is (seed, rid, q)-derived, so the
    chunked and monolithic servers draw IDENTICAL streams even though
    their step alignment differs."""
    kw = dict(KW, seed=7)
    sampling = {"temperature": 1.0, "top_k": 12}
    mono = run_schedule(DecodeServer(CFG, params, **kw), sampling=sampling)
    chunked = run_schedule(DecodeServer(CFG, params, prefill_budget=3, **kw),
                           sampling=sampling)
    assert chunked == mono
    # the draws are actually stochastic (not greedy in disguise)
    assert mono != mono_dense


def test_chunked_windowed_chunk_boundary_mid_window(params):
    """Banded config: budget 4 against window 8 puts chunk boundaries
    mid-window, so later chunks must attend earlier chunks' cache entries
    through the band — token-exact vs the monolithic banded server."""
    import dataclasses

    wcfg = dataclasses.replace(CFG, window=8)
    kw = dict(n_slots=2, max_seq=64, max_new_tokens=8)
    mono = DecodeServer(wcfg, params, **kw)
    chunked = DecodeServer(wcfg, params, prefill_budget=4, **kw)
    assert run_schedule(chunked) == run_schedule(mono)


def test_chunked_paged_token_exact_vs_monolithic_and_dense(params,
                                                           mono_dense):
    """Paged chunked prefill (page-aligned chunks through the pool via
    forward_chunk_io) matches both the monolithic paged server and the
    dense server exactly."""
    mono = run_schedule(PagedDecodeServer(CFG, params, page_size=4, **KW))
    chunked = run_schedule(PagedDecodeServer(CFG, params, page_size=4,
                                             prefill_budget=8, **KW))
    assert chunked == mono == mono_dense


def test_chunked_paged_windowed_ring(params):
    """window x page ring x chunked prefill composes: the ring maps up
    front, chunks stream through aliased pages, tokens exactly match the
    monolithic windowed paged server."""
    import dataclasses

    wcfg = dataclasses.replace(CFG, window=8)
    kw = dict(n_slots=2, max_seq=96, max_new_tokens=8, page_size=4)
    mono = run_schedule(PagedDecodeServer(wcfg, params, **kw))
    chunked = run_schedule(PagedDecodeServer(wcfg, params, prefill_budget=8,
                                             **kw))
    assert chunked == mono


def test_chunk_granular_page_reservation_under_pressure(params):
    """During a chunked prefill the slot holds pages for the tokens
    written so far, NOT the worst case — so a long admission streams in
    next to a decoding neighbor that a monolithic worst-case reservation
    would have blocked behind, and the final chunk still upgrades to the
    decode worst case before the first token."""
    ps = 4
    long_prompt = [(i * 5) % 60 + 1 for i in range(16)]
    short = [7, 8]
    # worst cases: long = ceil((16+4+1)/4) = 6 pages, short = 2 pages
    srv = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=4, page_size=ps, n_pages=7,
                            prefill_budget=ps)
    rs = srv.submit(short)               # decoding: holds its 2 pages
    rl = srv.enqueue(long_prompt)
    srv.step()
    # one chunk (4 tokens = 1 page) in flight: 2 (short) + 1, not 2 + 6
    assert srv.pages_in_use() == 3
    assert not srv.finished(rl)
    srv.step()
    assert srv.pages_in_use() == 4       # second chunk, still not worst case
    srv.drain()
    assert srv.finished(rs) and srv.finished(rl)
    assert srv.pages_in_use() == 0
    # parity: the streamed-in request decodes exactly the monolithic tokens
    ref = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=4, page_size=ps)
    rr = ref.submit(long_prompt)
    ref.drain()
    assert srv.result(rl) == ref.result(rr)


def test_prefill_deadlock_parks_younger_back_to_queue(params):
    """Two chunked prefills contending for a pool with no decoder left to
    free pages must NOT deadlock: the scheduler parks the younger back to
    the queue (pages released), the older completes, then the parked one
    runs — both finish with exact monolithic tokens."""
    ps = 4
    p1 = [(i * 3) % 60 + 1 for i in range(12)]   # worst case 5 pages
    p2 = [(i * 11) % 60 + 1 for i in range(12)]
    srv = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=3, page_size=ps, n_pages=5,
                            prefill_budget=64)
    r1, r2 = srv.enqueue(p1), srv.enqueue(p2)
    srv.drain()
    assert srv.finished(r1) and srv.finished(r2)
    assert srv.pages_in_use() == 0
    ref = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=3, page_size=ps)
    for rid, p in ((r1, p1), (r2, p2)):
        rr = ref.submit(p)
        ref.drain()
        assert srv.result(rid) == ref.result(rr)


def test_cancel_mid_prefill_releases_slot_and_pages(params):
    srv = PagedDecodeServer(CFG, params, n_slots=1, max_seq=64,
                            max_new_tokens=4, page_size=4, prefill_budget=4)
    rid = srv.enqueue([(i * 7) % 60 + 1 for i in range(16)])
    srv.step()                           # first chunk only
    assert not srv.finished(rid) and srv.pages_in_use() > 0
    assert srv.cancel(rid) is True
    assert srv.finished(rid)
    assert srv.pages_in_use() == 0       # chunk-granular pages reclaimed
    # the freed slot serves the next request exactly
    r2 = srv.submit([3, 14, 15, 9])
    srv.drain()
    ref = PagedDecodeServer(CFG, params, n_slots=1, max_seq=64,
                            max_new_tokens=4, page_size=4)
    rr = ref.submit([3, 14, 15, 9])
    ref.drain()
    assert srv.result(r2) == ref.result(rr)


def test_overlap_tokens_identical_and_lagged(params, mono_dense):
    """overlap=True changes WHEN tokens surface (one step later), never
    WHICH tokens — drained results are identical, and the first step
    after admission routes only the deferred first token (the decode
    token is still in flight)."""
    sync = mono_dense
    # chunked + overlap together (the bench configuration)
    both = run_schedule(DecodeServer(CFG, params, overlap=True,
                                     prefill_budget=4, **KW))
    assert both == sync

    srv = DecodeServer(CFG, params, overlap=True, **KW)
    p = PROMPTS[0]
    rid = srv.enqueue(p)
    out1 = srv.step()
    # first token only: this step's decode token is still in flight
    assert out1[rid] == sync[0][len(p):len(p) + 1]
    out2 = srv.step()
    # step 1's decode token surfaces one step late
    assert out2[rid] == sync[0][len(p) + 1:len(p) + 2]
    srv.drain()
    assert srv.result(rid) == sync[0]    # pure-overlap parity end to end


def test_overlap_dispatches_ahead_of_materialization(params, monkeypatch):
    """The no-per-token-host-sync pin: with overlap on, step N+1 is
    DISPATCHED before step N's tokens are materialized (event order
    dispatch, dispatch, route, dispatch, route, ...), the un-materialized
    step is held in flight across the step() boundary, and
    jax.block_until_ready never runs on the hot path."""
    events = []

    class Probe(DecodeServer):
        def _device_step(self):
            events.append("dispatch")
            return super()._device_step()

        def _route_step(self, handle, out):
            events.append("route")
            return super()._route_step(handle, out)

    srv = Probe(CFG, params, n_slots=2, max_seq=64, max_new_tokens=16,
                overlap=True)
    srv.warmup()

    blocks = []
    real = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda *a, **k: blocks.append(1) or real(*a, **k))
    srv.submit([3, 14, 15, 9])
    for _ in range(4):
        srv.step()
        assert srv._inflight is not None   # a step is ALWAYS in flight
    assert events == ["dispatch", "dispatch", "route", "dispatch", "route",
                      "dispatch", "route"]
    assert blocks == []                    # no block_until_ready per token
    srv.drain()

    # the sync server, by contrast, routes every dispatch immediately
    events.clear()
    ref = Probe(CFG, params, n_slots=2, max_seq=64, max_new_tokens=4)
    ref.submit([3, 14, 15, 9])
    ref.step()
    ref.step()
    assert events == ["dispatch", "route", "dispatch", "route"]


@pytest.mark.slow
def test_chunked_multi_lora_applies_adapter_per_chunk(params):
    """Multi-LoRA rides chunked prefill: the adapter binds at prefill
    begin and every chunk applies it, so the chunked multi-tenant server
    matches the monolithic one exactly, per adapter."""
    from kubetpu.jobs.lora import LoraConfig, init_lora_params
    from kubetpu.jobs.multi_lora import MultiLoraDecodeServer, stack_adapters

    lcfg = LoraConfig(rank=4, alpha=8.0)

    def adapter(seed):
        lora = init_lora_params(jax.random.PRNGKey(seed), CFG, lcfg)
        keys = jax.random.split(jax.random.PRNGKey(seed + 100), 4)
        for i, t in enumerate(lcfg.targets):
            b = lora["blocks"][f"{t}_b"]
            lora["blocks"][f"{t}_b"] = (
                jax.random.normal(keys[i], b.shape, b.dtype) * 0.05)
        return lora

    stack = stack_adapters(lcfg, [adapter(1), adapter(2)])
    kw = dict(n_slots=2, max_seq=64, max_new_tokens=5)

    def run(server):
        ra = server.enqueue(PROMPTS[0], adapter=1)
        server.step()
        rb = server.enqueue(PROMPTS[1], adapter=0)
        server.drain()
        return [server.result(r) for r in (ra, rb)]

    mono = run(MultiLoraDecodeServer(CFG, params, lcfg, stack, **kw))
    chunked = run(MultiLoraDecodeServer(CFG, params, lcfg, stack,
                                        prefill_budget=2, **kw))
    assert chunked == mono


@pytest.mark.slow
def test_paged_budgeted_warmup_and_long_admission(params):
    """A budgeted paged server's warmup pre-compiles the resumed-chunk
    (chunk, gather-prefix) shapes too; a long admission after warmup
    streams through them and still matches the monolithic tokens."""
    p = [(i * 3) % 60 + 1 for i in range(24)]
    srv = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=4, page_size=4, prefill_budget=8)
    srv.warmup()
    rid = srv.enqueue(p)
    srv.drain()
    assert srv.finished(rid)
    ref = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=4, page_size=4)
    rr = ref.submit(p)
    ref.drain()
    assert srv.result(rid) == ref.result(rr)


def test_prefill_chunk_metrics_recorded(params):
    """The token-budget scheduler reports its work: per-chunk timings
    land under "prefill_chunk", admission_stall still counts one entry
    per admission (the summed chunk cost)."""
    srv = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=4,
                       prefill_budget=4)
    rid = srv.enqueue([(i * 7) % 60 + 1 for i in range(13)])  # 4 chunks
    srv.drain()
    assert srv.finished(rid)
    stats = srv.metrics_summary()
    assert stats["prefill_chunk"]["count"] == 4   # 4 + 4 + 4 + 1 tokens
    assert stats["admission_stall"]["count"] == 1
