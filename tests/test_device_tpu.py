"""TPU device manager tests — the fake-backend fixture strategy of the
reference (nvidia_gpu_manager_test.go, SURVEY.md §4 item 3) applied to TPU:
canned v5e topologies, no hardware."""

from kubetpu.api.types import ContainerInfo, NodeInfo, PodInfo
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.plugintypes.mesh import TOPOLOGIES


def _expected_chip_prefix(i):
    # v5e-8 host 2x4 tiles into two 2x2 blocks: block = y//2 for local
    # row-major ids (0,1,4,5 -> block 0; 2,3,6,7 -> block 1).
    topo = TOPOLOGIES["v5e-8"]
    x, y = topo.host_coords(0)[i]
    blk = (x // 2) * 2 + (y // 2)
    return f"resource/group/tpugrp1/0/tpugrp0/{blk}/tpu/{i}"


def test_update_node_info_advertises_v5e8():
    info = make_fake_tpus_info("v5e-8")
    mgr = new_fake_tpu_dev_manager(info)
    node = NodeInfo(name="n0")
    mgr.update_node_info(node)

    hbm = TOPOLOGIES["v5e-8"].hbm_bytes_per_chip
    expected = {ResourceTPU: 8, "resource/group/tpu-slice/v5e-8/slice0/0": 1}
    for i in range(8):
        expected[_expected_chip_prefix(i) + "/cards"] = 1
        expected[_expected_chip_prefix(i) + "/memory"] = hbm
        # Round-18 vChips: fractional capacity advertised per chip
        expected[_expected_chip_prefix(i) + "/milli"] = 1000
    assert node.capacity == expected
    assert node.allocatable == expected
    assert node.kube_cap == {ResourceTPU: 8}
    assert node.kube_alloc == {ResourceTPU: 8}


def test_missing_chip_degrades_gracefully():
    # chip 3 absent (failed device) -> 7 chips advertised, no chip-3 keys
    # (the reference's disappearing-device contract, SURVEY.md §5.3).
    info = make_fake_tpus_info("v5e-8", missing_chips=(3,))
    mgr = new_fake_tpu_dev_manager(info)
    node = NodeInfo(name="n0")
    mgr.update_node_info(node)
    assert node.capacity[ResourceTPU] == 7
    assert not any("/tpu/3/" in k for k in node.capacity)


def test_in_use_survives_rediscovery():
    info = make_fake_tpus_info("v5e-8")
    mgr = new_fake_tpu_dev_manager(info)
    mgr.start()
    some_id = next(iter(mgr.tpus))
    mgr.tpus[some_id].in_use = True
    mgr.update_tpu_info()  # re-probe (reference :142-145)
    assert mgr.tpus[some_id].in_use


def test_allocate_emits_devices_and_libtpu_env():
    info = make_fake_tpus_info("v5e-8")
    mgr = new_fake_tpu_dev_manager(info)
    mgr.start()

    cont = ContainerInfo()
    # AllocateFrom: flat request key -> node's advertised chip key
    for frm, to in [(0, 0), (1, 1), (2, 4), (3, 5)]:
        cont.allocate_from[f"resource/group/tpu/{frm}/cards"] = (
            _expected_chip_prefix(to) + "/cards"
        )
    mounts, devices, env = mgr.allocate(PodInfo(name="p"), cont)
    assert devices == ["/dev/accel0", "/dev/accel1", "/dev/accel4", "/dev/accel5"]
    assert env["TPU_VISIBLE_DEVICES"] == "0,1,4,5"
    # chips (0,0),(0,1),(1,0),(1,1): a 2x2 sub-slice bounding box
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
    assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"
    assert env["TPU_WORKER_ID"] == "0"


def test_allocate_empty_allocate_from():
    mgr = new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    mgr.start()
    assert mgr.allocate(PodInfo(), ContainerInfo()) == ([], [], {})


def test_multi_host_slice_host_index():
    # host 3 of a v5e-64 slice advertises its own host index and global
    # coordinates (the gang scheduler's global frame).
    info = make_fake_tpus_info("v5e-64", host_index=3)
    mgr = new_fake_tpu_dev_manager(info)
    node = NodeInfo(name="host3")
    mgr.update_node_info(node)
    assert node.capacity["resource/group/tpu-slice/v5e-64/slice0/3"] == 1
    assert node.capacity[ResourceTPU] == 8
    assert any(k.startswith("resource/group/tpugrp1/3/") for k in node.capacity)
    _, _, env = _alloc_all(mgr)
    assert env["TPU_WORKER_ID"] == "3"


def _alloc_all(mgr):
    cont = ContainerInfo()
    for chip in mgr.tpus.values():
        cont.allocate_from[f"resource/group/tpu/{chip.index}/cards"] = (
            "resource/group/" + chip.name + "/cards"
        )
    return mgr.allocate(PodInfo(name="p"), cont)


def test_probe_failure_starts_with_zero_chips():
    class BoomPlugin:
        def get_tpu_info(self):
            raise RuntimeError("libtpu exploded")

    from kubetpu.device.tpu_manager import TpuDevManager

    mgr = TpuDevManager(plugin=BoomPlugin())
    mgr.new()
    mgr.start()  # must not raise (reference Start, :185-188)
    assert mgr.num_tpus == 0
