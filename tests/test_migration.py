"""Round-16 live KV migration: token-exact slot handoff between paged
replicas, and the drain/failover/scale-down paths that use it.

The serving-layer half (snapshot/restore round trips — f32 + kv_int8
pools, prefix-cache shared pages, mid-chunked-prefill refusal, spec
gamma-EMA survival) drives the servers in-process; the wire half
(chunked idempotent ``/migrate_in``, replayed commit-acks, the epoch
fence, drain-with-migration and the drain-timeout escalation) runs real
``ReplicaServer``s over HTTP. The chaos-grade fault soak lives in
``make migrate-check`` (scripts/migrate_check.py)."""

import json
import threading
import time
import urllib.error

import jax
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.paged import PagedDecodeServer
from kubetpu.jobs.spec_serving import PagedSpeculativeDecodeServer
from kubetpu.router import ReplicaServer, RouterServer
from kubetpu.router.migration import (
    blob_chunks,
    chunk_b64,
    decode_snapshot,
    encode_snapshot,
)
from kubetpu.wire.httpcommon import NO_RETRY, request_json

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def make_server(params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_seq", 64)
    kw.setdefault("max_new_tokens", 12)
    kw.setdefault("page_size", PS)
    return PagedDecodeServer(CFG, params, **kw)


def quiet_run(server, prompt):
    rid = server.enqueue(prompt)
    server.drain()
    return server.pop_result(rid)


def decode_until(server, rid, n_emitted):
    for _ in range(200):
        if len(server._emitted.get(rid, [])) >= n_emitted:
            return
        server.step()
    raise AssertionError(f"never reached {n_emitted} emitted tokens")


def handoff(src, dst, rid, epoch=1):
    """The in-process spelling of one migration: snapshot -> freeze ->
    restore -> finish; returns the target-local rid."""
    snap = src.snapshot_slot(rid)
    src.freeze_slot(rid)
    rid2 = dst.restore_slot(snap)
    assert rid2 is not None
    src.finish_migrated(rid, {"replica": "dst", "rid": rid2,
                              "epoch": epoch})
    return rid2


PROMPT = [(i * 7) % 60 + 1 for i in range(19)]


# -- serving-layer round trips ------------------------------------------------


@pytest.mark.parametrize("kv_int8", [False, True],
                         ids=["f32", "kv_int8"])
def test_snapshot_restore_token_exact(params, kv_int8):
    """The headline: a stream migrated mid-decode emits exactly the
    tokens (and logprobs) an unmigrated run emits — f32 and quantized
    pools (int8 pairs ship AS STORED, no dequant round-trip)."""
    quiet = make_server(params, kv_int8=kv_int8)
    want = quiet_run(quiet, PROMPT)
    want_lps = None
    rid_q = quiet.enqueue(PROMPT)
    quiet.drain()
    want_lps = quiet.result_logprobs(rid_q)
    quiet.pop_result(rid_q)

    src = make_server(params, kv_int8=kv_int8)
    dst = make_server(params, kv_int8=kv_int8)
    rid = src.enqueue(PROMPT)
    decode_until(src, rid, 4)
    rid2 = handoff(src, dst, rid)
    assert src.migrated_to(rid) == {"replica": "dst", "rid": rid2,
                                    "epoch": 1}
    src.check_invariants()          # pages freed/published on the source
    while not dst.finished(rid2):
        dst.step()
    assert dst.result_logprobs(rid2)[-1] == want_lps[-1]
    assert dst.pop_result(rid2) == want
    dst.check_invariants()


def test_snapshot_int8_pages_stay_quantized(params):
    """The snapshot of a kv_int8 pool carries the stored int8 values +
    f32 scales — never a dequantized f32 copy (byte size pins it)."""
    src = make_server(params, kv_int8=True)
    rid = src.enqueue(PROMPT)
    decode_until(src, rid, 2)
    snap = src.snapshot_slot(rid)
    assert set(snap["pages"]) == {"k_q", "k_s", "v_q", "v_s"}
    assert snap["pages"]["k_q"].dtype == np.int8
    assert snap["pages"]["k_s"].dtype == np.float32
    assert snap["pages"]["k_s"].shape[-1] == 1     # per-token per-head scale


def test_seeded_sampling_continues_exactly_across_seeds(params):
    """The restored slot reuses the SOURCE's raw request key, so even
    seeded sampling continues identically on a target built with a
    different server seed."""
    quiet = make_server(params, temperature=0.9, seed=3)
    want = quiet_run(quiet, PROMPT)
    src = make_server(params, temperature=0.9, seed=3)
    dst = make_server(params, temperature=0.9, seed=999)
    rid = src.enqueue(PROMPT)
    decode_until(src, rid, 5)
    rid2 = handoff(src, dst, rid)
    while not dst.finished(rid2):
        dst.step()
    assert dst.pop_result(rid2) == want


def test_restore_maps_prefix_cache_pages_readonly(params):
    """A target whose radix tree already holds the prompt's prefix maps
    those pages READ-ONLY instead of writing shipped bytes — pinned by
    the pages_remapped counter, byte-stability of the shared pages, and
    balanced refcounts after both retirements."""
    fam = [(i * 5) % 60 + 1 for i in range(2 * PS)]
    warm_prompt = fam + [11]
    mig_prompt = fam + [9]
    quiet = make_server(params)
    want = quiet_run(quiet, mig_prompt)

    src = make_server(params, prefix_cache_pages=16)
    dst = make_server(params, prefix_cache_pages=16)
    quiet_run(dst, warm_prompt)     # dst tree now owns the family pages
    tree_pages = sorted(dst._prefix_cache.owned_pages())
    before = {p: np.asarray(jax.device_get(dst.k_pages[:, p]))
              for p in tree_pages}

    rid = src.enqueue(mig_prompt)
    decode_until(src, rid, 4)
    rid2 = handoff(src, dst, rid)
    assert int(dst.obs.counter(
        "kubetpu_migration_pages_remapped_total").value) == 2
    slot = dst._slot_rid.index(rid2)
    assert dst._slot_shared[slot] == 2      # two leading rows are shared
    while not dst.finished(rid2):
        dst.step()
    assert dst.pop_result(rid2) == want
    # shared pages were mapped, never copied into: bytes unchanged
    for p in tree_pages:
        np.testing.assert_array_equal(
            before[p], np.asarray(jax.device_get(dst.k_pages[:, p])))
    src.check_invariants()
    dst.check_invariants()                  # refcounts balanced


def test_snapshot_refusals(params):
    """Migration only between rounds: queued, mid-chunked-prefill and
    deferred-first-token streams refuse to snapshot (nothing mutated)."""
    src = make_server(params, prefill_budget=PS, max_seq=64)
    long_prompt = [(i * 3) % 60 + 1 for i in range(3 * PS)]
    rid = src.enqueue(long_prompt)
    with pytest.raises(ValueError, match="queued"):
        src.snapshot_slot(rid)
    src.step()                               # first chunk only
    assert src._prefills, "prompt should still be mid-prefill"
    with pytest.raises(ValueError, match="mid-chunked-prefill"):
        src.snapshot_slot(rid)
    src.drain()
    src.pop_result(rid)
    src.check_invariants()


def test_restore_refuses_mismatched_config(params):
    src = make_server(params)
    dst = make_server(params, max_new_tokens=20)   # different budget
    rid = src.enqueue(PROMPT)
    decode_until(src, rid, 2)
    snap = src.snapshot_slot(rid)
    with pytest.raises(ValueError, match="max_new_tokens"):
        dst.restore_slot(snap)
    # the source stream is untouched and finishes normally
    src.drain()
    assert len(src.pop_result(rid)) == len(PROMPT) + 12


def test_restore_returns_none_when_full_and_rolls_back(params):
    """A target with no free slot refuses with None and mutates
    nothing — the source resumes (unfreeze) token-exactly."""
    quiet = make_server(params)
    want = quiet_run(quiet, PROMPT)
    src = make_server(params)
    dst = make_server(params, n_slots=1)
    blocker = dst.enqueue([5] * 4)
    dst.step()                                # occupies the only slot
    rid = src.enqueue(PROMPT)
    decode_until(src, rid, 3)
    snap = src.snapshot_slot(rid)
    src.freeze_slot(rid)
    assert dst.restore_slot(snap) is None
    dst.check_invariants()
    src.unfreeze_slot(rid)
    src.drain()
    assert src.pop_result(rid) == want
    src.check_invariants()
    dst.drain()
    dst.pop_result(blocker)


@pytest.mark.slow
def test_spec_server_gamma_ema_survive_handoff(params):
    """PagedSpeculativeDecodeServer: the adaptive-gamma EMA migrates
    with the stream (no optimistic reset on the target) and the
    migrated stream's output stays greedy-exact.
    Slow: boots two full spec servers (draft+target compiles on both
    sides); the non-spec handoff paths keep tier-1 round trips."""
    dcfg = ModelConfig(vocab=64, d_model=16, n_layers=1, n_heads=2,
                       d_ff=32)
    dparams = init_params(jax.random.PRNGKey(7), dcfg)

    def mk():
        return PagedSpeculativeDecodeServer(
            CFG, dcfg, params, dparams, n_slots=2, max_seq=64,
            max_new_tokens=16, page_size=PS, gamma_max=3)

    quiet = mk()
    want = quiet_run(quiet, PROMPT)
    src, dst = mk(), mk()
    rid = src.enqueue(PROMPT)
    decode_until(src, rid, 5)
    slot = src._slot_rid.index(rid)
    snap = src.snapshot_slot(rid)
    assert snap["spec"]["gamma"] == int(src._gamma[slot])
    assert snap["spec"]["accept_ema"] == pytest.approx(
        float(src._accept_ema[slot]))
    src.freeze_slot(rid)
    rid2 = dst.restore_slot(snap)
    src.finish_migrated(rid, {"replica": "dst", "rid": rid2, "epoch": 1})
    slot2 = dst._slot_rid.index(rid2)
    assert int(dst._gamma[slot2]) == snap["spec"]["gamma"]
    assert float(dst._accept_ema[slot2]) == pytest.approx(
        snap["spec"]["accept_ema"])
    while not dst.finished(rid2):
        dst.step()
    assert dst.pop_result(rid2) == want
    src.check_invariants()
    dst.check_invariants()


def test_spec_snapshot_refused_by_plain_server(params):
    dcfg = ModelConfig(vocab=64, d_model=16, n_layers=1, n_heads=2,
                       d_ff=32)
    spec = PagedSpeculativeDecodeServer(
        CFG, dcfg, params, init_params(jax.random.PRNGKey(7), dcfg),
        n_slots=2, max_seq=64, max_new_tokens=12, page_size=PS,
        gamma_max=2)
    plain = make_server(params)
    rid = spec.enqueue(PROMPT)
    decode_until(spec, rid, 2)
    snap = spec.snapshot_slot(rid)
    with pytest.raises(ValueError, match="kind"):
        plain.restore_slot(snap)


def test_frozen_slot_is_not_free_not_idle_not_snapshottable(params):
    """A frozen slot is mid-handoff: not reusable, not idle, not
    migratable — and NOT snapshottable again (two racing policies must
    never ship the same stream's next epoch to two different targets),
    and the /load surface must read it as occupied + migrating (the
    pool's drained() gate would otherwise let the autoscaler terminate
    the source before the commit-ack)."""
    src = make_server(params)
    rid = src.enqueue(PROMPT)
    decode_until(src, rid, 2)
    slot = src._slot_rid.index(rid)
    free_before = src._free_slots()
    active_before = src.load_info()["active_slots"]
    src.freeze_slot(rid)
    assert slot not in src._free_slots()
    assert not src._idle()
    assert rid not in src.migratable_rids()
    with pytest.raises(ValueError, match="already frozen"):
        src.snapshot_slot(rid)
    info = src.load_info()
    assert info["migrating_slots"] == 1
    assert info["active_slots"] == active_before
    src.unfreeze_slot(rid)
    assert src._free_slots() == free_before
    assert src.load_info()["migrating_slots"] == 0
    src.drain()
    src.pop_result(rid)


def test_dense_server_migration_degrades_to_skip(params):
    """Non-paged servers carry no shippable cache view: snapshot
    raises NotImplementedError, and the wire layer's migrate leg turns
    that into a per-stream SKIP (migrate_skip event, False) — a dense
    fleet's drain degrades to wait-drain instead of crashing the
    drain-migrate thread."""
    from kubetpu.jobs.serving import DecodeServer

    dense = DecodeServer(CFG, params, n_slots=2, max_seq=64,
                         max_new_tokens=8)
    rid = dense.enqueue(PROMPT)
    for _ in range(3):
        dense.step()
    with pytest.raises(NotImplementedError, match="live migration"):
        dense.snapshot_slot(rid)
    rep = ReplicaServer(dense, "dense0", idle_wait=0.002)
    rep.start()
    try:
        assert rep.migrate_rid(rid, "http://127.0.0.1:9",
                               reason="test") is False
        assert any(e["kind"] == "migrate_skip"
                   for e in rep.events.events())
    finally:
        rep.shutdown(graceful=False)


def test_snapshot_codec_roundtrip_and_truncation():
    snap = {
        "prompt": [1, 2, 3], "epoch": 2,
        "pages": {
            "k": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
            "q8": np.arange(6, dtype=np.int8).reshape(2, 3),
        },
    }
    meta, blob = encode_snapshot(snap)
    chunks = blob_chunks(blob, 16)
    assert b"".join(chunks) == blob
    back = decode_snapshot(meta, blob)
    assert back["prompt"] == [1, 2, 3] and back["epoch"] == 2
    np.testing.assert_array_equal(back["pages"]["k"], snap["pages"]["k"])
    np.testing.assert_array_equal(back["pages"]["q8"],
                                  snap["pages"]["q8"])
    with pytest.raises(ValueError, match="truncated"):
        decode_snapshot(meta, blob[:-1])
    with pytest.raises(ValueError, match="trailing"):
        decode_snapshot(meta, blob + b"x")
    assert blob_chunks(b"", 16) == [b""]    # empty manifest still commits


# -- wire-level paths ---------------------------------------------------------


@pytest.fixture()
def wire(params):
    """(replica list, shutdown) — two real ReplicaServers over paged
    servers with longer streams so a handoff can land mid-flight."""
    made = []

    def build(n=2, rep_kw=None, **server_kw):
        # long streams by default: a handoff must land MID-flight, not
        # race a short sprint to natural completion
        server_kw.setdefault("max_new_tokens", 96)
        server_kw.setdefault("max_seq", 192)
        reps = []
        for i in range(n):
            rep = ReplicaServer(make_server(params, **server_kw),
                                f"mig{i}", idle_wait=0.002,
                                **(rep_kw or {}))
            rep.start()
            reps.append(rep)
        made.extend(reps)
        return reps

    yield build
    for rep in made:
        rep.shutdown(graceful=False)


def _generate_async(rep_or_router_addr, prompt, key, timeout=30.0,
                    retry=None):
    out = {}

    def go():
        try:
            out["body"] = request_json(
                rep_or_router_addr + "/generate",
                {"prompt": prompt, "timeout": timeout},
                idempotency_key=key, timeout=timeout, retry=retry)
            out["code"] = 200
        except urllib.error.HTTPError as e:
            out["code"] = e.code
            out["body"] = json.loads(e.read() or b"{}")

    t = threading.Thread(target=go, daemon=True)
    t.start()
    return t, out


def _wait_midstream(rep, min_emitted=3, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with rep._cv:
            rids = rep.server.migratable_rids()
            if rids and len(rep.server._emitted.get(
                    rids[0], [])) >= min_emitted:
                return rids[0]
        time.sleep(0.003)
    raise AssertionError("stream never reached mid-flight")


def test_wire_migrate_409_and_adoption(params, wire):
    """/migrate_out hands the stream over; the source's open generate
    answers 409 with the new owner; a retry with the same key at the
    target ADOPTS the restored stream (no re-admission) and returns the
    full quiet-run tokens."""
    want = quiet_run(make_server(params, max_new_tokens=96, max_seq=192),
                     PROMPT)
    src, dst = wire(2)
    t, out = _generate_async(src.address, PROMPT, "w-adopt")
    rid = _wait_midstream(src)
    res = request_json(src.address + "/migrate_out",
                       {"target": dst.address, "reason": "test",
                        "wait": True},
                       idempotency_key="w-adopt-mo", timeout=30.0)
    assert res == {"migrated": 1, "failed": 0}
    t.join(20.0)
    assert out["code"] == 409
    assert out["body"]["migrated"]["replica"] == dst.name
    body = request_json(dst.address + "/generate",
                        {"prompt": PROMPT, "timeout": 30.0},
                        idempotency_key="w-adopt", timeout=30.0)
    assert body["tokens"] == want
    assert int(dst.server.obs.counter(
        "kubetpu_replica_generate_adopted_total").value) == 1
    # the generate was NOT re-admitted fresh on the target
    assert int(dst.server.obs.counter(
        "kubetpu_replica_generate_requests_total").value) == 0
    # a retry at the SOURCE deterministically re-learns the 409
    with pytest.raises(urllib.error.HTTPError) as ei:
        request_json(src.address + "/generate",
                     {"prompt": PROMPT, "timeout": 30.0},
                     idempotency_key="w-adopt", timeout=30.0)
    assert ei.value.code == 409
    assert int(src.server.obs.counter(
        "kubetpu_migrations_total", reason="test",
        result="committed").value) == 1
    src.server.check_invariants()
    dst.server.check_invariants()


def test_wire_commit_replay_never_double_restores(params, wire):
    """A re-sent commit (same idempotency key — the lost-response
    retry) REPLAYS the committed ack: one restore, one active copy."""
    src, dst = wire(2)
    t, out = _generate_async(src.address, PROMPT, "w-replay")
    rid = _wait_midstream(src)
    with src._cv:
        snap = src.server.snapshot_slot(rid)
        src.server.freeze_slot(rid)
    snap["origin"] = [src.name, rid]
    snap["epoch"] = 1
    meta, blob = encode_snapshot(snap)
    meta["gen_key"] = "w-replay"
    tok = {"origin": [src.name, rid], "epoch": 1}
    kbase = f"mig-{src.name}-{rid}-e1"
    commit_body = {"phase": "commit", "token": tok, "n_chunks": 1,
                   "arrays": meta["arrays"], "ship_from_page": 0}
    request_json(dst.address + "/migrate_in",
                 {"phase": "begin", "token": tok, "meta": meta},
                 idempotency_key=kbase + "-begin", timeout=10.0)
    request_json(dst.address + "/migrate_in",
                 {"phase": "chunk", "token": tok, "seq": 0,
                  "data": chunk_b64(blob)},
                 idempotency_key=kbase + "-c0", timeout=10.0)
    ack1 = request_json(dst.address + "/migrate_in", commit_body,
                        idempotency_key=kbase + "-commit", timeout=10.0)
    ack2 = request_json(dst.address + "/migrate_in", commit_body,
                        idempotency_key=kbase + "-commit", timeout=10.0)
    assert ack1 == ack2                     # replay, not re-execution
    assert int(dst.server.obs.counter(
        "kubetpu_migrations_in_total", result="committed").value) == 1
    with src._cv:
        src.server.finish_migrated(
            rid, {"replica": ack1["replica"], "rid": ack1["rid"],
                  "epoch": 1})
        src._cv.notify_all()
    t.join(20.0)
    assert out["code"] == 409


def test_wire_epoch_fence_refuses_stale_handoff(params, wire):
    """A DUPLICATE handoff of the same (origin, rid) at an epoch the
    target has already committed is fenced 409 — at most one copy of a
    stream ever goes active (zero double-restores)."""
    src, dst = wire(2)
    t, out = _generate_async(src.address, PROMPT, "w-fence")
    rid = _wait_midstream(src)
    with src._cv:
        snap = src.server.snapshot_slot(rid)
    assert src.migrate_rid(rid, dst.address, reason="test")
    t.join(20.0)
    # forge a second handoff of the SAME stream at the SAME epoch under
    # DIFFERENT idempotency keys (so the replay window can't save us —
    # only the fence can)
    tok = {"origin": [src.name, rid], "epoch": 1}
    meta, blob = encode_snapshot(dict(snap, origin=[src.name, rid],
                                      epoch=1))
    request_json(dst.address + "/migrate_in",
                 {"phase": "begin", "token": tok, "meta": meta},
                 idempotency_key="forge-begin", timeout=10.0)
    request_json(dst.address + "/migrate_in",
                 {"phase": "chunk", "token": tok, "seq": 0,
                  "data": chunk_b64(blob)},
                 idempotency_key="forge-c0", timeout=10.0)
    with pytest.raises(urllib.error.HTTPError) as ei:
        request_json(dst.address + "/migrate_in",
                     {"phase": "commit", "token": tok, "n_chunks": 1,
                      "arrays": meta["arrays"], "ship_from_page": 0},
                     idempotency_key="forge-commit", timeout=10.0)
    assert ei.value.code == 409
    assert json.loads(ei.value.read())["fenced"] is True
    assert int(dst.server.obs.counter(
        "kubetpu_migrations_fenced_total").value) == 1
    assert int(dst.server.obs.counter(
        "kubetpu_migrations_in_total", result="committed").value) == 1
    dst.server.check_invariants()


def test_return_hop_sheds_stale_migrated_verdict(params, wire):
    """A stream that RETURNS to a replica (A -> B -> A) must shed the
    stale migrated-away verdict there: a keyed retry at A attaches to
    the live stream (200, full tokens), never loops on the old
    lower-epoch 409."""
    want = quiet_run(make_server(params, max_new_tokens=96, max_seq=192),
                     PROMPT)
    a, b = wire(2)
    t, out = _generate_async(a.address, PROMPT, "w-return")
    rid = _wait_midstream(a)
    assert a.migrate_rid(rid, b.address, reason="test")     # A -> B
    t.join(20.0)
    assert out["code"] == 409                                # stale owner: B
    rid_b = _wait_midstream(b, min_emitted=0)
    assert b.migrate_rid(rid_b, a.address, reason="test")   # B -> A
    body = request_json(a.address + "/generate",
                        {"prompt": PROMPT, "timeout": 30.0},
                        idempotency_key="w-return", timeout=30.0)
    assert body["tokens"] == want
    assert int(a.server.obs.counter(
        "kubetpu_replica_generate_adopted_total").value) == 1
    a.server.check_invariants()
    b.server.check_invariants()


def test_prefix_negotiation_skips_shipping_matched_pages(params, wire):
    """The begin-phase prefix hint: pages the target can map from its
    own radix tree never cross the wire — bytes-shipped counts only
    the uncached suffix, and the restore still lands token-exact."""
    fam = [(i * 5) % 60 + 1 for i in range(2 * PS)]
    warm_prompt = fam + [11]
    mig_prompt = fam + [9]
    want = quiet_run(make_server(params, max_new_tokens=96, max_seq=192),
                     mig_prompt)
    src, dst = wire(2, prefix_cache_pages=16)
    # warm the TARGET's tree with the family
    with dst._cv:
        r = dst.server.enqueue(warm_prompt)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        with dst._cv:
            if dst.server.finished(r):
                dst.server.pop_result(r)
                break
        time.sleep(0.005)
    assert dst.server.migration_prefix_hint(mig_prompt) == 2
    t, out = _generate_async(src.address, mig_prompt, "w-skip")
    rid = _wait_midstream(src)
    with src._cv:
        full_bytes = len(encode_snapshot(
            {"pages": src.server.snapshot_slot(rid)["pages"]})[1])
    assert src.migrate_rid(rid, dst.address, reason="test")
    shipped = int(src.server.obs.counter(
        "kubetpu_migration_bytes_shipped_total").value)
    assert 0 < shipped < full_bytes
    assert int(dst.server.obs.counter(
        "kubetpu_migration_pages_remapped_total").value) == 2
    t.join(20.0)
    body = request_json(dst.address + "/generate",
                        {"prompt": mig_prompt, "timeout": 30.0},
                        idempotency_key="w-skip", timeout=30.0)
    assert body["tokens"] == want
    src.server.check_invariants()
    dst.server.check_invariants()


def test_drain_with_migration_completes_without_stream_end(params, wire):
    """drain(migrate_to=...) hands the in-flight stream off and goes
    idle immediately — the drain-complete gate never waits for the
    stream's natural end (pinned by the stream still being mid-flight
    on the TARGET when the source reads drained)."""
    src, dst = wire(2)
    t, out = _generate_async(src.address, PROMPT, "w-drain")
    _wait_midstream(src)
    src.drain(migrate_to=dst.address, reason="scale_down")
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        with src._cv:
            if src.server._idle():
                break
        time.sleep(0.005)
    with src._cv:
        assert src.server._idle(), "drain did not complete via migration"
    t.join(20.0)
    assert out["code"] == 409
    assert out["body"]["migrated"]["replica"] == dst.name
    assert int(src.server.obs.counter(
        "kubetpu_migrations_total", reason="scale_down",
        result="committed").value) == 1
    src.server.check_invariants()
    dst.server.check_invariants()


def test_drain_timeout_cancels_instead_of_wedging(params, wire):
    """The satellite fix: a drain with no migrate target and a
    long-max_tokens stream escalates at drain_timeout_s — the stream
    cancels with a drain_timeout event and its caller gets a retryable
    503, instead of scale-down wedging on natural stream end."""
    (src,) = wire(1, rep_kw={"drain_timeout_s": 0.15},
                  max_new_tokens=4096, max_seq=8192, n_pages=2048)
    # NO_RETRY: the shared client would otherwise retry the 503 into
    # the draining replica and surface the generic draining refusal —
    # in production that retry is the router landing elsewhere
    t, out = _generate_async(src.address, PROMPT, "w-timeout",
                             timeout=30.0, retry=NO_RETRY)
    _wait_midstream(src)
    src.drain()                              # no migrate target
    t.join(10.0)
    assert out["code"] == 503
    assert "drain_timeout" in out["body"]["error"]
    assert any(e["kind"] == "drain_timeout"
               for e in src.events.events())
    with src._cv:
        assert src.server._idle()
    src.server.check_invariants()


def test_router_repin_follows_migrated_stream(params, wire):
    """RouterServer re-pins the rid->replica mapping mid-stream: a
    routed request whose replica migrates the stream away lands on the
    new owner via the 409 notice and completes token-exactly."""
    # a longer stream: the drain-migrate must land MID-flight, not race
    # a 24-token sprint to the finish line
    want = quiet_run(make_server(params, max_new_tokens=96, max_seq=192),
                     PROMPT)
    src, dst = wire(2)
    router = RouterServer(load_refresh_s=0.05)
    router.start()
    try:
        for rep in (src, dst):
            router.register_replica(rep.address)
        t, out = _generate_async(router.address, PROMPT, "w-repin")
        rep0 = None
        deadline = time.monotonic() + 10.0
        while rep0 is None and time.monotonic() < deadline:
            for rep in (src, dst):
                with rep._cv:
                    rids = rep.server.migratable_rids()
                    if rids and len(rep.server._emitted.get(
                            rids[0], [])) >= 3:
                        rep0 = rep
                        break
            time.sleep(0.003)
        assert rep0 is not None
        other = dst if rep0 is src else src
        router.pool.drain(rep0.name, migrate_to=other.address,
                          reason="scale_down")
        t.join(25.0)
        assert out["code"] == 200
        assert out["body"]["tokens"] == want
        assert out["body"]["replica"] == other.name
        assert int(router._c_repin.value) >= 1
        kinds = [e["kind"] for e in router.events.events()]
        assert "repin" in kinds
        rep0.server.check_invariants()
        other.server.check_invariants()
    finally:
        router.shutdown()
