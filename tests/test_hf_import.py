"""Cross-framework parity: a HuggingFace llama checkpoint converted by
hf_import must produce the torch reference's logits through kubetpu's
forward — the strongest possible check that the block math (RoPE
convention, RMSNorm, GQA grouping, SiLU MLP) matches the llama recipe."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from kubetpu.jobs import forward  # noqa: E402
from kubetpu.jobs.hf_import import config_from_hf, params_from_hf  # noqa: E402


def _tiny_hf(n_kv_heads=4, tie=False, seed=0):
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(seed)
    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=n_kv_heads, max_position_embeddings=128,
        rms_norm_eps=1e-6, rope_theta=10000.0, tie_word_embeddings=tie,
        attention_bias=False, mlp_bias=False,
    )
    model = LlamaForCausalLM(hf_cfg).eval()
    return model, hf_cfg


def _assert_logits_match(model, atol=2e-4):
    params, cfg = params_from_hf(model)
    ids = np.array([[1, 5, 9, 2, 30, 7], [3, 3, 60, 4, 11, 0]], np.int64)
    with torch.no_grad():
        want = model(torch.tensor(ids)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(ids, jnp.int32), cfg))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=atol)


def test_mha_logits_match_torch_reference():
    model, _ = _tiny_hf(n_kv_heads=4)
    _assert_logits_match(model)


def test_gqa_logits_match_torch_reference():
    model, _ = _tiny_hf(n_kv_heads=2, seed=1)
    cfg = config_from_hf(model.config)
    assert cfg.n_kv_heads == 2
    _assert_logits_match(model)


def test_tied_embeddings_use_embed_as_head():
    model, _ = _tiny_hf(tie=True, seed=2)
    params, cfg = params_from_hf(model)
    np.testing.assert_array_equal(
        np.asarray(params["head"]), np.asarray(params["embed"]).T
    )
    _assert_logits_match(model)


def test_converted_checkpoint_serves_and_decodes():
    """The point of the importer: the converted tree drives the existing
    decode stack (greedy generate matches HF greedy)."""
    from kubetpu.jobs.decode import make_generate

    model, _ = _tiny_hf(seed=3)
    params, cfg = params_from_hf(model)
    prompt = [[1, 5, 9, 2]]
    steps = 8
    with torch.no_grad():
        want = model.generate(
            torch.tensor(prompt), max_new_tokens=steps, do_sample=False,
            pad_token_id=0,
        ).numpy()
    gen = make_generate(cfg)
    got = np.asarray(gen(params, jnp.asarray(prompt, jnp.int32),
                         jax.random.PRNGKey(0), steps))
    np.testing.assert_array_equal(got, want)


def test_import_validation():
    model, _ = _tiny_hf()
    with pytest.raises(ValueError):
        params_from_hf(model.state_dict())  # bare state_dict needs cfg
    cfg = config_from_hf(model.config)
    sd = {k: v for k, v in model.state_dict().items()
          if "embed_tokens" not in k}
    with pytest.raises(KeyError):
        params_from_hf(sd, cfg=cfg)
    import dataclasses
    bad = dataclasses.replace(cfg, vocab=128)
    with pytest.raises(ValueError):
        params_from_hf(model.state_dict(), cfg=bad)

    class FakeCfg:
        model_type = "gpt2"

    with pytest.raises(ValueError):
        config_from_hf(FakeCfg())


def test_bf16_override_dtype():
    model, _ = _tiny_hf()
    params, cfg = params_from_hf(model, dtype=jnp.bfloat16)
    assert params["blocks"]["wq"].dtype == jnp.bfloat16


def test_unsupported_checkpoint_features_refused():
    """What the importer cannot reproduce it must refuse, never silently
    drop: rope scaling, bias terms, unmapped tensors; eps drift warns."""
    import warnings

    from transformers import LlamaConfig

    base = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=4, max_position_embeddings=128,
                rms_norm_eps=1e-6)
    with pytest.raises(ValueError):  # unsupported scaling TYPE refuses
        config_from_hf(LlamaConfig(**base, rope_scaling={
            "rope_type": "yarn", "factor": 8.0}))
    with pytest.raises(ValueError):  # bias terms would be dropped
        config_from_hf(LlamaConfig(**base, attention_bias=True))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        config_from_hf(LlamaConfig(**{**base, "rms_norm_eps": 1e-5}))
    assert any("rms_norm_eps" in str(x.message) for x in w)

    # unmapped leftover tensors refuse at conversion time
    model, _ = _tiny_hf(seed=4)
    cfg = config_from_hf(model.config)
    sd = dict(model.state_dict())
    sd["model.layers.0.self_attn.q_proj.bias"] = torch.zeros(32)
    with pytest.raises(ValueError):
        params_from_hf(sd, cfg=cfg)


def test_llama3_rope_scaling_matches_torch_reference():
    """A Llama-3.1-style rope_scaling checkpoint converts and reproduces
    the torch reference logits — the frequency warp is translated, not
    refused (long positions exercise the warped low-frequency band)."""
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(5)
    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
        attention_bias=False, mlp_bias=False,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32},
    )
    model = LlamaForCausalLM(hf_cfg).eval()
    params, cfg = params_from_hf(model)
    assert cfg.rope_llama3_scaling == (8.0, 1.0, 4.0, 32)
    ids = np.arange(1, 49, dtype=np.int64)[None] % 64  # past original_max
    with torch.no_grad():
        want = model(torch.tensor(ids)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(ids, jnp.int32), cfg))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)
    # the warp is REAL: at long positions the rotated vectors differ
    # materially from plain rope (end-to-end logits of a RANDOM model can
    # wash this out, so assert at the rope level)
    from kubetpu.jobs.model import rope

    x = jnp.ones((1, 1, 1, cfg.head_dim))
    pos = jnp.array([40])
    warped = rope(x, pos, cfg.rope_theta, cfg.rope_llama3_scaling)
    plain = rope(x, pos, cfg.rope_theta)
    assert float(jnp.abs(warped - plain).max()) > 0.1
    # and greedy decode through the KV cache applies it too
    from kubetpu.jobs.decode import make_generate

    gen = make_generate(cfg)
    got_gen = np.asarray(gen(params, jnp.asarray(ids[:, :8], jnp.int32),
                             jax.random.PRNGKey(0), 8))
    with torch.no_grad():
        want_gen = model.generate(torch.tensor(ids[:, :8]), max_new_tokens=8,
                                  do_sample=False, pad_token_id=0).numpy()
    np.testing.assert_array_equal(got_gen, want_gen)


def test_rope_scaling_config_validation():
    from kubetpu.jobs import ModelConfig

    with pytest.raises(ValueError):  # the HF dict, not the tuple
        ModelConfig(rope_llama3_scaling={"factor": 8.0})
    with pytest.raises(ValueError):  # wrong arity
        ModelConfig(rope_llama3_scaling=(8.0, 1.0, 4.0))
    with pytest.raises(ValueError):  # degenerate smoothing band
        ModelConfig(rope_llama3_scaling=(8.0, 2.0, 2.0, 32))
    ModelConfig(rope_llama3_scaling=(8.0, 1.0, 4.0, 32))  # ok
