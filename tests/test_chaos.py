"""Chaos soak: the whole wire stack (controller + N in-process agents +
gang placements) under seeded fault injection — drops, injected 5xx,
partial (truncated) responses — must CONVERGE: no lost pods, no double
allocations, an empty pending queue once the network heals, and zero gang
reschedules for a transient (< dead_after) agent blackout.

The layering under test (ISSUE 2 tentpole):

- retries absorb single-call faults (jittered backoff + deadline,
  ``httpcommon.request_json`` / ``RemoteDevice``);
- idempotency keys make the retries SAFE (a replayed ``POST /pods`` /
  ``POST /allocate`` whose first response was lost cannot double-place /
  double-allocate);
- the circuit breaker absorbs multi-pass outages (suspect/probation keep
  pods placed; only ``dead_after`` consecutive missed probes evict);
- ``Cluster.check_invariants`` is the oracle: after any soak, held + free
  == capacity on every node and every pod has exactly one placement.

Deterministic: every fault draw comes from ``random.Random(seed)`` in
request order. The short soak stays in tier-1; the long one is ``slow``.
"""

import json
import urllib.error

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.wire import (
    ControllerServer,
    FaultInjector,
    NodeAgentServer,
    RetryPolicy,
    RoutePolicy,
)
from kubetpu.wire.controller import pod_to_json
from kubetpu.wire.httpcommon import request_json

pytestmark = pytest.mark.chaos

# aggressive client retry for the chaos runs: enough attempts that a
# sub-50% per-call fault rate practically never exhausts the budget
CHAOS_RETRY = RetryPolicy(attempts=8, base_delay=0.01, max_delay=0.1,
                          deadline=30.0)


def tpu_pod(name, chips):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})},
    )


def _mk_stack(seed, fault_rate):
    """4 v5e-64 hosts (8 chips each, one slice) + controller, every server
    running its own seeded injector at *fault_rate* split across
    drop/error/partial, plus injected latency on top."""
    per = fault_rate / 3.0
    delay = 0.1 if fault_rate else 0.0
    policy = lambda: RoutePolicy(  # noqa: E731
        drop=per, error=per, partial=per, delay=delay, delay_s=0.005)
    agents = []
    for h in range(4):
        inj = FaultInjector(seed=seed + 1 + h, default=policy())
        agents.append(NodeAgentServer(
            new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=h)),
            f"h{h}", faults=inj,
        ))
    for a in agents:
        a.start()
    controller = ControllerServer(
        poll_interval=3600,
        faults=FaultInjector(seed=seed, default=policy()),
        suspect_after=1, dead_after=3,
    )
    controller.start()
    return controller, agents


def _heal(controller, agents):
    controller.faults.clear()
    for a in agents:
        a.faults.clear()


def _shutdown(controller, agents):
    controller.shutdown()
    for a in agents:
        try:
            a.shutdown()
        except Exception:  # noqa: BLE001 — may already be down
            pass


def _post(url, obj, key=None):
    return request_json(url, obj, retry=CHAOS_RETRY, idempotency_key=key)


def _delete(url):
    """DELETE with retry; a 404 on a retry means the FIRST attempt
    succeeded and its response was lost — deleted either way."""
    try:
        request_json(url, method="DELETE", retry=CHAOS_RETRY)
    except urllib.error.HTTPError as e:
        if e.code != 404:
            raise


def _run_soak(seed, rounds, fault_rate):
    controller, agents = _mk_stack(seed, fault_rate)
    try:
        for i, a in enumerate(agents):
            # registration POST: retriable because keyed; a replayed
            # register at the same URL is a server-side no-op
            _post(controller.address + "/nodes", {"url": a.address},
                  key=f"reg-{seed}-{i}")
        live_singles, live_gang, submitted, deleted = [], None, set(), set()
        for r in range(rounds):
            name = f"p{r}"
            _post(controller.address + "/pods",
                  {"pod": pod_to_json(tpu_pod(name, 4)), "queue": True},
                  key=f"sub-{seed}-{name}")
            submitted.add(name)
            live_singles.append(name)
            # sliding windows keep outstanding chips under total capacity
            # (32): <= 3 singles (12) + 1 gang (16)
            if len(live_singles) > 3:
                victim = live_singles.pop(0)
                _delete(controller.address + f"/pods/{victim}")
                deleted.add(victim)
            if r % 4 == 0:
                if live_gang is not None:
                    for m in live_gang:
                        _delete(controller.address + f"/pods/{m}")
                        deleted.add(m)
                live_gang = [f"g{r}w{i}" for i in range(2)]
                _post(controller.address + "/pods",
                      {"gang": [pod_to_json(tpu_pod(m, 8)) for m in live_gang],
                       "queue": True},
                      key=f"gang-{seed}-{r}")
                submitted.update(live_gang)
            controller.poll_once()
        # the network heals; the control plane must CONVERGE
        _heal(controller, agents)
        expected = submitted - deleted
        for _ in range(30):
            result = controller.poll_once()
            placed = {
                p for n in controller.cluster.nodes.values() for p in n.pods
            }
            if not result["pending"] and placed == expected:
                break
        placed = {p for n in controller.cluster.nodes.values() for p in n.pods}
        assert placed == expected, (
            f"lost or duplicated pods: placed={sorted(placed)} "
            f"expected={sorted(expected)} pending={controller.pending_pods}"
        )
        assert controller.pending_pods == []
        # the oracle: no double allocation anywhere in the accounting
        assert controller.cluster.check_invariants() == []
        # faults actually fired (the soak tested something)
        total_injected = sum(
            sum(s.faults.counts.values()) for s in [controller, *agents]
        )
        assert total_injected > 0, "no faults injected — dead knob?"
    finally:
        _shutdown(controller, agents)


def test_chaos_soak_short():
    """Tier-1 soak: >= 10% aggregate injected fault rate on every route,
    fixed seed, full convergence."""
    _run_soak(seed=1234, rounds=10, fault_rate=0.12)


@pytest.mark.slow
def test_chaos_soak_long():
    """The full soak (make chaos): more rounds, ~30% injected faults."""
    _run_soak(seed=987, rounds=40, fault_rate=0.3)


def test_transient_blackout_causes_zero_reschedules():
    """An agent that goes fully dark for FEWER than dead_after reconcile
    passes: its gang must never be evicted or re-placed — the breaker
    holds it suspect (no new placements) until the blackout ends, then
    returns it to service through probation."""
    controller, agents = _mk_stack(seed=77, fault_rate=0.0)
    try:
        for a in agents:
            _post(controller.address + "/nodes", {"url": a.address})
        out = _post(controller.address + "/pods",
                    {"gang": [pod_to_json(tpu_pod(f"w{i}", 8))
                              for i in range(2)]})
        placed_before = {p["pod"]: p["node"] for p in out["placements"]}
        victim_node = placed_before["w0"]
        victim = next(a for a in agents if a.node_name == victim_node)

        # total blackout, 2 polls < dead_after=3
        victim.faults.set_default(RoutePolicy(drop=1.0))
        for expected_state in ("suspect", "suspect"):
            result = controller.poll_once()
            assert result["failed_nodes"] == []
            assert result["rescheduled"] == []
            assert result["suspect_nodes"] == [victim_node]
            with controller._lock:
                assert controller._health_state(victim_node) == expected_state
        # pods never moved; the suspect node takes no NEW work
        with controller._lock:
            assert set(controller.cluster.nodes[victim_node].pods) >= {"w0"}
            assert victim_node in controller.cluster.cordoned
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(controller.address + "/pods",
                  {"pod": pod_to_json(tpu_pod("px", 32))})
        assert e.value.code == 409  # capacity exists only on the suspect

        # blackout ends: probation, then healthy + schedulable again
        victim.faults.clear()
        assert controller.poll_once()["suspect_nodes"] == []
        with controller._lock:
            assert controller._health_state(victim_node) == "probation"
        controller.poll_once()
        with controller._lock:
            assert controller._health_state(victim_node) == "healthy"
            assert victim_node not in controller.cluster.cordoned
        # the gang sat still through the whole episode
        placed_after = {
            p: node_name
            for node_name, node in controller.cluster.nodes.items()
            for p in node.pods
        }
        assert placed_after == placed_before
        assert controller.cluster.check_invariants() == []
    finally:
        _shutdown(controller, agents)


def test_retried_submit_with_idempotency_key_places_once():
    """A ``POST /pods`` whose response is truncated mid-write (processed,
    reply lost) is retried by the client and REPLAYED by the dedup window
    — one placement, identical response bytes."""
    controller, agents = _mk_stack(seed=5, fault_rate=0.0)
    try:
        for a in agents:
            _post(controller.address + "/nodes", {"url": a.address})
        # fault exactly one response on /pods: the commit lands, the reply
        # is cut, the client's retry must replay
        controller.faults.set_route("/pods", RoutePolicy(partial=1.0, times=1))
        out = _post(controller.address + "/pods",
                    {"pod": pod_to_json(tpu_pod("once", 4))}, key="k-once")
        assert out["placements"][0]["pod"] == "once"
        placed = [p for n in controller.cluster.nodes.values() for p in n.pods]
        assert placed.count("once") == 1
        # an explicit replay (same key) returns the SAME response and does
        # not double-place
        again = _post(controller.address + "/pods",
                      {"pod": pod_to_json(tpu_pod("once", 4))}, key="k-once")
        assert json.dumps(again, sort_keys=True) == json.dumps(
            out, sort_keys=True)
        placed = [p for n in controller.cluster.nodes.values() for p in n.pods]
        assert placed.count("once") == 1
        assert controller.cluster.check_invariants() == []
    finally:
        _shutdown(controller, agents)
