"""Speculative continuous batching: greedy-exact parity with the plain
dense server under staggered admissions, EOS clipping inside a round, and
the measured tokens-per-round stat."""

import jax
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.serving import DecodeServer
from kubetpu.jobs.spec_serving import SpeculativeDecodeServer

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
DCFG = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=2, d_ff=32)


@pytest.fixture(scope="module")
def params():
    return (init_params(jax.random.PRNGKey(0), CFG),
            init_params(jax.random.PRNGKey(7), DCFG))


def _spec(params, **kw):
    t, d = params
    return SpeculativeDecodeServer(CFG, DCFG, t, d, **kw)


def test_spec_server_matches_dense_greedy_staggered(params):
    """Same tokens as DecodeServer for staggered requests — speculation
    must be invisible in the output stream."""
    t, _d = params
    prompts = [[3, 14, 15, 9], [26, 5], [35, 8, 9, 7, 9]]

    dense = DecodeServer(CFG, t, n_slots=2, max_seq=64, max_new_tokens=10)
    spec = _spec(params, n_slots=2, max_seq=64, max_new_tokens=10, gamma=3)
    results = {}
    for server, tag in ((dense, "dense"), (spec, "spec")):
        ra = server.submit(prompts[0])
        server.step()
        rb = server.submit(prompts[1])
        server.drain()
        rc = server.submit(prompts[2])
        server.drain()
        results[tag] = [server.result(r) for r in (ra, rb, rc)]
    assert results["spec"] == results["dense"]
    assert spec.mean_tokens_per_round() >= 1.0


def test_spec_server_self_draft_accepts_everything(params):
    """Target as its own draft: every round accepts gamma+1 tokens, so a
    max_new_tokens=8, gamma=3 request finishes in ceil(7/4)+prefill
    rounds and the stat shows the ceiling."""
    t, _d = params
    srv = SpeculativeDecodeServer(CFG, CFG, t, t, n_slots=1, max_seq=64,
                                  max_new_tokens=9, gamma=3)
    rid = srv.submit([3, 14, 15, 9])
    steps = 0
    while not srv.finished(rid):
        srv.step()
        steps += 1
    assert steps <= 3  # 8 post-first tokens / 4-per-round = 2 (+ slack)
    # parity with plain greedy too
    dense = DecodeServer(CFG, t, n_slots=1, max_seq=64, max_new_tokens=9)
    rd = dense.submit([3, 14, 15, 9])
    dense.drain()
    assert srv.result(rid) == dense.result(rd)
    assert srv.mean_tokens_per_round() > 2.0


@pytest.mark.slow
def test_spec_server_eos_and_queue(params):
    """EOS emitted mid-round clips the request there; queued requests
    enter freed slots at round boundaries."""
    t, _d = params
    probe = _spec(params, n_slots=1, max_seq=64, max_new_tokens=6, gamma=3)
    r = probe.submit([3, 14, 15, 9])
    probe.drain()
    eos = probe.result(r)[4 + 2]  # the 3rd emitted token becomes "EOS"

    srv = _spec(params, n_slots=1, max_seq=64, max_new_tokens=6, gamma=3,
                eos_id=int(eos))
    ra = srv.submit([3, 14, 15, 9])
    rb = srv.enqueue([26, 5])
    srv.drain()
    out_a = srv.result(ra)
    assert out_a[-1] == eos and len(out_a) <= 4 + 6
    assert out_a == probe.result(r)[: len(out_a)]
    assert srv.finished(rb)


def test_spec_server_rejects_sampling_and_mismatched_vocab(params):
    t, d = params
    srv = _spec(params, n_slots=1, max_seq=64, max_new_tokens=4)
    with pytest.raises(ValueError):
        srv.submit([1, 2], sampling={"temperature": 1.0})
    with pytest.raises(ValueError):
        SpeculativeDecodeServer(
            CFG, ModelConfig(vocab=32, d_model=32, n_layers=1, n_heads=2,
                             d_ff=32), t, d)


def test_spec_server_queue_ttl_and_queue_wait(params):
    """The dense speculative server inherits the SHARED graceful-
    degradation path (Round-7/8 audit): a queued request past its TTL
    expires with the counted reason, and admitted-from-queue requests
    record queue_wait like every SlotServerBase peer."""
    import time as _time

    srv = _spec(params, n_slots=1, max_seq=64, max_new_tokens=4, gamma=2)
    ra = srv.submit([1, 2, 3])           # occupies the only slot
    rb = srv.enqueue([4, 5], ttl=0.0)    # expires at the next round
    rc = srv.enqueue([6, 7, 8])          # no TTL: admitted once a frees
    _time.sleep(0.01)
    srv.step()
    assert srv.finished(rb) and not srv._emitted[rb]
    assert srv.expire_reason(rb) == "queue_ttl"
    assert srv.expire_reason(rc) is None
    srv.drain()
    assert srv.finished(ra) and srv.finished(rc)
    stats = srv.metrics_summary()
    assert stats["queue_expired"]["count"] == 1
    # queue_wait: one sample per ADMITTED request (ra via submit, rc via
    # the queue; the expired rb records queue_expired instead)
    assert stats["queue_wait"]["count"] == 2


@pytest.mark.slow
def test_spec_server_exports_round_metrics(params):
    """Round/acceptance counters + the tokens-per-round gauge land on
    the serving registry (the obs satellite of Round 10).
    Slow: boots its own spec server just for the metrics surface; the
    greedy-parity spec tests keep the serve path tier-1."""
    t, _d = params
    srv = SpeculativeDecodeServer(CFG, CFG, t, t, n_slots=1, max_seq=64,
                                  max_new_tokens=9, gamma=3)
    rid = srv.submit([3, 14, 15, 9])
    srv.drain()
    assert srv.finished(rid)
    text = srv.metrics_text()
    for series in ("kubetpu_spec_rounds_total",
                   "kubetpu_spec_accepted_tokens_total",
                   "kubetpu_spec_proposed_tokens_total",
                   "kubetpu_spec_mean_tokens_per_round"):
        assert series in text, series
    # self-draft: every proposal accepted, gauge matches the method
    assert srv._c_spec_accepted.value == srv._c_spec_proposed.value > 0
    assert srv._c_spec_rounds.value >= 2
    line = next(l for l in text.splitlines()
                if l.startswith("kubetpu_spec_mean_tokens_per_round "))
    assert float(line.split()[-1]) == pytest.approx(
        srv.mean_tokens_per_round())


@pytest.mark.slow
def test_spec_server_acceptance_sustains_over_long_generation(params):
    """Self-draft acceptance must hold the gamma+1 ceiling across MANY
    rounds — regression for the draft-cache hole: the scan fed only
    [last, d_0..d_{gamma-2}], so a fully-accepted round left position
    pos+gamma unwritten in the draft cache and acceptance decayed.
    Slow: a long-generation soak by construction; short-round parity
    tests keep the draft-cache path tier-1."""
    t, _d = params
    srv = SpeculativeDecodeServer(CFG, CFG, t, t, n_slots=1, max_seq=128,
                                  max_new_tokens=41, gamma=3)
    rid = srv.submit([3, 14, 15, 9])
    rounds = 0
    while not srv.finished(rid):
        srv.step()
        rounds += 1
    # 40 post-first tokens at exactly 4/round = 10 rounds, no decay slack
    assert rounds == 10, rounds
    assert srv.mean_tokens_per_round() == 4.0
    dense = DecodeServer(CFG, t, n_slots=1, max_seq=128, max_new_tokens=41)
    rd = dense.submit([3, 14, 15, 9])
    dense.drain()
    assert srv.result(rid) == dense.result(rd)
