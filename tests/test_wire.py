"""The agent <-> control-plane wire protocol (VERDICT r1 #1).

Three layers:
- codec round-trips (the wire format),
- an in-process ``NodeAgentServer`` driven through ``Cluster`` via
  ``RemoteDevice`` (register -> schedule -> allocate over HTTP),
- REAL agent subprocesses: gang scheduling across live processes, and a
  SIGKILLed agent driving the ``fail_node`` -> reschedule path.

The reference's process topology (CRI shim / scheduler / nvmlinfo as
separate processes, SURVEY.md §3) is what these tests pin down for kubetpu.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from kubetpu.api.device import Mount
from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster, SchedulingError
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.wire import (
    AgentUnreachable,
    NodeAgentServer,
    RemoteDevice,
    allocate_result_from_json,
    allocate_result_to_json,
    node_info_from_json,
    node_info_to_json,
    pod_info_from_json,
    pod_info_to_json,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tpu_pod(name, chips):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})},
    )


# -- codec ------------------------------------------------------------------


def test_codec_round_trips():
    dev = new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    from kubetpu.api.types import new_node_info

    info = new_node_info("n0")
    dev.update_node_info(info)
    back = node_info_from_json(json.loads(json.dumps(node_info_to_json(info))))
    assert back.name == "n0"
    assert back.capacity == info.capacity
    assert back.allocatable == info.allocatable
    assert back.kube_alloc == info.kube_alloc

    pod = tpu_pod("p", 4)
    pod.requests["kubetpu/priority"] = 3
    pod.init_containers["init"] = ContainerInfo(kube_requests={ResourceTPU: 2})
    pod.running_containers["main"].allocate_from = {"a": "b"}
    back_pod = pod_info_from_json(json.loads(json.dumps(pod_info_to_json(pod))))
    assert back_pod.name == "p"
    assert back_pod.requests == pod.requests
    assert back_pod.running_containers["main"].allocate_from == {"a": "b"}
    assert back_pod.init_containers["init"].kube_requests == {ResourceTPU: 2}

    result = ([Mount("m", "/h", "/c", True)], ["/dev/accel0"], {"E": "1"})
    back_res = allocate_result_from_json(
        json.loads(json.dumps(allocate_result_to_json(result)))
    )
    assert back_res[0][0].host_path == "/h"
    assert back_res[1] == ["/dev/accel0"]
    assert back_res[2] == {"E": "1"}


# -- in-process server over the real HTTP stack -----------------------------


@pytest.fixture
def agent_server():
    dev = new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    server = NodeAgentServer(dev, "wire-n0")
    server.start()
    yield server
    server.shutdown()


def test_remote_register_schedule_allocate(agent_server):
    cluster = Cluster()
    info = cluster.register_remote_node(agent_server.address)
    assert info.name == "wire-n0"
    assert info.allocatable[ResourceTPU] == 8

    placed = cluster.schedule(tpu_pod("job", 4))
    assert placed.node_name == "wire-n0"
    # allocation crosses the wire to where the devices live
    mounts, devices, env = cluster.allocate("job")["main"]
    assert len(devices) == 4
    assert env["TPU_VISIBLE_DEVICES"].count(",") == 3
    # accounting happened control-plane-side
    assert cluster.nodes["wire-n0"].info.allocatable[ResourceTPU] == 4


def test_remote_refresh_over_wire(agent_server):
    cluster = Cluster()
    cluster.register_remote_node(agent_server.address)
    cluster.schedule(tpu_pod("job", 4))
    # healthy agent: refresh re-advertises and preserves held resources
    evicted = cluster.poll_remote_nodes()
    assert evicted == {}
    assert cluster.nodes["wire-n0"].info.allocatable[ResourceTPU] == 4


def test_dead_agent_drives_fail_node(agent_server):
    cluster = Cluster()
    cluster.register_remote_node(agent_server.address)
    placed = cluster.schedule(tpu_pod("job", 4))
    assert placed.node_name == "wire-n0"
    agent_server.shutdown()

    evicted = cluster.poll_remote_nodes()
    assert list(evicted) == ["wire-n0"]
    assert [p.name for p in evicted["wire-n0"]] == ["job"]
    assert "wire-n0" not in cluster.nodes  # node deregistered


def test_register_dead_address_raises():
    cluster = Cluster()
    with pytest.raises(AgentUnreachable):
        cluster.register_remote_node("http://127.0.0.1:1")  # nothing listens


def test_agent_application_error_is_not_node_death(agent_server):
    dev = RemoteDevice(agent_server.address)
    dev.start()
    pod = tpu_pod("p", 1)
    with pytest.raises(ValueError):
        dev.allocate(pod, ContainerInfo())  # container not in pod
    # server-side application errors surface as RuntimeError, not unreachability
    cluster = Cluster()
    cluster.register_remote_node(agent_server.address)
    assert cluster.poll_remote_nodes() == {}


# -- real agent processes ---------------------------------------------------


def spawn_agent(host_index, topo="v5e-64", env=None):
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "kubetpu.cli.agent", "--serve",
            "--fake", topo, "--host", str(host_index), "--port", "0",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        cwd=REPO,
        text=True,
        env=env,
    )
    line = proc.stdout.readline()
    hello = json.loads(line)
    return proc, hello["listening"], hello["node"]


@pytest.fixture
def three_agents():
    procs = []
    try:
        agents = [spawn_agent(h) for h in range(3)]
        procs = [a[0] for a in agents]
        yield agents
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)


def test_gang_across_live_agent_processes(three_agents):
    cluster = Cluster()
    for _proc, url, _name in three_agents:
        cluster.register_remote_node(url)
    assert sorted(cluster.nodes) == ["v5e-64-h0", "v5e-64-h1", "v5e-64-h2"]

    placed = cluster.schedule_gang([tpu_pod("w0", 8), tpu_pod("w1", 8)])
    assert cluster.gang_contiguity(placed) == 1.0
    for p in placed:  # container-start injection crosses each pod's wire
        _mounts, devices, env = cluster.allocate(p.name)["main"]
        assert len(devices) == 8
        assert env["TPU_WORKER_ID"] == p.node_name.removeprefix("v5e-64-h")


def test_killed_agent_process_drives_failover(three_agents):
    cluster = Cluster()
    for _proc, url, _name in three_agents:
        cluster.register_remote_node(url)
    placed = cluster.schedule_gang([tpu_pod("w0", 8), tpu_pod("w1", 8)])
    victim_node = placed[0].node_name
    victim_proc = next(
        proc for proc, _url, name in three_agents if name == victim_node
    )

    victim_proc.send_signal(signal.SIGKILL)
    victim_proc.wait(timeout=10)
    deadline = time.time() + 10
    evicted = {}
    while time.time() < deadline and not evicted:
        evicted = cluster.poll_remote_nodes()
    assert list(evicted) == [victim_node]
    assert [p.name for p in evicted[victim_node]] == [placed[0].name]

    # elastic recovery: the evicted worker lands on the remaining free host
    again = cluster.schedule(evicted[victim_node][0])
    assert again.node_name not in (victim_node, placed[1].node_name)
    _mounts, devices, _env = cluster.allocate(again.name)["main"]
    assert len(devices) == 8


def test_agent_metrics_endpoint(agent_server):
    """GET /metrics: Prometheus-style counters + capacity gauges (the
    metrics endpoint the reference never had, SURVEY.md §5.5)."""
    import urllib.request

    cluster = Cluster()
    cluster.register_remote_node(agent_server.address)
    cluster.schedule(tpu_pod("job", 2))
    cluster.allocate("job")

    with urllib.request.urlopen(agent_server.address + "/metrics", timeout=5) as r:
        text = r.read().decode()
    assert "kubetpu_agent_uptime_seconds" in text
    assert "kubetpu_agent_allocate_requests_total 1" in text
    # register (1x nodeinfo) only — register_remote_node probes once
    assert "kubetpu_agent_nodeinfo_requests_total 1" in text
    assert 'kubetpu_agent_capacity{resource="kubedevice/tpu",node="wire-n0"} 8' in text


def test_wire_auth_token():
    """With a shared secret set, unauthenticated requests are rejected 401
    (healthz stays open for liveness); matching tokens work end to end."""
    import urllib.error
    import urllib.request

    dev = new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    server = NodeAgentServer(dev, "auth-n0", token="s3cret")
    server.start()
    try:
        # healthz open
        with urllib.request.urlopen(server.address + "/healthz", timeout=5) as r:
            assert json.loads(r.read())["ok"]
        # nodeinfo: no token -> 401
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(server.address + "/nodeinfo", timeout=5)
        assert e.value.code == 401
        # wrong token -> 401, surfaced as RuntimeError (not node death)
        from kubetpu.api.types import new_node_info

        bad = RemoteDevice(server.address, token="wrong")
        with pytest.raises(RuntimeError):
            bad.update_node_info(new_node_info("x"))
        # right token: full register/schedule/allocate flow over the wire
        cluster = Cluster()
        cluster.register_remote_node(server.address, token="s3cret")
        placed = cluster.schedule(tpu_pod("job", 2))
        assert placed.node_name == "auth-n0"
        _m, devices, _e = cluster.allocate("job")["main"]
        assert len(devices) == 2
    finally:
        server.shutdown()


def test_wire_empty_token_means_no_auth():
    """A blank token (templated env file with an empty value) must mean
    no-auth on BOTH sides, not a bricked wire."""
    dev = new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    server = NodeAgentServer(dev, "blank-n0", token="")
    server.start()
    try:
        cluster = Cluster()
        cluster.register_remote_node(server.address, token="")
        assert "blank-n0" in cluster.nodes
    finally:
        server.shutdown()


def test_wire_concurrent_requests_stress(agent_server):
    """ThreadingHTTPServer + counter lock under parallel load: concurrent
    nodeinfo probes and allocates must all succeed and the counters must
    add up exactly (no lost increments)."""
    import threading
    import urllib.request

    cluster = Cluster()
    cluster.register_remote_node(agent_server.address)
    placed = [cluster.schedule(tpu_pod(f"job{i}", 1)) for i in range(4)]

    errors = []

    def probe(n):
        try:
            for _ in range(n):
                with urllib.request.urlopen(
                    agent_server.address + "/nodeinfo", timeout=10
                ) as r:
                    json.loads(r.read())
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def allocate(pod_name, n):
        try:
            for _ in range(n):
                out = cluster.allocate(pod_name)
                assert len(out["main"][1]) == 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=probe, args=(5,)) for _ in range(4)] + [
        threading.Thread(target=allocate, args=(p.name, 5)) for p in placed
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors

    with urllib.request.urlopen(agent_server.address + "/metrics", timeout=5) as r:
        text = r.read().decode()
    # 1 register probe + 4*5 concurrent probes; 4 pods * 5 allocates
    assert "kubetpu_agent_nodeinfo_requests_total 21" in text
    assert "kubetpu_agent_allocate_requests_total 20" in text


def test_wire_auth_non_ascii_is_401_not_node_death():
    """A non-ASCII Authorization header must get a clean 401 (not a dropped
    connection that poll_remote_nodes would misread as node death)."""
    import urllib.error
    import urllib.request

    dev = new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    server = NodeAgentServer(dev, "na-n0", token="s3cret")
    server.start()
    try:
        req = urllib.request.Request(
            server.address + "/nodeinfo",
            headers={"Authorization": "Bearer café"},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 401
    finally:
        server.shutdown()
