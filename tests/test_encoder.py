"""The bidirectional encoder family: full-visibility semantics, masked-LM
objective, flash(causal=False) parity, sharded training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, forward, init_params, init_state, make_mesh
from kubetpu.jobs.encoder import (
    dense_bidirectional_attention,
    encoder_forward,
    make_mlm_train_step,
    masked_lm_loss,
)

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
MASK_ID = 63


def test_encoder_sees_the_future():
    """Bidirectional semantics: perturbing a LATE token must change EARLY
    positions' logits (it cannot under the causal decoder)."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 60)
    tokens2 = tokens.at[0, 15].set((tokens[0, 15] + 1) % 60)

    enc1 = encoder_forward(params, tokens, CFG)
    enc2 = encoder_forward(params, tokens2, CFG)
    assert not np.allclose(np.asarray(enc1[0, 0]), np.asarray(enc2[0, 0]))

    dec1 = forward(params, tokens, CFG)
    dec2 = forward(params, tokens2, CFG)
    np.testing.assert_allclose(
        np.asarray(dec1[0, :15]), np.asarray(dec2[0, :15]), rtol=1e-5
    )


def test_flash_encoder_matches_dense():
    import functools

    from kubetpu.ops import flash_attention

    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, CFG.vocab)
    attn = functools.partial(flash_attention, block_q=16, block_k=16,
                             interpret=True, causal=False)
    np.testing.assert_allclose(
        np.asarray(encoder_forward(params, tokens, CFG, attn_fn=attn)),
        np.asarray(encoder_forward(params, tokens, CFG)),
        rtol=2e-4, atol=2e-5,
    )


def test_masked_lm_loss_counts_only_masked_positions():
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 60)
    no_mask = jnp.zeros((2, 16), bool)
    assert float(masked_lm_loss(params, tokens, no_mask, MASK_ID, CFG)) == 0.0

    one = jnp.zeros((2, 16), bool).at[:, 3].set(True)
    loss = float(masked_lm_loss(params, tokens, one, MASK_ID, CFG))
    assert loss > 0.0 and np.isfinite(loss)


@pytest.mark.slow
def test_mlm_train_step_learns_on_mesh():
    # Slow: a real MLM train loop on an 8-way mesh; the loss-masking and
    # forward-parity encoder pins stay tier-1.
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), CFG, mesh)
    step = make_mlm_train_step(CFG, mesh, MASK_ID, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 60)
    mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.15, (4, 32))
    losses = []
    for _ in range(10):
        state, loss = step(state, tokens, mask)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses


def test_mlm_unknown_attention_rejected():
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1})
    with pytest.raises(ValueError):
        make_mlm_train_step(CFG, mesh, MASK_ID, attention="falsh")


def test_mlm_moe_aux_loss_applied():
    """An MoE encoder config with moe_aux_coeff must include the
    load-balance term, like the decoder's next_token_loss."""
    import dataclasses

    base = dataclasses.replace(CFG, n_experts=4)
    with_aux = dataclasses.replace(base, moe_aux_coeff=0.5)
    params = init_params(jax.random.PRNGKey(0), with_aux)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 60)
    mask = jnp.zeros((2, 16), bool).at[:, 2].set(True)
    plain = float(masked_lm_loss(params, tokens, mask, MASK_ID, base))
    plus = float(masked_lm_loss(params, tokens, mask, MASK_ID, with_aux))
    assert plus > plain  # the aux term (>= 1 by construction) was added


def test_mlm_flash_trains_with_sp_mesh():
    """attention='flash' must work on a mesh that HAS an sp axis: encoder
    batches shard over dp only, so the opaque kernel never sees a
    sequence-partitioned operand."""
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), CFG, mesh)
    step = make_mlm_train_step(CFG, mesh, MASK_ID, optimizer=opt,
                               attention="flash", interpret=True)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 60)
    mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.15, (4, 32))
    state, loss = step(state, tokens, mask)
    assert np.isfinite(float(loss))


def test_mlm_batches_feed_training():
    from kubetpu.jobs.data import SyntheticCorpus, mlm_batches

    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), CFG, mesh)
    step = make_mlm_train_step(CFG, mesh, MASK_ID, optimizer=opt)
    corpus = SyntheticCorpus(vocab=60)
    for (tokens, mask), _ in zip(mlm_batches(corpus, 4, 32, seed=3), range(3)):
        assert mask.any(axis=1).all()  # every row contributes
        state, loss = step(state, tokens, mask)
    assert np.isfinite(float(loss))


def test_mlm_chunked_loss_matches_unchunked():
    """cfg.loss_chunk on the masked-LM tail: the weighted (masked-position)
    reduction must survive chunking — value and grads identical."""
    import dataclasses

    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, MASK_ID)
    mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.3, tokens.shape)
    cfgc = dataclasses.replace(CFG, loss_chunk=4)
    f = lambda p, c: masked_lm_loss(p, tokens, mask, MASK_ID, c)
    l0, g0 = jax.value_and_grad(f)(params, CFG)
    l1, g1 = jax.value_and_grad(f)(params, cfgc)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for p0, p1 in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(p0), np.asarray(p1),
                                   rtol=2e-4, atol=2e-5)
