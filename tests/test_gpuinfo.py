"""The native GPU enumerator (gpuinfo) — the GPU analog of tpuinfo behind
the reference's nvmlinfo exec-JSON boundary (nvgputypes/types.go:45-58),
NVML-free: sysfs probe with PCI-topology-derived link levels, plus canned
fake boxes mirroring the reference's test fixtures."""

import os
import subprocess

import pytest

from kubetpu.api.types import new_node_info
from kubetpu.device.nvidia import new_native_nvidia_gpu_manager, parse_gpus_info

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(REPO, "_output", "gpuinfo")


@pytest.fixture(scope="module")
def gpuinfo_binary():
    if not os.path.exists(BINARY):
        subprocess.run(["make", "-C", REPO, "gpuinfo"], check=True, capture_output=True)
    return BINARY


def test_fake_titan8_matches_reference_fixture_shape(gpuinfo_binary):
    out = subprocess.run([gpuinfo_binary, "--fake", "titan8"],
                         capture_output=True, check=True)
    info = parse_gpus_info(out.stdout)
    assert len(info.gpus) == 8
    assert info.gpus[0].model == "GeForce GTX TITAN X"
    assert info.gpus[0].memory.global_mib == 12238
    # NVLink pairs within a socket, hostbridge across pairs, no cross-socket
    links = {t.bus_id: t.link for t in info.gpus[0].topology}
    assert links[info.gpus[1].pci.bus_id] == 5
    assert links[info.gpus[2].pci.bus_id] == 3
    assert info.gpus[4].pci.bus_id not in links  # other socket: absent


def test_fake_k80x4_has_no_topology(gpuinfo_binary):
    out = subprocess.run([gpuinfo_binary, "--fake", "k80x4"],
                         capture_output=True, check=True)
    info = parse_gpus_info(out.stdout)
    assert len(info.gpus) == 4
    assert all(not g.topology for g in info.gpus)


def test_manager_over_native_probe_advertises_groups(gpuinfo_binary):
    """Full manager lifecycle over the REAL exec boundary: the titan8 box
    must group into gpugrp0 pairs and per-socket gpugrp1 quads — the same
    expectations as the reference's TITAN fixture
    (nvidia_gpu_manager_test.go:118-145)."""
    mgr = new_native_nvidia_gpu_manager(binary=gpuinfo_binary,
                                        extra_args=["--fake", "titan8"])
    mgr.start()
    info = new_node_info("g0")
    mgr.update_node_info(info)
    assert info.kube_alloc.get("nvidia.com/gpu") == 8
    grp_keys = [k for k in info.allocatable if "/gpugrp1/" in k and k.endswith("/cards")]
    assert len(grp_keys) == 8
    # pairs: GPUs 0,1 share a gpugrp0 id; quads: 0..3 share a gpugrp1 id
    def seg(key, name):
        parts = key.split("/")
        return parts[parts.index(name) + 1]
    by_uuid = {k.split("/gpu/")[1].split("/")[0]: k for k in grp_keys}
    k0, k1, k2, k4 = (by_uuid[f"GPU-titan8-{i}"] for i in (0, 1, 2, 4))
    assert seg(k0, "gpugrp0") == seg(k1, "gpugrp0")
    assert seg(k0, "gpugrp0") != seg(k2, "gpugrp0")
    assert seg(k0, "gpugrp1") == seg(k2, "gpugrp1")
    assert seg(k0, "gpugrp1") != seg(k4, "gpugrp1")


def test_sysfs_probe_with_fixture_root(gpuinfo_binary, tmp_path):
    """Fixtured GPUINFO_SYSFS_ROOT: two GPUs behind one bridge (link 4), a
    third on another NUMA node (link 1); model from the PCI device id,
    memory from the fixture's vram_mib."""
    def dev(bus, parent, numa, devid="0x17c2"):
        d = tmp_path / "bus" / "pci" / "devices" / bus
        d.mkdir(parents=True)
        (d / "vendor").write_text("0x10de\n")
        (d / "device").write_text(devid + "\n")
        (d / "class").write_text("0x030000\n")
        (d / "numa_node").write_text(f"{numa}\n")
        (d / "parent").write_text(parent + "\n")
        (d / "vram_mib").write_text("12238\n")

    dev("0000:05:00.0", "bridgeA", 0)
    dev("0000:06:00.0", "bridgeA", 0)
    dev("0000:85:00.0", "bridgeB", 1, devid="0x102d")
    # a non-GPU PCI function must be ignored
    d = tmp_path / "bus" / "pci" / "devices" / "0000:00:1f.0"
    d.mkdir(parents=True)
    (d / "vendor").write_text("0x8086\n")
    (d / "class").write_text("0x060100\n")

    env = dict(os.environ)
    env["GPUINFO_SYSFS_ROOT"] = str(tmp_path)
    out = subprocess.run([gpuinfo_binary, "json"], capture_output=True,
                         check=True, env=env)
    info = parse_gpus_info(out.stdout)
    assert [g.pci.bus_id for g in info.gpus] == [
        "0000:05:00.0", "0000:06:00.0", "0000:85:00.0"
    ]
    assert info.gpus[0].model == "GeForce GTX TITAN X"
    assert info.gpus[2].model == "Tesla K80"
    assert info.gpus[0].memory.global_mib == 12238
    links0 = {t.bus_id: t.link for t in info.gpus[0].topology}
    assert links0["0000:06:00.0"] == 4  # same bridge
    assert links0["0000:85:00.0"] == 1  # cross NUMA


def test_sysfs_fixture_with_json_metachars_still_parses(gpuinfo_binary, tmp_path):
    """A fixture whose device-id carries quotes/backslashes must still emit
    valid JSON: all string fields are routed through the C++ JsonEscape
    (ADVICE r2: unescaped interpolation produced malformed JSON)."""
    d = tmp_path / "bus" / "pci" / "devices" / "0000:05:00.0"
    d.mkdir(parents=True)
    (d / "vendor").write_text("0x10de\n")
    (d / "device").write_text('0xbad"id\\\n')
    (d / "class").write_text("0x030000\n")

    env = dict(os.environ)
    env["GPUINFO_SYSFS_ROOT"] = str(tmp_path)
    env["GPUINFO_DRIVER_VERSION"] = 'drv"ver\\'
    out = subprocess.run([gpuinfo_binary, "json"], capture_output=True,
                         check=True, env=env)
    info = parse_gpus_info(out.stdout)  # must not raise
    assert len(info.gpus) == 1
    assert '"id\\' in info.gpus[0].model


def test_human_mode_runs(gpuinfo_binary):
    out = subprocess.run([gpuinfo_binary, "--fake", "titan8", "--human"],
                         capture_output=True, check=True)
    assert b"TITAN X" in out.stdout


def test_unknown_fake_errors(gpuinfo_binary):
    r = subprocess.run([gpuinfo_binary, "--fake", "nope"], capture_output=True)
    assert r.returncode == 2
