"""Scheduler tests.

``TestTreeScenario`` ports the reference's scheduler algorithm test
(``gpuschedulerplugin/gpu_test.go:13-113``) with its exact expected literal
keys — including the fallback when the best node shape is removed from the
cache — fixing the reference test's hygiene debt (stale unexported
identifiers, aliased node maps; SURVEY.md §4 item 2). Run once with GPU
names (pinning the reference grammar byte-for-byte) and once with TPU names.
"""

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.plugintypes import print_tree_node
from kubetpu.scheduler import GPU, TPU, NodeTreeCache, add_to_node, compute_tree_score
from kubetpu.scheduler.topology_gen import convert_to_best_requests


def _two_level_node(dc, groups):
    """Build a ResourceList like the reference's nodeRes fixtures:
    groups = {grp1_id: {grp0_id: [device ids]}}."""
    out = {}
    for g1, g0s in groups.items():
        for g0, devs in g0s.items():
            for d in devs:
                out[
                    f"resource/group/{dc.grp1}/{g1}/{dc.grp0}/{g0}/{dc.base}/{d}/cards"
                ] = 1
    return out


@pytest.mark.parametrize("dc", [GPU, TPU], ids=["gpu", "tpu"])
def test_tree_scenario_reference_port(dc):
    # nodeRes1: 8 devices, 2 sockets x 2 pairs (gpu_test.go:14-23).
    node_res1 = _two_level_node(
        dc, {"A": {"0": [0, 1], "1": [2, 3]}, "B": {"2": [4, 5], "3": [6, 7]}}
    )
    # nodeRes2: socket B is one 4-device group (gpu_test.go:24-33).
    node_res2 = _two_level_node(
        dc, {"A": {"0": [0, 1], "1": [2, 3]}, "B": {"2": [4, 5, 6, 7]}}
    )
    node_res3 = dict(node_res1)  # reference aliased these; we copy (hygiene)

    tree1 = add_to_node(None, node_res1, dc.grp_prefix, "cards", 1)
    tree2 = add_to_node(None, node_res2, dc.grp_prefix, "cards", 1)
    assert tree1.val == 8 and tree2.val == 8
    # nodeRes2 groups more densely -> higher tree score.
    assert compute_tree_score(tree2) > compute_tree_score(tree1)

    cache = NodeTreeCache(dc.grp_prefix, "cards", levels=1)
    cache.add_resources("A", node_res1)
    cache.add_resources("B", node_res2)
    cache.add_resources("C", node_res3)
    cache.add_resources("D", {"ABCD": 4})
    # A and C share a shape; B and D are distinct: 3 cached shapes.
    assert len(cache.shapes()) == 3

    cache.remove_node("A")  # C still holds shape 1

    pod = PodInfo(
        running_containers={
            "A": ContainerInfo(
                requests={dc.resource_name: 3},
                dev_requests={
                    f"resource/group/{dc.grp1}/B/{dc.grp0}/3/{dc.base}/6/cards": 1,
                    f"resource/group/{dc.grp1}/B/{dc.grp0}/3/{dc.base}/7/cards": 1,
                },
            )
        }
    )
    assert convert_to_best_requests(dc, cache, pod)
    # Best shape is nodeRes2's (denser): 3 devices in one level-0 group,
    # stale dev_requests stripped (expected literals, gpu_test.go:74-85).
    assert pod.running_containers["A"].dev_requests == {
        f"resource/group/{dc.grp1}/0/{dc.grp0}/0/{dc.base}/0/cards": 1,
        f"resource/group/{dc.grp1}/0/{dc.grp0}/0/{dc.base}/1/cards": 1,
        f"resource/group/{dc.grp1}/0/{dc.grp0}/0/{dc.base}/2/cards": 1,
    }
    assert pod.running_containers["A"].requests == {dc.resource_name: 3}

    # Remove the best shape's only node: falls back to nodeRes1's shape,
    # splitting 2 + 1 across level-0 groups (gpu_test.go:89-112).
    cache.remove_node("B")
    assert convert_to_best_requests(dc, cache, pod)
    assert pod.running_containers["A"].dev_requests == {
        f"resource/group/{dc.grp1}/0/{dc.grp0}/0/{dc.base}/0/cards": 1,
        f"resource/group/{dc.grp1}/0/{dc.grp0}/0/{dc.base}/1/cards": 1,
        f"resource/group/{dc.grp1}/0/{dc.grp0}/1/{dc.base}/0/cards": 1,
    }


def test_convert_fails_when_no_tree_fits():
    cache = NodeTreeCache(TPU.grp_prefix, "cards", levels=1)
    cache.add_resources(
        "small", _two_level_node(TPU, {"0": {"0": [0, 1]}})
    )
    pod = PodInfo(
        running_containers={"c": ContainerInfo(requests={TPU.resource_name: 3})}
    )
    assert not convert_to_best_requests(TPU, cache, pod)


def _v5e8_node_alloc(free_chips=range(8)):
    """A v5e-8 host the way the TPU device manager advertises it: scalar +
    2-level grouped cards/memory keys + the tpu-slice geometry key."""
    from kubetpu.plugintypes.mesh import TOPOLOGIES
    from kubetpu.scheduler.meshstate import slice_resource_key

    topo = TOPOLOGIES["v5e-8"]
    alloc = {TPU.resource_name: len(list(free_chips))}
    alloc[slice_resource_key("v5e-8", 0)] = 1
    for c in free_chips:
        # blocks of 2x2: local ids 0,1,4,5 -> block 0; 2,3,6,7 -> block 1
        x, y = topo.host_coords(0)[c]
        blk = (x // 2) * ((topo.host_shape[1] + 1) // 2) + (y // 2)
        alloc[f"resource/group/tpugrp1/0/tpugrp0/{blk}/tpu/{c}/cards"] = 1
        alloc[f"resource/group/tpugrp1/0/tpugrp0/{blk}/tpu/{c}/memory"] = (
            topo.hbm_bytes_per_chip
        )
    return alloc


def test_tpu_scheduler_add_node_and_fit():
    from kubetpu.api.types import NodeInfo
    from kubetpu.scheduler import TpuScheduler

    s = TpuScheduler()
    node = NodeInfo(
        name="tpu-node-0",
        allocatable=_v5e8_node_alloc(),
        kube_alloc={TPU.resource_name: 8},
    )
    s.add_node("tpu-node-0", node)

    pod = PodInfo(
        name="train4",
        running_containers={"main": ContainerInfo(requests={TPU.resource_name: 4})},
    )
    fits, reasons, score = s.pod_fits_device(node, pod, False)
    assert fits and not reasons
    assert score == 1.0  # a 2x2 block is available
    # Translation produced 2-level tpu-grammar dev requests totalling 4 cards.
    dev = pod.running_containers["main"].dev_requests
    assert sum(v for k, v in dev.items() if k.endswith("/cards")) == 4
    assert all(k.startswith("resource/group/tpugrp1/") for k in dev)


def test_tpu_scheduler_rejects_when_insufficient():
    from kubetpu.api.types import NodeInfo
    from kubetpu.scheduler import TpuScheduler

    s = TpuScheduler()
    node = NodeInfo(
        name="tpu-node-0",
        allocatable=_v5e8_node_alloc(free_chips=[0, 1]),
        kube_alloc={TPU.resource_name: 2},
    )
    s.add_node("tpu-node-0", node)
    pod = PodInfo(
        name="toolarge",
        running_containers={"main": ContainerInfo(requests={TPU.resource_name: 4})},
    )
    fits, reasons, score = s.pod_fits_device(node, pod, False)
    assert not fits and reasons and reasons[0].resource_name == TPU.resource_name


def test_tpu_scheduler_fragmented_scores_lower():
    from kubetpu.api.types import NodeInfo
    from kubetpu.scheduler import TpuScheduler

    s = TpuScheduler()
    # Node A: contiguous 2x2 free; Node B: 4 scattered chips free.
    node_a = NodeInfo(
        name="a", allocatable=_v5e8_node_alloc([0, 1, 4, 5]),
        kube_alloc={TPU.resource_name: 4},
    )
    node_b = NodeInfo(
        name="b", allocatable=_v5e8_node_alloc([0, 2, 5, 7]),
        kube_alloc={TPU.resource_name: 4},
    )
    s.add_node("a", node_a)
    s.add_node("b", node_b)
    pod = lambda: PodInfo(
        running_containers={"m": ContainerInfo(requests={TPU.resource_name: 4})}
    )
    _, _, score_a = s.pod_fits_device(node_a, pod(), False)
    _, _, score_b = s.pod_fits_device(node_b, pod(), False)
    assert score_a == 1.0
    assert score_b < score_a  # ICI ranking prefers the contiguous node


def test_pristine_fit_cache_shares_without_staleness():
    """Fully-free hosts of the same (topology, host-index) share one
    geometry-search result across nodes and schedulers; a node that stops
    being pristine falls back to a fresh per-state search."""
    from kubetpu.api.types import NodeInfo
    from kubetpu.scheduler import TpuScheduler

    TpuScheduler._pristine_fit.clear()
    s = TpuScheduler()
    nodes = {}
    for name in ("a", "b", "c"):
        nodes[name] = NodeInfo(
            name=name, allocatable=_v5e8_node_alloc(),
            kube_alloc={TPU.resource_name: 8},
        )
        s.add_node(name, nodes[name])
    pod = lambda: PodInfo(
        running_containers={"m": ContainerInfo(requests={TPU.resource_name: 4})}
    )
    for name in ("a", "b", "c"):
        fits, _, score = s.pod_fits_device(nodes[name], pod(), False)
        assert fits and score == 1.0
    # one search served all three pristine nodes
    assert len(s._pristine_fit) == 1

    # a non-pristine node must NOT touch the shared cache: its free set
    # ({0, 2, 5, 7}, scattered) admits no 4-chip rectangle, so a stale
    # pristine hit would report contiguity 1.0
    frag = NodeInfo(
        name="f", allocatable=_v5e8_node_alloc([0, 2, 5, 7]),
        kube_alloc={TPU.resource_name: 4},
    )
    s.add_node("f", frag)
    fits, _, score = s.pod_fits_device(frag, pod(), False)
    assert fits and score < 1.0
    assert len(s._pristine_fit) == 1  # fragmented search never cached

    # a SIX-chip request on a pristine node adds a second entry (new n)
    pod6 = PodInfo(
        running_containers={"m": ContainerInfo(requests={TPU.resource_name: 6})}
    )
    fits, _, _ = s.pod_fits_device(nodes["a"], pod6, False)
    assert fits and len(s._pristine_fit) == 2
