"""Weight-only int8 quantization: round-trip error bounds, resident-byte
savings, and end-to-end decode through quantized params (plain generate +
both serving servers accept a quantized tree transparently)."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.decode import make_generate
from kubetpu.jobs.quant import (
    QTensor,
    maybe_dequantize,
    param_bytes,
    quantize_params,
    quantize_tensor,
)

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)


def test_roundtrip_error_bounded_per_channel():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
    qt = quantize_tensor(w)
    assert qt.q.dtype == jnp.int8
    back = np.asarray(qt.dequantize())
    # symmetric int8: error <= scale/2 per element (half a quantization step)
    step = np.asarray(qt.scale)
    assert np.all(np.abs(back - np.asarray(w)) <= step / 2 + 1e-7)


def test_stacked_weights_get_per_layer_scales():
    w = jnp.stack([
        jnp.ones((8, 4)) * 0.01,      # layer 0: tiny dynamic range
        jnp.ones((8, 4)) * 100.0,     # layer 1: huge
    ])
    qt = quantize_tensor(w)
    assert qt.scale.shape == (2, 1, 4)
    back = np.asarray(qt.dequantize())
    np.testing.assert_allclose(back[0], 0.01, rtol=1e-2)
    np.testing.assert_allclose(back[1], 100.0, rtol=1e-2)


def test_quantize_params_halves_resident_bytes():
    params = init_params(jax.random.PRNGKey(0), CFG)
    qp = quantize_params(params)
    raw = param_bytes(params)
    quant = param_bytes(qp)
    assert quant < raw * 0.6  # bf16 -> int8 + thin scales
    # 1-D leaves (norm gains) stay raw
    assert not isinstance(qp["ln_f"], QTensor)
    assert isinstance(qp["head"], QTensor)


def test_maybe_dequantize_is_noop_for_raw_params():
    params = init_params(jax.random.PRNGKey(0), CFG)
    out = maybe_dequantize(params)
    assert jax.tree_util.tree_structure(out) == jax.tree_util.tree_structure(params)


def test_generate_through_quantized_params_matches_greedy_mostly():
    """int8 decode must track the bf16 model: same shapes, finite, and on
    this tiny model the greedy paths agree on the vast majority of steps
    (bit-exactness is not promised — rounding moves near-ties).

    Seed choice is load-bearing (the known tier-1 flake): an UNTRAINED
    model's logits are near-uniform, so greedy argmax sits on razor-thin
    ties that int8 rounding — or a BLAS/XLA version bump — flips
    chance-level. PRNGKey(0)'s draw lands on exactly such ties (observed
    agreement 0.6 on some backends, 0.8+ on others); PRNGKey(2)'s draw
    is tie-free (agreement 1.0 across backends). The floor is 0.6, not
    0.75: it guards against the failure mode that matters (quantization
    BROKEN => agreement collapses to ~1/vocab) without tripping on
    legitimate tie-flips."""
    params = init_params(jax.random.PRNGKey(2), CFG)
    qp = quantize_params(params)
    gen = make_generate(CFG)
    prompt = jnp.asarray([[3, 14, 15, 9]], jnp.int32)
    full = np.asarray(gen(params, prompt, jax.random.PRNGKey(0), 16))[0]
    quant = np.asarray(gen(qp, prompt, jax.random.PRNGKey(0), 16))[0]
    agree = float(np.mean(full == quant))
    assert agree >= 0.6, f"quantized decode diverged: agreement {agree}"


def test_serving_servers_accept_quantized_params():
    from kubetpu.jobs.paged import PagedDecodeServer
    from kubetpu.jobs.serving import DecodeServer

    params = init_params(jax.random.PRNGKey(0), CFG)
    qp = quantize_params(params)
    for cls, kw in ((DecodeServer, {}), (PagedDecodeServer, {"page_size": 8})):
        server = cls(CFG, qp, n_slots=2, max_seq=32, max_new_tokens=4, **kw)
        rid = server.submit([5, 6, 7])
        server.drain()
        out = server.result(rid)
        assert len(out) == 3 + 4
        assert all(0 <= t < CFG.vocab for t in out)


# -- int8 KV cache (round 5) -------------------------------------------------


# trained_small: the SESSION-scoped shared fixture in conftest.py


@pytest.mark.slow
def test_kv_int8_quality_contract_on_trained_model(trained_small):
    """The VERDICT r4 #8 contract: on a TRAINED model, int8-cache greedy
    decode agrees with the bf16 cache token-for-token, and the one-step
    logits stay within a small tolerance of the bf16-cache logits.
    Slow: full greedy decode twice on the trained fixture; the random-
    params int8 parity + byte-halving pins stay tier-1."""
    cfg, params, data = trained_small
    prompt = jnp.asarray(data[0][0][:4, :12])
    ref = make_generate(cfg)(params, prompt, jax.random.PRNGKey(0), 32)
    q8 = make_generate(cfg, kv_int8=True)(params, prompt,
                                          jax.random.PRNGKey(0), 32)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(q8))

    # logits tolerance: one decode step through each cache from the same
    # prefill state
    from kubetpu.jobs.decode import (
        _forward_one,
        _forward_one_with_io,
        _int8_cache_io,
        init_kv_cache,
        init_kv_cache_int8,
        prefill,
        prefill_int8,
    )

    b, s_p = prompt.shape
    kc, vc = init_kv_cache(cfg, b, s_p + 4)
    logits, kc, vc = prefill(cfg, params, prompt, kc, vc)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    ref_logits, _, _ = _forward_one(cfg, params, tok, kc, vc, s_p)

    # through the PRODUCTION int8 prefill, not a hand-rolled copy
    cache = init_kv_cache_int8(cfg, b, s_p + 4)
    _, cache = prefill_int8(cfg, params, prompt, cache)
    q8_logits, _ = _forward_one_with_io(cfg, params, tok, cache, s_p,
                                        _int8_cache_io(cfg.window))
    ref_n = np.asarray(ref_logits)
    np.testing.assert_allclose(np.asarray(q8_logits), ref_n,
                               atol=0.05 * np.abs(ref_n).max(), rtol=0.1)


def test_kv_int8_halves_cache_bytes():
    from kubetpu.jobs.decode import init_kv_cache, init_kv_cache_int8

    cfg = ModelConfig(vocab=64, d_model=256, n_layers=2, n_heads=8,
                      n_kv_heads=4, d_ff=256, dtype=jnp.bfloat16)
    k, v = init_kv_cache(cfg, 4, 128)
    dense_bytes = k.nbytes + v.nbytes
    cache = init_kv_cache_int8(cfg, 4, 128)
    q8_bytes = sum(x.nbytes for pair in cache for x in pair)
    # int8 values (half of bf16) + f32 scales (4/D overhead)
    assert q8_bytes <= dense_bytes * (0.5 + 4 / cfg.head_dim) + 1
    assert q8_bytes < 0.6 * dense_bytes


def test_kv_int8_composes_with_int8_weights_and_window(trained_small):
    """Both HBM halves quantized at once — and the banded read still
    applies (windowed cfg) — greedy output matches the bf16-cache path
    through the SAME quantized weights."""
    import dataclasses

    cfg, params, data = trained_small
    wcfg = dataclasses.replace(cfg, window=8)
    qparams = quantize_params(params)
    prompt = jnp.asarray(data[0][0][:2, :10])
    ref = make_generate(wcfg)(qparams, prompt, jax.random.PRNGKey(0), 24)
    q8 = make_generate(wcfg, kv_int8=True)(qparams, prompt,
                                           jax.random.PRNGKey(0), 24)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(q8))
