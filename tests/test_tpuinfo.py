"""Integration tests for the native tpuinfo probe: build the C++ binary,
run it through the exec-JSON boundary (kubetpu.device.types.get_devices),
and check it agrees with the in-process fake fixtures."""

import json
import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(REPO, "_output", "tpuinfo")


@pytest.fixture(scope="module")
def tpuinfo_binary():
    if not os.path.exists(BINARY):
        if shutil.which("g++") is None:
            pytest.skip("no g++ toolchain")
        subprocess.run(["make", "-C", REPO, "tpuinfo"], check=True, capture_output=True)
    return BINARY


def test_fake_json_parses_and_matches_python_fixture(tpuinfo_binary):
    from kubetpu.device import make_fake_tpus_info
    from kubetpu.device.types import parse_tpus_info

    out = subprocess.run(
        [tpuinfo_binary, "--fake", "v5e-8"], capture_output=True, check=True
    ).stdout
    native = parse_tpus_info(out)
    python = make_fake_tpus_info("v5e-8")
    assert native.topology.type == python.topology.type == "v5e-8"
    assert [c.coords for c in native.tpus] == [c.coords for c in python.tpus]
    assert [c.path for c in native.tpus] == [c.path for c in python.tpus]
    assert [c.id for c in native.tpus] == [c.id for c in python.tpus]
    assert native.tpus[0].memory.global_bytes == 16 * 1024**3


def test_fake_multi_host_and_missing(tpuinfo_binary):
    from kubetpu.device.types import parse_tpus_info

    out = subprocess.run(
        [tpuinfo_binary, "--fake", "v5e-64", "--host", "3", "--missing", "2,5"],
        capture_output=True,
        check=True,
    ).stdout
    info = parse_tpus_info(out)
    assert info.topology.host_index == 3
    assert info.topology.num_hosts == 8
    assert len(info.tpus) == 6
    assert all(c.index not in (2, 5) for c in info.tpus)
    # host 3 of an 8x8 mesh owns the block at origin (2, 4)
    assert info.tpus[0].coords == (2, 4)


def test_exec_boundary_via_client(tpuinfo_binary, monkeypatch, tmp_path):
    """Drive get_devices() through a wrapper that makes the 'hardware' probe
    deterministic: the binary in fake mode behind KUBETPU_TPUINFO_PATH."""
    from kubetpu.device import types as tputypes

    wrapper = tmp_path / "tpuinfo"
    wrapper.write_text(f"#!/bin/sh\nexec {tpuinfo_binary} --fake v5e-4\n")
    wrapper.chmod(0o755)
    monkeypatch.setenv("KUBETPU_TPUINFO_PATH", str(wrapper))
    info = tputypes.get_devices()
    assert info.topology.type == "v5e-4"
    assert len(info.tpus) == 4


def test_manager_over_native_probe(tpuinfo_binary, monkeypatch, tmp_path):
    """Full node-agent path over the real exec boundary: native probe ->
    manager -> advertisement."""
    from kubetpu.api.types import NodeInfo
    from kubetpu.device.tpu_manager import TpuDevManager
    from kubetpu.plugintypes import ResourceTPU

    wrapper = tmp_path / "tpuinfo"
    wrapper.write_text(f"#!/bin/sh\nexec {tpuinfo_binary} --fake v5e-8\n")
    wrapper.chmod(0o755)
    mgr = TpuDevManager(tpuinfo_path=str(wrapper))
    mgr.new()
    node = NodeInfo(name="n")
    mgr.update_node_info(node)
    assert node.capacity[ResourceTPU] == 8
    assert node.capacity["resource/group/tpu-slice/v5e-8/slice0/0"] == 1


def test_human_mode_runs(tpuinfo_binary):
    out = subprocess.run(
        [tpuinfo_binary, "--fake", "v5e-8", "--human"], capture_output=True, check=True
    ).stdout.decode()
    assert "Topology: v5e-8" in out and "/dev/accel0" in out


def test_bad_topology_errors(tpuinfo_binary):
    proc = subprocess.run(
        [tpuinfo_binary, "--fake", "v9x-999"], capture_output=True
    )
    assert proc.returncode == 2
    assert b"unknown topology" in proc.stderr


def test_sysfs_probe_with_fixture_root(tpuinfo_binary, tmp_path):
    """Probe source 3: a fixtured TPUINFO_SYSFS_ROOT provides both device
    discovery (class/accel entries, /dev masked) and per-device model/vendor
    enrichment (the analog of NVML's model/memory detail,
    nvml.go:57-80)."""
    for i in range(4):
        d = tmp_path / "class" / "accel" / f"accel{i}" / "device"
        d.mkdir(parents=True)
        (d / "vendor").write_text("0x1ae0\n")
        (d / "device").write_text("0x0063\n")
        if i == 0:
            (d / "model").write_text("TPU v5e (sysfs)\n")
    env = dict(os.environ)
    env["TPUINFO_SYSFS_ROOT"] = str(tmp_path)
    env["TPU_ACCELERATOR_TYPE"] = "v5e-4"
    out = subprocess.run(
        [tpuinfo_binary, "json"], capture_output=True, check=True, env=env
    )
    info = json.loads(out.stdout)
    assert info["Topology"]["Type"] == "v5e-4"
    devs = info["Devices"]
    assert [d["Index"] for d in devs] == [0, 1, 2, 3]
    # driver-provided model wins; table model otherwise
    assert devs[0]["Model"] == "TPU v5e (sysfs)"
    assert devs[1]["Model"] == "TPU v5e"
    assert all(d["Pci"] == {"Vendor": "0x1ae0", "Device": "0x0063"} for d in devs)
    # coords still come from the fixed bijection
    assert devs[0]["Coords"] == [0, 0] and devs[3]["Coords"] == [1, 1]


def test_sysfs_vendor_brands_unknown_topology(tpuinfo_binary, tmp_path):
    d = tmp_path / "class" / "accel" / "accel0" / "device"
    d.mkdir(parents=True)
    (d / "vendor").write_text("0x1ae0\n")
    env = dict(os.environ)
    env["TPUINFO_SYSFS_ROOT"] = str(tmp_path)
    env.pop("TPU_ACCELERATOR_TYPE", None)
    out = subprocess.run(
        [tpuinfo_binary, "json"], capture_output=True, check=True, env=env
    )
    info = json.loads(out.stdout)
    # one sysfs-discovered chip; count-inferred topology (v5e-1) or vendor brand
    assert len(info["Devices"]) == 1
    assert info["Devices"][0]["Model"] in ("Google TPU", "TPU v5e")
