"""Round-14 data plane over REAL paged serving replicas (CPU backend):
routing must be semantics-free — greedy tokens through the router, with
prefix-affinity placement and cache hits, byte-identical to a direct
serial run on one replica — and affinity must actually warm the trees
(cluster-wide hits, each prompt family pinned to one replica).

The wire/admission/scaling logic is unit-covered in test_router.py;
``make router-check`` runs this contract under injected faults."""

import pytest

jax = pytest.importorskip("jax")

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402
from kubetpu.router import ReplicaServer, RouterServer  # noqa: E402
from kubetpu.wire.httpcommon import request_json  # noqa: E402

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
PS = 8
MAX_NEW = 4


def _params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _server(params):
    return PagedDecodeServer(
        CFG, params, n_slots=2, max_seq=64, max_new_tokens=MAX_NEW,
        page_size=PS, prefill_budget=PS, prefix_cache_pages=16)


def _family_prompts():
    """Three shared-prefix families (two full pages each) x two tails —
    the fleet workload affinity routing exists for."""
    prompts = []
    for f, seed in enumerate((5, 7, 11)):
        fam = [(i * seed) % 60 + 1 for i in range(2 * PS)]
        for tail in range(2):
            prompts.append(fam + [f * 10 + tail + 1])
    return prompts


@pytest.fixture(scope="module")
def routed_fleet():
    """Router + 2 paged replicas (shared compiled legs) + the routed
    storm's results, torn down after the module."""
    params = _params()
    replicas = []
    for i in range(2):
        rep = ReplicaServer(_server(params), f"paged{i}", idle_wait=0.002)
        rep.start()
        replicas.append(rep)
    router = RouterServer(load_refresh_s=0.05)
    router.start()
    for rep in replicas:
        router.register_replica(rep.address)
    results = []
    for i, prompt in enumerate(_family_prompts()):
        body = request_json(router.address + "/generate",
                            {"prompt": prompt, "timeout": 60.0},
                            idempotency_key=f"t-serve-{i}", timeout=60.0)
        results.append((prompt, body))
    yield router, replicas, results
    router.shutdown()
    for rep in replicas:
        rep.shutdown(graceful=False)


def test_router_tokens_match_direct_serving(routed_fleet):
    """Semantics-free routing: greedy tokens through the router ==
    a quiet direct serial run (same params), prefix-cache hits and
    replica placement notwithstanding."""
    _router, _replicas, results = routed_fleet
    direct = _server(_params())
    for prompt, body in results:
        rid = direct.enqueue(prompt)
        direct.drain()
        assert body["tokens"] == direct.pop_result(rid), (
            f"router tokens diverged for prompt {prompt[:4]}...")


def test_affinity_pins_families_and_warms_trees(routed_fleet):
    """Each shared-prefix family lands on ONE replica, and the second
    member of every family hits that replica's warm radix tree —
    cluster-wide reuse instead of per-replica luck."""
    router, replicas, results = routed_fleet
    prompts = _family_prompts()
    by_family = {}
    for (prompt, body) in results:
        by_family.setdefault(tuple(prompt[:2 * PS]), set()).add(
            body["replica"])
    assert len(by_family) == 3
    for members in by_family.values():
        assert len(members) == 1
    hits = sum(rep.server.prefix_cache_stats()["requests_hit"]
               for rep in replicas)
    # one cold miss per family; every later family member hits
    assert hits >= len(prompts) - len(by_family)
    direct_cells = [rep.server for rep in replicas]
    for srv in direct_cells:
        srv.check_invariants()     # routed storm left the pools honest


def test_load_info_reports_pool_pressure(routed_fleet):
    _router, replicas, _results = routed_fleet
    info = replicas[0].server.load_info()
    assert info["pool_pages"] > 0
    assert 0 <= info["pages_free"] <= info["pool_pages"]
    assert "prefix_hit_rate" in info
    assert info["queue_depth"] == 0
