"""The Vision Transformer family: patchify correctness, flash parity,
sharded training over the shared blocks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, make_mesh
from kubetpu.jobs.vision import (
    VitConfig,
    init_vit_params,
    init_vit_state,
    make_vit_train_step,
    patchify,
    vit_forward,
)

CFG = VitConfig(
    image_size=16, patch_size=4, channels=3, n_classes=10,
    model=ModelConfig(d_model=32, n_layers=2, n_heads=4, d_ff=64),
)


def test_patchify_geometry():
    """Patch (row 0, col 0) must be exactly image[0:P, 0:P] row-major."""
    img = jnp.arange(16 * 16 * 3, dtype=jnp.float32).reshape(1, 16, 16, 3)
    patches = patchify(img, CFG)
    assert patches.shape == (1, 16, 48)
    expected_first = np.asarray(img[0, :4, :4, :]).reshape(-1)
    np.testing.assert_array_equal(np.asarray(patches[0, 0]), expected_first)
    # second patch along the row: columns 4:8
    expected_second = np.asarray(img[0, :4, 4:8, :]).reshape(-1)
    np.testing.assert_array_equal(np.asarray(patches[0, 1]), expected_second)


def test_vit_forward_shape_and_finiteness():
    params = init_vit_params(jax.random.PRNGKey(0), CFG)
    images = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    logits = vit_forward(params, images, CFG)
    assert logits.shape == (2, 10)
    assert np.isfinite(np.asarray(logits)).all()


def test_vit_flash_matches_dense():
    import functools

    from kubetpu.ops import flash_attention

    params = init_vit_params(jax.random.PRNGKey(0), CFG)
    images = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    attn = functools.partial(flash_attention, block_q=16, block_k=16,
                             interpret=True, causal=False)
    np.testing.assert_allclose(
        np.asarray(vit_forward(params, images, CFG, attn_fn=attn)),
        np.asarray(vit_forward(params, images, CFG)),
        rtol=2e-4, atol=2e-5,
    )


@pytest.mark.slow
def test_vit_train_step_learns_on_mesh():
    # Slow: a real ViT train loop on a mesh; forward-parity + the
    # synthetic-images learning test keep vision training tier-1.
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2})
    state, opt = init_vit_state(jax.random.PRNGKey(0), CFG, mesh)
    # blocks tp-sharded via the shared spec tree
    assert state.params["blocks"]["wq"].sharding.spec[2] == "tp"
    step = make_vit_train_step(CFG, mesh, optimizer=opt)
    images = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3))
    labels = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)
    losses = []
    for _ in range(10):
        state, loss = step(state, images, labels)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses


def test_vit_unknown_attention_rejected():
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1})
    with pytest.raises(ValueError):
        make_vit_train_step(CFG, mesh, attention="falsh")


def test_vit_moe_aux_and_config_validation():
    import dataclasses

    with pytest.raises(ValueError):
        VitConfig(image_size=30, patch_size=4)

    base = dataclasses.replace(
        CFG, model=dataclasses.replace(CFG.model, n_experts=4)
    )
    with_aux = dataclasses.replace(
        base, model=dataclasses.replace(base.model, moe_aux_coeff=0.5)
    )
    from kubetpu.jobs.vision import vit_loss

    params = init_vit_params(jax.random.PRNGKey(0), with_aux)
    images = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    labels = jnp.asarray([1, 2])
    plain = float(vit_loss(params, images, labels, base))
    plus = float(vit_loss(params, images, labels, with_aux))
    assert plus > plain  # the aux term was added


@pytest.mark.slow
def test_synthetic_images_feed_training_and_learn():
    from kubetpu.jobs.data import SyntheticImages

    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2})
    state, opt = init_vit_state(jax.random.PRNGKey(0), CFG, mesh)
    step = make_vit_train_step(CFG, mesh, optimizer=opt)
    data = SyntheticImages(image_size=16, n_classes=10)
    it = data.batches(16, seed=1)
    images, labels = next(it)  # fixed batch: memorization shows learning
    losses = []
    for _ in range(10):
        state, loss = step(state, images, labels)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses
