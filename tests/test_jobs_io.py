"""Checkpoint/restore and data-pipeline tests (virtual CPU mesh)."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs import ModelConfig, init_state, make_mesh, make_train_step
from kubetpu.jobs.checkpoint import latest_step_dir, restore_checkpoint, save_checkpoint
from kubetpu.jobs.data import SyntheticCorpus, prefetch_to_mesh

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)


@pytest.mark.slow
def test_checkpoint_roundtrip_preserves_state_and_shardings(tmp_path):
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), CFG, mesh)
    step = make_train_step(CFG, mesh, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, CFG.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    state, _ = step(state, tokens, targets)

    ckpt = tmp_path / "ckpt" / "1"
    save_checkpoint(str(ckpt), state)

    # restore into a FRESH state on the mesh (resume-after-reschedule shape)
    fresh, _ = init_state(jax.random.PRNGKey(42), CFG, mesh)
    restored = restore_checkpoint(str(ckpt), fresh)
    assert int(restored.step) == 1
    np.testing.assert_array_equal(
        np.asarray(restored.params["head"]), np.asarray(state.params["head"])
    )
    assert restored.params["blocks"]["wq"].sharding.spec[2] == "tp"
    # training continues from the restored state
    cont, loss = step(restored, tokens, targets)
    assert jnp.isfinite(loss)
    assert int(cont.step) == 2


def test_latest_step_dir(tmp_path):
    root = tmp_path / "ckpts"
    assert latest_step_dir(str(root)) is None
    for s in (1, 10, 2):
        (root / str(s)).mkdir(parents=True)
    assert latest_step_dir(str(root)).endswith("/10")


def test_synthetic_corpus_deterministic_and_learnable():
    c1 = SyntheticCorpus(vocab=64, seed=3)
    c2 = SyntheticCorpus(vocab=64, seed=3)
    b1 = next(c1.batches(2, 16, seed=7))
    b2 = next(c2.batches(2, 16, seed=7))
    np.testing.assert_array_equal(b1[0], b2[0])
    np.testing.assert_array_equal(b1[1], b2[1])
    # targets are the shifted tokens
    tokens, targets = b1
    np.testing.assert_array_equal(tokens[:, 1:], targets[:, :-1])


def test_prefetch_shards_batches():
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    corpus = SyntheticCorpus(vocab=64)
    it = prefetch_to_mesh(
        iter([b for _, b in zip(range(4), corpus.batches(4, 32))]), mesh
    )
    out = list(it)
    assert len(out) == 4
    tokens, targets = out[0]
    assert tokens.sharding.spec == ("dp", "sp")
    assert tokens.shape == (4, 32)


@pytest.mark.slow
def test_end_to_end_training_on_corpus():
    """Model learns the synthetic corpus' transition structure: loss drops
    well below uniform (ln 64 ~ 4.16)."""
    from kubetpu.jobs.train import make_optimizer

    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    opt = make_optimizer(lr=5e-3)
    state, opt = init_state(jax.random.PRNGKey(0), CFG, mesh, optimizer=opt)
    step = make_train_step(CFG, mesh, optimizer=opt)
    corpus = SyntheticCorpus(vocab=64)
    losses = []
    for tokens, targets in prefetch_to_mesh(
        (b for _, b in zip(range(60), corpus.batches(8, 32))), mesh
    ):
        state, loss = step(state, tokens, targets)
        losses.append(float(loss))
    # uniform over 64 tokens is ln 64 ~ 4.16; the corpus' true entropy is
    # ln 4 ~ 1.39 — learning the transition structure must beat 2.8
    assert losses[-1] < 2.8 < losses[0]


@pytest.mark.slow
def test_checkpoint_restores_across_different_mesh():
    """The resume-on-a-new-slice claim: a state saved under one mesh layout
    restores into a DIFFERENT layout's shardings and keeps training."""
    mesh_a = make_mesh({"dp": 1, "sp": 4, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), CFG, mesh_a)
    step_a = make_train_step(CFG, mesh_a, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, CFG.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    state, _ = step_a(state, tokens, targets)

    import tempfile

    ckpt = tempfile.mkdtemp(prefix="xmesh-") + "/1"
    save_checkpoint(ckpt, state)

    mesh_b = make_mesh({"dp": 2, "sp": 2, "tp": 2})  # different layout
    fresh, opt_b = init_state(jax.random.PRNGKey(9), CFG, mesh_b)
    restored = restore_checkpoint(ckpt, fresh)
    np.testing.assert_array_equal(
        np.asarray(restored.params["head"]), np.asarray(state.params["head"])
    )
    step_b = make_train_step(CFG, mesh_b, optimizer=opt_b)
    cont, loss = step_b(restored, tokens, targets)
    assert jnp.isfinite(loss) and int(cont.step) == 2


@pytest.mark.slow
def test_checkpoint_pipeline_state_roundtrip(tmp_path):
    """pp-sharded (layer-axis) states checkpoint and restore too."""
    from kubetpu.jobs.pipeline import init_pipeline_state, make_pipeline_train_step

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=4, n_heads=4, d_ff=64)
    mesh = make_mesh({"dp": 2, "pp": 2, "sp": 2, "tp": 1, "ep": 1})
    state, opt = init_pipeline_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_pipeline_train_step(cfg, mesh, n_microbatches=2, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    state, _ = step(state, tokens, targets)

    ckpt = tmp_path / "pp" / "1"
    save_checkpoint(str(ckpt), state)
    fresh, _ = init_pipeline_state(jax.random.PRNGKey(7), cfg, mesh)
    restored = restore_checkpoint(str(ckpt), fresh)
    assert restored.params["blocks"]["wq"].sharding.spec[0] == "pp"
    cont, loss = step(restored, tokens, targets)
    assert jnp.isfinite(loss) and int(cont.step) == 2


def test_byte_tokenizer_roundtrip_and_file_bridge(tmp_path):
    """Text -> ByteTokenizer -> TokenFile -> train-shaped batches: the full
    text-to-training bridge, reversible at the token level."""
    from kubetpu.jobs.data import ByteTokenizer
    from kubetpu.jobs.native_data import TokenFile

    tok = ByteTokenizer()
    ids = tok.encode("héllo wörld")
    assert ids[0] == ByteTokenizer.BOS and ids[-1] == ByteTokenizer.EOS
    assert tok.decode(ids) == "héllo wörld"
    assert max(ids) < ByteTokenizer.vocab

    text = tmp_path / "corpus.txt"
    text.write_text("first doc\n\nsecond doc, slightly longer\n\nthird",
                    encoding="utf-8")
    out = tmp_path / "corpus.bin"
    n = tok.encode_file(str(text), str(out))
    assert n > 0
    with TokenFile(str(out)) as tf:
        tokens, targets = next(tf.batches(batch=2, seq=8, seed=0))
        assert tokens.shape == (2, 8) and targets.shape == (2, 8)
        np.testing.assert_array_equal(tokens[:, 1:], targets[:, :-1])
        assert int(tokens.max()) < ByteTokenizer.vocab


def test_evaluate_reports_loss_and_perplexity():
    from kubetpu.jobs import make_eval_step
    from kubetpu.jobs.data import SyntheticCorpus, evaluate

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, _ = init_state(jax.random.PRNGKey(0), cfg, mesh)
    es = make_eval_step(cfg, mesh)
    corpus = SyntheticCorpus(cfg.vocab)
    r = evaluate(es, state.params, corpus.batches(4, 16), n_batches=3)
    assert r["n_batches"] == 3 and r["n_tokens"] == 3 * 4 * 16
    assert np.isfinite(r["loss"]) and r["perplexity"] > 1.0
    # untrained model on a 64-token vocab: loss ~ ln(64)
    assert abs(r["loss"] - np.log(cfg.vocab)) < 1.0


@pytest.mark.slow
def test_async_checkpointer_overlaps_and_restores(tmp_path):
    """AsyncCheckpointer.save returns before I/O completes, training
    continues, and the flushed checkpoint restores exactly."""
    from kubetpu.jobs.checkpoint import AsyncCheckpointer

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    state, _ = step(state, tokens, targets)

    expected_head = np.asarray(jax.device_get(state.params["head"]))

    ckpt = tmp_path / "async" / "1"
    with AsyncCheckpointer() as ac:
        ac.save(str(ckpt), state)
        # train PAST the snapshot while the write drains — the step DONATES
        # state's buffers, so this deletes them; save() must have
        # host-snapshotted already or the background write would read
        # deleted arrays
        cont, _ = step(state, tokens, targets)
        ac.wait()
    fresh, _ = init_state(jax.random.PRNGKey(9), cfg, mesh)
    restored = restore_checkpoint(str(ckpt), fresh)
    # restored state is the SNAPSHOT (step 1), not the continued state
    assert int(restored.step) == 1 and int(cont.step) == 2
    np.testing.assert_allclose(
        np.asarray(jax.device_get(restored.params["head"])),
        expected_head, rtol=1e-6)
    cont2, loss = step(restored, tokens, targets)
    assert jnp.isfinite(loss) and int(cont2.step) == 2


# -- sequence packing ---------------------------------------------------------


def _docs(n, lens, vocab=50, seed=0):
    rng = np.random.RandomState(seed)
    for i in range(n):
        yield rng.randint(1, vocab, size=lens[i % len(lens)]).tolist()


def test_pack_stream_is_dense_and_shifted():
    from kubetpu.jobs.data import pack_documents

    EOS = 0
    batches = list(pack_documents(_docs(40, [7, 13, 29]), batch=4, seq=16,
                                  eos_id=EOS, mode="stream"))
    assert batches, "stream packing produced nothing"
    stream = []
    for d in _docs(40, [7, 13, 29]):
        stream.extend(d)
        stream.append(EOS)
    pos = 0
    all_targets = []
    for tokens, targets, weights in batches:
        assert tokens.shape == targets.shape == weights.shape == (4, 16)
        assert (weights == 1.0).all()  # zero pad: the whole point
        for r in range(4):
            window = stream[pos: pos + 17]
            np.testing.assert_array_equal(tokens[r], window[:-1])
            np.testing.assert_array_equal(targets[r], window[1:])
            all_targets.extend(targets[r].tolist())
            pos += 16  # windows overlap by 1: every position is a target
    # the covered region's every position (past the first) IS a target —
    # a stride of window would skip one per boundary
    np.testing.assert_array_equal(all_targets, stream[1: 1 + len(all_targets)])


def test_prefetch_stages_packed_triples():
    from kubetpu.jobs import make_mesh
    from kubetpu.jobs.data import pack_documents, prefetch_to_mesh

    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 1})
    it = pack_documents(_docs(60, [7, 13]), batch=4, seq=16, eos_id=0,
                        mode="greedy")
    staged = list(prefetch_to_mesh(it, mesh))
    assert staged and all(len(b) == 3 for b in staged)
    assert all(isinstance(x, jax.Array) for b in staged for x in b)


def test_pack_greedy_never_splits_and_masks_pad():
    from kubetpu.jobs.data import pack_documents

    EOS = 0
    lens = [5, 9, 3, 12, 7]
    orig = [tuple(d) for d in _docs(25, lens)]
    batches = list(pack_documents(iter([list(d) for d in orig]), batch=3,
                                  seq=20, eos_id=EOS, mode="greedy"))
    seen = []
    for tokens, targets, weights in batches:
        for r in range(tokens.shape[0]):
            n = int(weights[r].sum())
            # weights are a prefix mask; pad tail is exactly the rest
            assert (weights[r, :n] == 1).all() and (weights[r, n:] == 0).all()
            if n == 0:
                continue
            row = list(tokens[r, :n]) + [int(targets[r, n - 1])]
            # shifted-by-one invariant inside the packed region
            np.testing.assert_array_equal(tokens[r, 1:n], targets[r, : n - 1])
            # rows decompose into WHOLE documents (each ends with EOS)
            assert row[-1] == EOS
            parts, cur = [], []
            for t in row:
                if t == EOS:
                    parts.append(tuple(cur))
                    cur = []
                else:
                    cur.append(t)
            assert not cur
            seen.extend(parts)
    assert sorted(seen) == sorted(orig)  # nothing lost, nothing split


def test_pack_greedy_splits_only_oversized_docs():
    from kubetpu.jobs.data import pack_documents

    EOS = 0
    big = list(range(1, 40))  # longer than seq+1 = 17
    batches = list(pack_documents(iter([big]), batch=2, seq=16, eos_id=EOS,
                                  mode="greedy"))
    toks = np.concatenate([t[w > 0] for t, _g, w in batches])
    # the oversized doc comes through in order (split, not dropped)
    recovered = [int(x) for x in toks if x != EOS]
    assert recovered == big[:len(recovered)] and len(recovered) >= len(big) - 2


@pytest.mark.slow
def test_weighted_train_step_ignores_pad():
    """A packed batch trains through make_train_step(weighted=True); pad
    positions carry no gradient (loss equals the loss of the same batch
    with garbage in the pad region).
    Slow: compiles two full weighted train steps on an 8-way mesh just
    for the loss comparison; packing/masking stays covered by the
    cheaper loss-formula pins in tier-1."""
    from kubetpu.jobs import ModelConfig, init_state, make_mesh, make_train_step
    from kubetpu.jobs.model import next_token_loss

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 1})
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 1, 64)
    targets = jnp.roll(tokens, -1, axis=1)
    weights = jnp.ones((2, 16), jnp.float32).at[:, 10:].set(0.0)
    garbage = tokens.at[:, 10:].set(63)

    l0 = next_token_loss(state.params, tokens, targets, cfg, weights=weights)
    # garbage TARGETS under zero weight change nothing (pad targets are
    # free); garbage INPUT tokens do (they feed attention) — the packer
    # therefore pads inputs with a fixed pad_id, never random junk
    l1 = next_token_loss(state.params, tokens,
                         targets.at[:, 10:].set(63), cfg, weights=weights)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)

    step = make_train_step(cfg, mesh, optimizer=opt, weighted=True,
                           attention="dense")
    losses = []
    for _ in range(8):
        state, loss = step(state, tokens, targets, weights)
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_pack_greedy_isolate_documents_zeros_cross_doc_transitions():
    """isolate_documents=True: every EOS -> next-document-first-token
    transition carries weight 0 (no position trains on predicting an
    unrelated document's opening token); all other packed positions keep
    weight 1 and the document decomposition is unchanged."""
    from kubetpu.jobs.data import pack_documents

    EOS = 0
    lens = [5, 9, 3, 12, 7]
    docs = [list(d) for d in _docs(25, lens)]
    iso = list(pack_documents(iter(docs), batch=3, seq=20, eos_id=EOS,
                              mode="greedy", isolate_documents=True))
    ref = list(pack_documents(iter([list(d) for d in docs]), batch=3,
                              seq=20, eos_id=EOS, mode="greedy"))
    assert len(iso) == len(ref)
    for (t1, g1, w1), (t2, g2, w2) in zip(iso, ref):
        np.testing.assert_array_equal(t1, t2)
        np.testing.assert_array_equal(g1, g2)
        # the zeroed positions are EXACTLY the cross-document transitions:
        # tokens==EOS (a document just ended) with a real packed target
        diff = (w2 == 1.0) & (w1 == 0.0)
        expect = (t2 == EOS) & (w2 == 1.0)
        # ...except a row's FINAL document's EOS, whose target is pad/next
        # nothing — that position was already weight-0 in both
        np.testing.assert_array_equal(diff, expect)
        # everything else untouched
        np.testing.assert_array_equal(w1[~expect], w2[~expect])


def test_checkpoint_save_is_atomic_and_corrupt_load_is_typed(tmp_path):
    """Crash-safe checkpoints: a save never leaves a torn directory at the
    real path (temp-write + atomic rename; stale .tmp orphans are ignored
    by latest_step_dir), and loading a mangled checkpoint raises the typed
    CorruptCheckpointError — not an anonymous orbax stack trace."""
    import os

    import pytest

    from kubetpu.jobs.checkpoint import CorruptCheckpointError

    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, _opt = init_state(jax.random.PRNGKey(0), CFG, mesh)
    root = tmp_path / "ckpts"
    ckpt = root / "1"
    save_checkpoint(str(ckpt), state)
    # no temp residue after a clean save, and the step dir is discoverable
    assert [d for d in os.listdir(root) if ".tmp-" in d] == []
    assert latest_step_dir(str(root)).endswith("/1")

    # a crashed writer's orphan must not shadow the real checkpoint
    (root / "2.tmp-9999").mkdir()
    assert latest_step_dir(str(root)).endswith("/1")

    # missing checkpoint -> typed error
    fresh, _ = init_state(jax.random.PRNGKey(1), CFG, mesh)
    with pytest.raises(CorruptCheckpointError):
        restore_checkpoint(str(root / "404"), fresh)

    # mangled fixture: truncate every data file orbax wrote
    mangled = 0
    for dirpath, _dirs, files in os.walk(ckpt):
        for f in files:
            p = os.path.join(dirpath, f)
            if os.path.getsize(p) > 8:
                with open(p, "r+b") as fh:
                    fh.truncate(4)
                mangled += 1
    assert mangled > 0
    with pytest.raises(CorruptCheckpointError):
        restore_checkpoint(str(ckpt), fresh)


def test_async_checkpointer_commits_on_wait(tmp_path):
    """AsyncCheckpointer writes to .tmp-* and renames on wait/close — a
    reader polling latest_step_dir never sees a half-written step."""
    from kubetpu.jobs.checkpoint import AsyncCheckpointer

    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    state, _opt = init_state(jax.random.PRNGKey(0), CFG, mesh)
    root = tmp_path / "ckpts"
    with AsyncCheckpointer() as ckptr:
        ckptr.save(str(root / "1"), state)
        ckptr.wait()   # commit point
        assert latest_step_dir(str(root)).endswith("/1")
        ckptr.save(str(root / "2"), state)
    # close() flushed + committed the in-flight save
    assert latest_step_dir(str(root)).endswith("/2")
    fresh, _ = init_state(jax.random.PRNGKey(7), CFG, mesh)
    restored = restore_checkpoint(str(root / "2"), fresh)
    np.testing.assert_array_equal(
        np.asarray(restored.params["head"]), np.asarray(state.params["head"])
    )
