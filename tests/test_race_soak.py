"""Round-13 threaded race soak — the runtime twin of KTP008/KTP009.

The static rules claim the thread contract holds: wire-handler threads
touch only lock-guarded surfaces (the obs Registry, the EventLog ring,
the tracer), the step loop owns all serving state, and no lock order
cycles exist. This soak is the dynamic oracle for that claim: one
thread drives ``step()`` on a ``PagedDecodeServer`` (admissions, decode,
drains, checkpoint saves) while wire-handler threads hammer the
``MetricsServer`` exposing that SAME server's registry and event log —
through the fault-injected retrying client, so handlers see drops,
delays and retries (>= 10% injected) exactly like the chaos suite's
control plane.

Oracles, in order of strength:

- **token exactness**: the concurrently-scraped run must emit byte-for-
  byte the tokens a quiet serial replay emits — scraping is read-only
  or it isn't, there is no "mostly";
- **pool accounting**: ``check_invariants()`` (free + slot-private +
  tree-owned == n_pages, refcounts == pins) after every drain and at
  the end;
- **metric-counter consistency**: the final exposition parses clean,
  TTFT samples == finished requests, admit events == retire events ==
  requests, and the scrape responses themselves were well-formed under
  fault injection;
- **liveness**: no thread died, every scraper made progress, faults
  actually fired.

The short soak rides tier-1; the 30s+ one is ``slow`` and runs under
``make chaos`` next to the control-plane soak.
"""

import threading
import time

import jax
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.paged import PagedDecodeServer
from kubetpu.obs.events import validate_events_jsonl
from kubetpu.obs.exporter import MetricsServer
from kubetpu.obs.registry import validate_prometheus_text
from kubetpu.wire.faults import FaultInjector, RoutePolicy
from kubetpu.wire.httpcommon import RetryPolicy, request_text

pytestmark = pytest.mark.chaos

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)

# generous attempts: the soak asserts convergence THROUGH faults, so a
# scraper must practically never exhaust its budget at a ~10-30% rate
SOAK_RETRY = RetryPolicy(attempts=6, base_delay=0.01, max_delay=0.05,
                         deadline=10.0)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _mk_server(params):
    return PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                             max_new_tokens=6, page_size=8,
                             prefill_budget=8)


def _prompt(i):
    return [(i * 11 + j * 3) % 60 + 1 for j in range(4 + (i * 5) % 9)]


def _serial_reference(params, n_requests):
    """The quiet replay: same prompts, same server config, no scrapers —
    what the soaked run must reproduce token-for-token."""
    server = _mk_server(params)
    out = {}
    for i in range(n_requests):
        rid = server.enqueue(_prompt(i))
        server.step()
        out[i] = rid
    server.drain()
    return {i: server.result(rid) for i, rid in out.items()}


def _scraper(address, stop, injector, errors, stats, validate_every=7):
    n = 0
    while not stop.is_set():
        n += 1
        try:
            text = request_text(address + "/metrics", timeout=5,
                                retry=SOAK_RETRY, faults=injector)
            stats["scrapes"] += 1
            if n % validate_every == 0:
                problems = validate_prometheus_text(text)
                if problems:
                    errors.append(f"malformed exposition: {problems[:3]}")
            ev = request_text(address + "/events?limit=64", timeout=5,
                              retry=SOAK_RETRY, faults=injector)
            stats["scrapes"] += 1
            if n % validate_every == 0:
                problems = validate_events_jsonl(ev)
                if problems:
                    errors.append(f"malformed events: {problems[:3]}")
        except Exception as e:  # noqa: BLE001 — a scraper death is a FAIL
            errors.append(f"scraper died: {type(e).__name__}: {e}")
            return


def _run_race_soak(params, tmp_path, seconds, fault_rate, seed,
                   n_scrapers=3):
    from kubetpu.jobs.checkpoint import save_checkpoint
    from kubetpu.jobs.train import TrainState

    reference_n = 6
    reference = _serial_reference(params, reference_n)

    server = _mk_server(params)
    exporter = MetricsServer({"serving": server.obs}, events=server.events)
    exporter.start()
    stop = threading.Event()
    errors: list = []
    stats = {"scrapes": 0}
    per = fault_rate / 2.0
    injectors = [
        FaultInjector(seed=seed + i,
                      default=RoutePolicy(drop=per, delay=per, delay_s=0.002))
        for i in range(n_scrapers)
    ]
    threads = [
        threading.Thread(target=_scraper,
                         args=(exporter.address, stop, inj, errors, stats),
                         daemon=True)
        for inj in injectors
    ]
    ck_state = TrainState(params=params, opt_state=(),
                          step=jax.numpy.zeros((), jax.numpy.int32))
    try:
        server.warmup()
        for t in threads:
            t.start()
        deadline = time.monotonic() + seconds
        results = {}
        pending = {}
        i = 0
        rounds = 0
        while (time.monotonic() < deadline or pending
               or len(results) < reference_n):
            # keep a couple of requests in flight, FIFO-collect finishes;
            # past the deadline, top up only until the reference set (the
            # exactness oracle's prompts) has all been admitted
            while len(pending) < 3 and (time.monotonic() < deadline
                                        or i < reference_n):
                pending[i] = server.enqueue(_prompt(i))
                i += 1
            server.step()
            for key in list(pending):
                rid = pending[key]
                if server.finished(rid):
                    results[key] = server.result(rid)
                    del pending[key]
            rounds += 1
            if rounds % 16 == 0:
                # drain + pool oracle mid-flight, on the step thread (the
                # serving object is loop-owned state — that is the thread
                # contract KTP009 pins)
                server.drain()
                for key in list(pending):
                    results[key] = server.result(pending[key])
                    del pending[key]
                server.check_invariants()
            if rounds % 8 == 0:
                save_checkpoint(str(tmp_path / "soak_ck"), ck_state)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        exporter.shutdown()

    assert errors == [], f"wire-thread failures: {errors[:5]}"
    server.drain()
    server.check_invariants()

    # -- token exactness vs the quiet serial replay ------------------------
    assert len(results) >= reference_n, "soak produced too few requests"
    for key in range(reference_n):
        assert results[key] == reference[key], (
            f"request {key} diverged under concurrent scraping: "
            f"{results[key]} != {reference[key]}"
        )

    # -- metric-counter consistency ----------------------------------------
    text = server.metrics_text()
    assert validate_prometheus_text(text) == []
    stats_summary = server.metrics_summary()
    n_done = len(results)
    assert stats_summary["ttft"]["count"] == n_done
    ev_counts = server.events.counts()
    admits = sum(v for k, v in ev_counts.items() if k.startswith("admit"))
    assert admits == n_done, f"admit events {admits} != requests {n_done}"
    assert ev_counts.get("retire", 0) == n_done
    total_tokens = sum(len(v) for v in results.values())
    assert total_tokens >= n_done  # every request emitted

    # -- liveness: the soak actually soaked --------------------------------
    injected = sum(sum(inj.counts.values()) for inj in injectors)
    assert injected > 0, "no faults injected — dead knob?"
    assert stats["scrapes"] >= n_scrapers * 2, "scrapers made no progress"
    return stats, injected


@pytest.mark.slow
def test_race_soak_short(params, tmp_path):
    """Short soak (make chaos / unfiltered runs — slow-marked for the
    tier-1 wall budget): ~2.5s of concurrent step+scrape at >= 10%
    injected faults, token-exact vs serial, clean pool + counters."""
    _run_race_soak(params, tmp_path, seconds=2.5, fault_rate=0.12,
                   seed=4242)


@pytest.mark.slow
def test_race_soak_long(params, tmp_path):
    """The full soak (make chaos): 30+ seconds at ~25% injected faults —
    the acceptance oracle for KTP008/KTP009's static claims."""
    stats, injected = _run_race_soak(params, tmp_path, seconds=32,
                                     fault_rate=0.25, seed=987,
                                     n_scrapers=4)
    # a 30s soak must accumulate real coverage on both sides
    assert stats["scrapes"] > 50
    assert injected > 10
