"""Round-14 data plane: hash-ring stability, the replica wire surface,
affinity routing + load fallback, SLO-class admission, breaker health,
graceful drain, and the autoscaler's event-sequence contract.

Everything here runs against ``FakeSlotServer`` — a host-only stand-in
implementing the ``SlotServerBase`` duck surface — so the wire/admission
/scaling logic is exercised without jax device work (the jax-backed
token-exactness and warm-hit contracts live in
``tests/test_router_serving.py`` and ``make router-check``)."""

import threading
import time
import urllib.error

import pytest

from kubetpu.obs.events import EventLog
from kubetpu.obs.registry import Registry, validate_prometheus_text
from kubetpu.obs.slo import Objective
from kubetpu.router import (
    HashRing,
    ReplicaAutoscaler,
    ReplicaServer,
    RouterServer,
    ScalePolicy,
    prefix_head_key,
)
from kubetpu.wire.faults import FaultInjector, RoutePolicy
from kubetpu.wire.httpcommon import NO_RETRY, request_json, request_text


class FakeSlotServer:
    """Host-only ``SlotServerBase`` duck: admits into ``n_slots``,
    emits one deterministic token per step (prompt reversed, cycled),
    finishes after ``max_new`` tokens. ``load_override`` lets tests
    feed the autoscaler synthetic pressure signals."""

    def __init__(self, n_slots=2, max_new=3, step_sleep=0.0):
        self.obs = Registry()
        self.events = EventLog(component="serving")
        self.slo = None
        self.n_slots = n_slots
        self.max_new = max_new
        self.step_sleep = step_sleep
        self.load_override = {}
        self._next = 0
        self._queue = []
        self._prompts = {}
        self._emitted = {}
        self._active = set()
        self._done = {}
        self._expired = {}
        self.obs.gauge_fn("kubetpu_serving_queue_depth",
                          lambda: len(self._queue))
        self.obs.gauge_fn("kubetpu_serving_active_slots",
                          lambda: len(self._active))

    def enqueue(self, prompt, sampling=None, ttl=None):
        if not prompt:
            raise ValueError("empty prompt")
        if sampling and float(sampling.get("temperature", 0) or 0) < 0:
            raise ValueError("temperature must be >= 0")
        rid = self._next
        self._next += 1
        self._prompts[rid] = list(prompt)
        self._emitted[rid] = []
        self._done[rid] = False
        self._queue.append(rid)
        return rid

    def step(self):
        if self.step_sleep:
            time.sleep(self.step_sleep)
        while self._queue and len(self._active) < self.n_slots:
            rid = self._queue.pop(0)
            self._active.add(rid)
            self.events.emit("admit", rid=rid)
        out = {}
        for rid in sorted(self._active):
            toks = self._emitted[rid]
            prompt = self._prompts[rid]
            toks.append(prompt[::-1][len(toks) % len(prompt)])
            out[rid] = [toks[-1]]
            if len(toks) >= self.max_new:
                self._done[rid] = True
                self._active.discard(rid)
                self.events.emit("retire", rid=rid)
        return out

    def _idle(self):
        return not self._queue and not self._active

    def finished(self, rid):
        return self._done.get(rid, False)

    def cancel(self, rid):
        if self._done.get(rid, True):
            return False
        self._queue = [r for r in self._queue if r != rid]
        self._active.discard(rid)
        self._done[rid] = True
        return True

    def expire_reason(self, rid):
        return self._expired.get(rid)

    def pop_result(self, rid):
        out = self._prompts.pop(rid) + self._emitted.pop(rid)
        del self._done[rid]
        return out

    # -- Round-16 migration duck surface (host-only: nothing to
    # snapshot, so drains with a migrate target complete via idleness)

    def migratable_rids(self):
        return []

    def migrated_to(self, rid):
        return None

    def unfinished_rids(self):
        return sorted(set(self._queue) | self._active)

    def cancel_expired(self, rid, reason):
        if self._done.get(rid, False):
            return False
        self._expired[rid] = str(reason)
        return self.cancel(rid)

    def metrics_text(self):
        return self.obs.render()

    def load_info(self):
        info = {
            "n_slots": self.n_slots,
            "active_slots": len(self._active),
            "queue_depth": len(self._queue),
            "inflight_prefills": 0,
            "queue_wait_p99_ms": 0.0,
            "ttft_p50_ms": 0.0,
        }
        info.update(self.load_override)
        return info


@pytest.fixture()
def fleet(request):
    """(router, [(replica_server, fake)]) with 2 registered replicas;
    everything shut down at teardown."""
    made = []

    def build(n=2, router_kw=None, fake_kw=None):
        router = RouterServer(load_refresh_s=0.0, **(router_kw or {}))
        router.start()
        replicas = []
        for i in range(n):
            fake = FakeSlotServer(**(fake_kw or {}))
            rep = ReplicaServer(fake, f"rep{i}", idle_wait=0.002)
            rep.start()
            router.register_replica(rep.address)
            replicas.append((rep, fake))
        made.append((router, replicas))
        return router, replicas

    yield build
    for router, replicas in made:
        router.shutdown()
        for rep, _fake in replicas:
            rep.shutdown(graceful=False)


# -- hashing -----------------------------------------------------------------


def test_prefix_head_key_depends_only_on_head():
    a = prefix_head_key([5] * 40 + [1], head_tokens=32)
    b = prefix_head_key([5] * 40 + [2, 3, 4], head_tokens=32)
    c = prefix_head_key([6] + [5] * 39, head_tokens=32)
    assert a == b          # tails past the head don't matter
    assert a != c          # any head token does
    # stable across processes/runs: pinned literal
    assert prefix_head_key([1, 2, 3]) == (
        prefix_head_key((1, 2, 3)))


def test_ring_add_remaps_about_one_over_n():
    """Adding a 5th replica must remap ~1/5 of keys — every moved key
    moving TO the newcomer — and removing it must restore the exact
    prior mapping (the scale-event cache-survival contract)."""
    keys = [prefix_head_key([i, i * 3, i * 7]) for i in range(1000)]
    ring = HashRing(vnodes=64)
    for n in ("r0", "r1", "r2", "r3"):
        ring.add(n)
    before = {k: ring.lookup(k) for k in keys}
    ring.add("r4")
    after = {k: ring.lookup(k) for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    # expected 0.20 at 64 vnodes; generous bounds for the fixed hash
    assert 0.08 < len(moved) / len(keys) < 0.40
    assert all(after[k] == "r4" for k in moved)
    ring.remove("r4")
    assert {k: ring.lookup(k) for k in keys} == before


def test_ring_remove_only_moves_the_removed_owner():
    keys = [prefix_head_key([i, i + 1]) for i in range(1000)]
    ring = HashRing(vnodes=64)
    for n in ("r0", "r1", "r2", "r3"):
        ring.add(n)
    before = {k: ring.lookup(k) for k in keys}
    ring.remove("r1")
    after = {k: ring.lookup(k) for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    assert moved and all(before[k] == "r1" for k in moved)
    assert all(after[k] != "r1" for k in keys)


def test_ring_preference_is_deterministic_and_full():
    ring = HashRing(vnodes=16)
    for n in ("a", "b", "c"):
        ring.add(n)
    key = prefix_head_key([9, 9, 9])
    pref = ring.preference(key)
    assert sorted(pref) == ["a", "b", "c"]
    assert pref == ring.preference(key)
    assert ring.preference(key, n=1) == [pref[0]]
    assert HashRing().preference(key) == []


# -- replica wire surface ----------------------------------------------------


def test_replica_generate_roundtrip(fleet):
    _router, replicas = fleet(n=1)
    rep, _fake = replicas[0]
    body = request_json(rep.address + "/generate",
                        {"prompt": [1, 2, 3]},
                        idempotency_key="t-rt-1")
    assert body["tokens"][:3] == [1, 2, 3]
    assert len(body["emitted"]) == 3          # max_new
    assert body["replica"] == "rep0"
    load = request_json(rep.address + "/load")
    assert load["queue_depth"] == 0 and load["draining"] is False
    text = request_text(rep.address + "/metrics")
    assert validate_prometheus_text(text) == []
    assert "kubetpu_replica_generate_requests_total 1" in text


def test_replica_idempotent_replay_no_double_admission(fleet):
    _router, replicas = fleet(n=1)
    rep, fake = replicas[0]
    first = request_json(rep.address + "/generate", {"prompt": [7, 8]},
                         idempotency_key="t-replay")
    again = request_json(rep.address + "/generate", {"prompt": [7, 8]},
                         idempotency_key="t-replay")
    assert again == first                     # committed result replayed
    assert len(fake.events.events(kind="admit")) == 1
    text = request_text(rep.address + "/metrics")
    assert "kubetpu_replica_generate_requests_total 1" in text
    assert "kubetpu_replica_generate_replays_total 1" in text


def test_replica_truncated_response_retry_is_replayed():
    """The partial fault: the first POST EXECUTES but its response is
    truncated mid-write; the client's keyed retry must get the
    committed tokens replayed — never a second admission (the
    double-allocation window idempotency keys exist for)."""
    fake = FakeSlotServer()
    faults = FaultInjector(seed=3, routes={
        "/generate": RoutePolicy(partial=1.0, times=1)})
    rep = ReplicaServer(fake, "rp", faults=faults, idle_wait=0.002)
    rep.start()
    try:
        body = request_json(rep.address + "/generate",
                            {"prompt": [4, 5, 6]},
                            idempotency_key="t-partial")
        assert body["tokens"][:3] == [4, 5, 6]
        assert faults.counts.get("partial") == 1
        assert len(fake.events.events(kind="admit")) == 1
        text = request_text(rep.address + "/metrics")
        assert "kubetpu_replica_generate_replays_total 1" in text
    finally:
        rep.shutdown(graceful=False)


def test_draining_replica_completes_inflight_requests(fleet):
    """The scale-down prerequisite: a request in flight when drain
    lands COMPLETES (tokens delivered), while new work is refused."""
    _router, replicas = fleet(n=1, fake_kw={"step_sleep": 0.03,
                                            "max_new": 5})
    rep, _fake = replicas[0]
    out = {}

    def go():
        out["body"] = request_json(rep.address + "/generate",
                                   {"prompt": [1, 2]},
                                   idempotency_key="t-drain",
                                   timeout=30.0)

    t = threading.Thread(target=go)
    t.start()
    time.sleep(0.06)              # mid-generation
    request_json(rep.address + "/drain", {},
                 idempotency_key="t-drain-post")
    t.join(timeout=10.0)
    assert not t.is_alive()
    assert len(out["body"]["emitted"]) == 5   # completed, not dropped
    with pytest.raises(urllib.error.HTTPError) as ei:
        request_json(rep.address + "/generate", {"prompt": [9]},
                     retry=NO_RETRY)
    assert ei.value.code == 503


# -- routing -----------------------------------------------------------------


def test_affinity_same_head_same_replica(fleet):
    router, _replicas = fleet(n=3)
    heads = {}
    for fam in range(3):
        picks = set()
        for tail in range(4):
            body = request_json(
                router.address + "/generate",
                {"prompt": [fam + 1] * 40 + [tail + 1]},
                idempotency_key=f"t-aff-{fam}-{tail}")
            picks.add(body["replica"])
            assert body["affinity"] is True
        assert len(picks) == 1                # family sticks together
        heads[fam] = picks.pop()
    counts = router.events.counts()
    assert counts.get("route") == 12


def test_load_fallback_skips_overloaded_target(fleet):
    router, replicas = fleet(n=2)
    prompt = [3] * 40
    target = request_json(router.address + "/generate",
                          {"prompt": prompt},
                          idempotency_key="t-fb-0")["replica"]
    # overload the affinity target: deep queue in its /load snapshot
    fake = dict(replicas)[  # name -> fake via the replica servers
        {rep.name: rep for rep, _f in replicas}[target]]
    fake.load_override = {"queue_depth": 99}
    router.pool.refresh(0.0)
    body = request_json(router.address + "/generate",
                        {"prompt": prompt},
                        idempotency_key="t-fb-1")
    assert body["replica"] != target
    assert body["affinity"] is False
    assert router._c_fallback.value >= 1
    # pressure clears -> affinity returns home
    fake.load_override = {}
    router.pool.refresh(0.0)
    body = request_json(router.address + "/generate",
                        {"prompt": prompt},
                        idempotency_key="t-fb-2")
    assert body["replica"] == target and body["affinity"] is True


def test_cordoned_affinity_target_is_an_honest_fallback(fleet):
    """When the TRUE ring target is draining, landing elsewhere must
    report affinity=False and count as a fallback — the health-skip
    case the fallback metric exists to measure."""
    router, replicas = fleet(n=2)
    prompt = [6] * 40
    target = request_json(router.address + "/generate",
                          {"prompt": prompt},
                          idempotency_key="t-cord-0")["replica"]
    router.pool.drain(target)
    before = router._c_fallback.value
    body = request_json(router.address + "/generate", {"prompt": prompt},
                        idempotency_key="t-cord-1")
    assert body["replica"] != target
    assert body["affinity"] is False
    assert router._c_fallback.value == before + 1


def test_pool_drain_cordon_is_sticky_across_refresh(fleet):
    """pool.drain() promises the cordon holds even when the /drain POST
    was lost: a later refresh reading draining=False from the replica
    must NOT un-cordon the handle."""
    router, replicas = fleet(n=2)
    rep, _fake = replicas[0]
    with router.pool._lock:
        router.pool._replicas[rep.name].draining = True   # as if POST lost
    router.pool.refresh(0.0)      # replica itself reports draining=False
    assert rep.name not in router.pool.routable()


def test_replica_client_error_passes_through_without_failover(fleet):
    """A deterministic replica 4xx (bad sampling) surfaces as-is — not
    retried on a second replica, not mis-filed as upstream_error."""
    router, _replicas = fleet(n=2)
    with pytest.raises(urllib.error.HTTPError) as ei:
        request_json(router.address + "/generate",
                     {"prompt": [1, 2],
                      "sampling": {"temperature": -1.0}},
                     retry=NO_RETRY)
    assert ei.value.code == 400
    assert router._c_uperr.value == 0


def test_autoscaler_launcher_receives_vchip_share(fleet):
    """Round-18: a ``launcher(role, frac)`` is handed the pool's
    ``vchip_frac`` so a scale-up boots a PACKED fractional replica; a
    zero-arg launcher keeps today's whole-chip behavior; and a
    fractional policy with a share-blind launcher fails loudly instead
    of silently booting whole-chip replicas."""
    router, replicas = fleet(n=1)
    launched = []

    def launcher(role, frac):
        launched.append((role, frac))
        fake = FakeSlotServer()
        rep = ReplicaServer(fake, f"vc{len(launched)}", idle_wait=0.002)
        rep.start()
        launched_reps.append(rep)
        return rep.address

    launched_reps = []
    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=3, up_after=1,
                           cooldown_s=0.0, vchip_frac=0.25))
    replicas[0][1].load_override = {"queue_wait_p99_ms": 9999.0}
    res = scaler.poll_once()
    assert res["action"] and res["action"].startswith("scale_up:")
    assert launched == [("both", 0.25)]
    for rep in launched_reps:
        rep.shutdown(graceful=False)


def test_autoscaler_zero_arg_launcher_keeps_whole_chip_default(fleet):
    router, replicas = fleet(n=1)
    launched = []

    def launcher():
        fake = FakeSlotServer()
        rep = ReplicaServer(fake, f"z{len(launched)}", idle_wait=0.002)
        rep.start()
        launched.append(rep)
        return rep.address

    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=3, up_after=1,
                           cooldown_s=0.0))          # vchip_frac=1.0
    replicas[0][1].load_override = {"queue_wait_p99_ms": 9999.0}
    res = scaler.poll_once()
    assert res["action"] and res["action"].startswith("scale_up:")
    assert len(launched) == 1
    for rep in launched:
        rep.shutdown(graceful=False)


def test_autoscaler_legacy_two_param_launcher_not_fed_the_share(fleet):
    """A pre-Round-18 ``launcher(role, port_base=9000)`` (defaulted
    second extra) was called with ONE arg — raw arity must not start
    feeding 1.0 into its unrelated parameter. Only a REQUIRED second
    positional (or one named for the share) receives vchip_frac."""
    router, replicas = fleet(n=1)
    launched = []
    launched_reps = []

    def launcher(role, port_base=9000):
        launched.append((role, port_base))
        fake = FakeSlotServer()
        rep = ReplicaServer(fake, f"lg{len(launched)}", idle_wait=0.002)
        rep.start()
        launched_reps.append(rep)
        return rep.address

    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=3, up_after=1,
                           cooldown_s=0.0))          # vchip_frac=1.0
    replicas[0][1].load_override = {"queue_wait_p99_ms": 9999.0}
    res = scaler.poll_once()
    assert res["action"] and res["action"].startswith("scale_up:")
    assert launched == [("both", 9000)]   # default intact, no 1.0 fed in
    for rep in launched_reps:
        rep.shutdown(graceful=False)
    # and under a FRACTIONAL policy the same launcher is share-blind:
    # loud scale_error, never 0.5 silently bound to port_base
    launched.clear()
    scaler2 = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=3, up_after=1,
                           cooldown_s=0.0, vchip_frac=0.5))
    replicas[0][1].load_override = {"queue_wait_p99_ms": 9999.0}
    scaler2.poll_once()
    assert launched == []                 # never called with the share
    errs = [e for e in router.events.events() if e["kind"] == "scale_error"]
    assert errs and "launcher(role, frac)" in errs[-1]["error"]


def test_autoscaler_fractional_policy_refuses_share_blind_launcher(fleet):
    """vchip_frac < 1 with a launcher that cannot receive the share
    would strand (1 - frac) of every chip while the config claims
    packing — the pass must scale_error, not launch."""
    router, replicas = fleet(n=1)
    launched = []

    def launcher(role):                  # role-aware but share-blind
        launched.append(role)
        return "http://127.0.0.1:1"

    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=3, up_after=1,
                           cooldown_s=0.0, vchip_frac=0.5))
    replicas[0][1].load_override = {"queue_wait_p99_ms": 9999.0}
    res = scaler.poll_once()
    assert res["action"] is None
    assert launched == []
    errs = [e for e in router.events.events() if e["kind"] == "scale_error"]
    assert errs and "launcher(role, frac)" in errs[-1]["error"]


def test_scale_policy_rejects_bad_vchip_frac():
    with pytest.raises(ValueError):
        ScalePolicy(vchip_frac=0.0)
    with pytest.raises(ValueError):
        ScalePolicy(vchip_frac=1.5)


def test_autoscaler_reaps_dead_and_scale_up_gate_uses_alive(fleet):
    """A breaker-DEAD replica is reaped from the pool/ring, and the
    max_replicas gate counts ALIVE capacity — a dead handle must not
    hold the fleet one replica short while it burns."""
    router, replicas = fleet(n=2)
    launched = []

    def launcher():
        fake = FakeSlotServer()
        rep = ReplicaServer(fake, f"heal{len(launched)}", idle_wait=0.002)
        rep.start()
        launched.append(rep)
        return rep.address

    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=2, up_after=1,
                           cooldown_s=0.0))
    dead_rep, _fake = replicas[0]
    dead_rep.shutdown(graceful=False)
    for _ in range(5):
        router.pool.refresh(0.0)
    assert router.pool.state(dead_rep.name) == "dead"
    # pressure on the survivor: at max_replicas=2 the dead handle would
    # have blocked healing; reap + alive-gate let the fleet recover
    replicas[1][1].load_override = {"queue_wait_p99_ms": 9999.0}
    res = scaler.poll_once()
    assert dead_rep.name not in router.pool.names()       # reaped
    assert res["action"] and res["action"].startswith("scale_up:")
    assert len(router.pool.alive()) == 2
    kinds = [e["kind"] for e in router.events.events()]
    assert "reap" in kinds
    for rep in launched:
        rep.shutdown(graceful=False)


def test_autoscaler_heals_below_min_replicas_without_heat(fleet):
    """min_replicas is a FLOOR, not just a scale-down gate: a fleet
    reaped below it produces no hot signals (no traffic, absent SLIs),
    so healing must not wait for hysteresis heat."""
    router, replicas = fleet(n=1)
    launched = []

    def launcher():
        fake = FakeSlotServer()
        rep = ReplicaServer(fake, f"floor{len(launched)}", idle_wait=0.002)
        rep.start()
        launched.append(rep)
        return rep.address

    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=2, up_after=99,
                           cooldown_s=0.0))
    rep, _fake = replicas[0]
    rep.shutdown(graceful=False)
    for _ in range(5):
        router.pool.refresh(0.0)
    res = scaler.poll_once()        # reaps the dead one, heals the floor
    assert res["action"] and res["action"].startswith("scale_up:")
    assert len(router.pool.alive()) == 1
    for r in launched:
        r.shutdown(graceful=False)


def test_register_name_conflict_is_409_not_silent_swap(fleet):
    router, replicas = fleet(n=1)
    rep, _fake = replicas[0]
    other = ReplicaServer(FakeSlotServer(), "elsewhere", idle_wait=0.002)
    other.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            request_json(router.address + "/replicas",
                         {"url": other.address, "name": rep.name},
                         idempotency_key="t-conflict")
        assert ei.value.code == 409
        assert router.pool.url(rep.name) == rep.address   # untouched
    finally:
        other.shutdown(graceful=False)


def test_random_policy_spreads(fleet):
    router, _replicas = fleet(n=2, router_kw={"policy": "random",
                                              "seed": 0})
    picks = set()
    for i in range(12):
        picks.add(request_json(router.address + "/generate",
                               {"prompt": [5] * 40 + [i]},
                               idempotency_key=f"t-rand-{i}")["replica"])
    assert len(picks) == 2       # same head, both replicas hit


def test_router_rejects_bad_prompt(fleet):
    router, _replicas = fleet(n=1)
    with pytest.raises(urllib.error.HTTPError) as ei:
        request_json(router.address + "/generate", {"prompt": []},
                     retry=NO_RETRY)
    assert ei.value.code == 400


def test_router_no_replicas_is_503():
    router = RouterServer()
    router.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            request_json(router.address + "/generate", {"prompt": [1]},
                         retry=NO_RETRY)
        assert ei.value.code == 503
    finally:
        router.shutdown()


# -- SLO-class admission -----------------------------------------------------

# an objective that can never be good: queue depth <= -1 (the gauge
# renders >= 0), so one evaluation makes the fast window burn at 100
_ALWAYS_BURNING = [Objective(
    "always_bad", metric="kubetpu_serving_queue_depth",
    threshold=-1.0, op="<=", reduce="max")]


def test_burning_sheds_batch_and_routes_interactive(fleet):
    router, _replicas = fleet(
        n=1, router_kw={"slos": _ALWAYS_BURNING, "slo_interval_s": 0.0,
                        "queue_timeout_s": 0.15})
    router.evaluate_slos(0.0)
    assert router._burning()
    with pytest.raises(urllib.error.HTTPError) as ei:
        request_json(router.address + "/generate",
                     {"prompt": [1, 2], "slo_class": "batch"},
                     retry=NO_RETRY)
    assert ei.value.code == 503
    body = request_json(router.address + "/generate",
                        {"prompt": [1, 2], "slo_class": "interactive"},
                        idempotency_key="t-slo-int")
    assert body["replica"] == "rep0"
    assert router._c_shed.value == 1
    counts = router.events.counts()
    assert counts.get("shed") == 1 and counts.get("route") == 1


def test_burning_queues_standard_until_timeout(fleet):
    router, _replicas = fleet(
        n=1, router_kw={"slos": _ALWAYS_BURNING, "slo_interval_s": 0.0,
                        "queue_timeout_s": 0.15})
    router.evaluate_slos(0.0)
    t0 = time.perf_counter()
    with pytest.raises(urllib.error.HTTPError) as ei:
        request_json(router.address + "/generate",
                     {"prompt": [1], "slo_class": "standard"},
                     retry=NO_RETRY, timeout=10.0)
    assert ei.value.code == 503
    assert time.perf_counter() - t0 >= 0.15   # actually parked
    assert router._c_queued.value == 1
    assert router._c_qtimeout.value == 1


# -- breaker health ----------------------------------------------------------


def test_pool_breaker_suspect_then_dead(fleet):
    router, replicas = fleet(n=2)
    rep, _fake = replicas[0]
    name = rep.name
    rep.shutdown(graceful=False)              # abrupt death
    for _ in range(2):
        router.pool.refresh(0.0)
    assert name not in router.pool.routable()
    kinds = [e["kind"] for e in router.events.events()
             if e.get("replica") == name]
    assert "replica_suspect" in kinds
    for _ in range(3):
        router.pool.refresh(0.0)
    assert "replica_dead" in [
        e["kind"] for e in router.events.events()
        if e.get("replica") == name]
    # ring membership unchanged (no remap): routing just skips it
    assert name in router.ring.members()
    body = request_json(router.address + "/generate", {"prompt": [2] * 40},
                        idempotency_key="t-bk-1")
    assert body["replica"] != name


def test_pool_breaker_recovers_through_probation(fleet):
    router, replicas = fleet(n=1)
    rep, _fake = replicas[0]
    # whitebox: pause the background signals loop so its concurrent
    # refreshes can't interleave with the hand-driven breaker script
    router._stop.set()
    time.sleep(0.3)
    # cordon via misses against a paused scrape: simulate by recording
    # misses directly (the wire path is covered by the dead test above)
    router.pool._record_miss(rep.name)
    router.pool._record_miss(rep.name)
    assert router.pool.routable() == []
    router.pool.refresh(0.0)                  # success -> probation
    assert rep.name in router.pool.routable()
    router.pool.refresh(0.0)                  # second pass -> healthy
    assert "replica_recovered" in [
        e["kind"] for e in router.events.events()]


# -- autoscaler --------------------------------------------------------------


def test_autoscaler_event_sequence_up_drain_down(fleet):
    """The acceptance pin: a sustained hot signal scales UP; a
    sustained cold fleet drains the victim and only a COMPLETED drain
    emits scale_down — scale_up -> ... -> drain -> scale_down in the
    event log, in seq order."""
    router, replicas = fleet(n=2)
    fakes = [f for _r, f in replicas]
    extra = []

    def launcher():
        fake = FakeSlotServer()
        rep = ReplicaServer(fake, f"scaled{len(extra)}", idle_wait=0.002)
        rep.start()
        extra.append((rep, fake))
        return rep.address

    stopped = []
    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=3, up_after=2,
                           down_after=2, cooldown_s=0.0),
        terminator=lambda name, url: stopped.append(name))
    # sustained pressure: both replicas report queue-wait way past the
    # policy ceiling
    for f in fakes:
        f.load_override = {"queue_wait_p99_ms": 9999.0}
    assert scaler.poll_once()["action"] is None        # hysteresis holds
    action = scaler.poll_once()["action"]
    assert action and action.startswith("scale_up:")
    assert len(router.pool.names()) == 3
    # pressure clears entirely -> cold passes -> drain, then completion
    for f in fakes:
        f.load_override = {}
    assert scaler.poll_once()["action"] is None
    action = scaler.poll_once()["action"]
    assert action and action.startswith("drain:")
    victim = action.split(":", 1)[1]
    # the victim is idle, so the NEXT pass observes it drained
    action = scaler.poll_once()["action"]
    assert action == f"scale_down:{victim}"
    assert stopped == [victim]
    assert len(router.pool.names()) == 2
    seqs = {}
    for e in router.events.events():
        if e["kind"] in ("scale_up", "drain", "scale_down"):
            seqs.setdefault(e["kind"], e["seq"])
    assert seqs["scale_up"] < seqs["drain"] < seqs["scale_down"]
    for rep, _f in extra:
        rep.shutdown(graceful=False)


def test_autoscaler_scale_down_is_migrate_then_drain(fleet):
    """Round-16: scale-down names a survivor target and emits
    ``scale_down_migrate -> drain -> scale_down`` in seq order — the
    migrate-then-remove contract the ISSUE pins."""
    router, replicas = fleet(n=2)
    scaler = ReplicaAutoscaler(
        router, lambda: (_ for _ in ()).throw(RuntimeError("no launch")),
        policy=ScalePolicy(min_replicas=1, max_replicas=3, up_after=99,
                           down_after=1, cooldown_s=0.0))
    action = scaler.poll_once()["action"]
    assert action and action.startswith("drain:")
    victim = action.split(":", 1)[1]
    action = scaler.poll_once()["action"]
    assert action == f"scale_down:{victim}"
    seqs = {}
    targets = {}
    for e in router.events.events():
        if e["kind"] in ("scale_down_migrate", "drain", "scale_down"):
            seqs.setdefault(e["kind"], e["seq"])
            targets[e["kind"]] = e
    assert (seqs["scale_down_migrate"] < seqs["drain"]
            < seqs["scale_down"])
    # the handoff target is the surviving replica, never the victim
    assert targets["scale_down_migrate"]["target"] != victim
    assert targets["scale_down_migrate"]["replica"] == victim


def test_suspect_triggers_migrate_away_once(fleet):
    """Round-16 breaker policy: a replica newly SUSPECT gets ONE
    migrate-away sweep toward a routable survivor ('migrate away'
    instead of 'pray'); repeated ticks don't re-spam it, and recovery
    to healthy re-arms the trigger."""
    router, replicas = fleet(n=2)
    pool = router.pool
    victim = pool.names()[0]
    for _ in range(pool.suspect_after):
        pool._record_miss(victim)
    assert pool.state(victim) == "suspect"
    router._check_suspects()
    router._check_suspects()          # second tick: no duplicate sweep
    aways = [e for e in router.events.events()
             if e["kind"] == "migrate_away"]
    assert len(aways) == 1
    assert aways[0]["replica"] == victim
    assert aways[0]["target"] != victim
    assert int(router._c_migrate_away.value) == 1
    # recovery through probation -> healthy re-arms the trigger
    pool._record_ok(victim, {"draining": False})
    for _ in range(pool.probation_passes):
        pool._record_ok(victim, {"draining": False})
    assert pool.state(victim) == "healthy"
    router._check_suspects()
    for _ in range(pool.suspect_after):
        pool._record_miss(victim)
    router._check_suspects()
    aways = [e for e in router.events.events()
             if e["kind"] == "migrate_away"]
    assert len(aways) == 2


def test_autoscaler_respects_min_and_drain_gate(fleet):
    """Scale-down never drops below min_replicas, and a victim with
    in-flight work is NOT removed until its drain completes."""
    router, replicas = fleet(n=2, fake_kw={"step_sleep": 0.03,
                                           "max_new": 6})
    scaler = ReplicaAutoscaler(
        router, lambda: (_ for _ in ()).throw(RuntimeError("no launch")),
        policy=ScalePolicy(min_replicas=1, max_replicas=3, up_after=99,
                           down_after=1, cooldown_s=0.0))
    # keep one replica busy, then go cold enough to pick a victim: the
    # idle one drains first (least loaded)
    busy_rep, _busy_fake = replicas[0]
    out = {}

    def go():
        out["body"] = request_json(busy_rep.address + "/generate",
                                   {"prompt": [1, 2, 3]},
                                   idempotency_key="t-gate", timeout=30.0)

    t = threading.Thread(target=go)
    t.start()
    time.sleep(0.04)
    res = scaler.poll_once()
    # with one replica mid-request the fleet may read hot-ish via queue
    # depth 0 + active < 0.25? active_frac = 1/4 -> not cold... force:
    while res["action"] is None:
        res = scaler.poll_once()
        if res["action"] is not None or not t.is_alive():
            break
        time.sleep(0.02)
    t.join(timeout=10.0)
    assert len(out["body"]["emitted"]) == 6
    # drive to completion: drain finishes, never below min
    for _ in range(10):
        scaler.poll_once()
        if len(router.pool.names()) == 1:
            break
        time.sleep(0.02)
    assert len(router.pool.names()) == 1


# -- Round-17: disaggregated roles -------------------------------------------


@pytest.fixture()
def role_fleet(request):
    """(router, {name: (replica_server, fake)}) with one replica per
    requested role; everything shut down at teardown."""
    made = []

    def build(roles, router_kw=None, fake_kw=None):
        router = RouterServer(load_refresh_s=0.0, **(router_kw or {}))
        router.start()
        replicas = {}
        for i, role in enumerate(roles):
            fake = FakeSlotServer(**(fake_kw or {}))
            rep = ReplicaServer(fake, f"{role}{i}", role=role,
                                idle_wait=0.002)
            rep.start()
            router.register_replica(rep.address)
            replicas[rep.name] = (rep, fake)
        made.append((router, replicas))
        return router, replicas

    yield build
    for router, replicas in made:
        router.shutdown()
        for rep, _fake in replicas.values():
            rep.shutdown(graceful=False)


def test_decode_role_gets_no_ring_arcs_or_fresh_prompts(role_fleet):
    """A decode-only replica receives streams over the handoff wire,
    never fresh prompts: no ring arcs at registration, and the prompt
    path routes to the prefill-capable replica."""
    router, replicas = role_fleet(["prefill", "decode"])
    assert router.ring.members() == ["prefill0"]
    assert router.pool.role("prefill0") == "prefill"
    assert router.pool.role("decode1") == "decode"
    for i in range(4):
        body = request_json(router.address + "/generate",
                            {"prompt": [i + 1] * 40},
                            idempotency_key=f"t-role-{i}")
        # the FakeSlotServer has no page machinery, so the handoff
        # degrades to local completion — the routing decision is what
        # this test pins
        assert body["replica"] == "prefill0"


def test_migrate_away_respects_role(role_fleet):
    """The Round-17 satellite pin: a suspect PREFILL replica's
    in-flight streams hand off to another prefill replica or a "both"
    node — never to a decode-only target (its pool is sized and
    SLO-judged for pure decode traffic)."""
    router, replicas = role_fleet(["prefill", "decode", "both"])
    pool = router.pool
    for _ in range(pool.suspect_after):
        pool._record_miss("prefill0")
    assert pool.state("prefill0") == "suspect"
    router._check_suspects()
    aways = [e for e in router.events.events()
             if e["kind"] == "migrate_away"]
    assert len(aways) == 1
    assert aways[0]["replica"] == "prefill0"
    assert aways[0]["target"] == "both2"       # never decode1


def test_migrate_away_skips_when_only_decode_survives(role_fleet):
    """With no role-compatible survivor the sweep is SKIPPED — honest
    residue beats shipping prefill streams into the decode pool."""
    router, replicas = role_fleet(["prefill", "decode", "decode"])
    pool = router.pool
    for _ in range(pool.suspect_after):
        pool._record_miss("prefill0")
    router._check_suspects()
    kinds = [e["kind"] for e in router.events.events()]
    assert "migrate_away_skip" in kinds
    assert "migrate_away" not in kinds


def test_autoscaler_scales_pools_independently(role_fleet):
    """Round-17: the prefill pool scales on queue-wait/TTFT pressure,
    the decode pool on ITL p99 — each with its own hysteresis, and a
    signal from the wrong pool never buys the other pool hardware."""
    router, replicas = role_fleet(["prefill", "decode"])
    launched = []

    def launcher(role):
        fake = FakeSlotServer()
        rep = ReplicaServer(fake, f"auto-{role}-{len(launched)}",
                            role=role, idle_wait=0.002)
        rep.start()
        launched.append((role, rep))
        return rep.address

    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=2, up_after=1,
                           cooldown_s=0.0))
    pre_fake = replicas["prefill0"][1]
    dec_fake = replicas["decode1"][1]
    # decode-pool signals (queue wait) on the DECODE replica must not
    # scale the decode pool — its criteria are ITL + free pages
    dec_fake.load_override = {"queue_wait_p99_ms": 9999.0}
    res = scaler.poll_once()
    assert res["actions"] == []
    dec_fake.load_override = {}
    # prefill pressure scales the PREFILL pool only
    pre_fake.load_override = {"queue_wait_p99_ms": 9999.0}
    res = scaler.poll_once()
    assert [r for r, _ in launched] == ["prefill"]
    assert any(a.startswith("scale_up:") for a in res["actions"])
    pre_fake.load_override = {}
    # decode ITL pressure scales the DECODE pool only
    dec_fake.load_override = {"itl_p99_ms": 9999.0}
    res = scaler.poll_once()
    assert [r for r, _ in launched] == ["prefill", "decode"]
    ups = [e for e in router.events.events() if e["kind"] == "scale_up"]
    assert [e.get("role") for e in ups] == ["prefill", "decode"]
    for _role, rep in launched:
        rep.shutdown(graceful=False)


def test_autoscaler_heals_a_fully_dead_role_pool(role_fleet):
    """A dedicated pool whose LAST replica died and was reaped must
    keep reconciling: the decode pool's min_replicas floor-heal fires
    even though no alive replica carries the role anymore — otherwise
    a disagg fleet that lost its whole decode pool would silently
    degrade to colocated forever."""
    router, replicas = role_fleet(["prefill", "decode"])
    launched = []

    def launcher(role):
        fake = FakeSlotServer()
        rep = ReplicaServer(fake, f"heal-{role}-{len(launched)}",
                            role=role, idle_wait=0.002)
        rep.start()
        launched.append((role, rep))
        return rep.address

    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=2, up_after=99,
                           cooldown_s=0.0))
    scaler.poll_once()                 # observe both pools alive
    dead_rep, _fake = replicas["decode1"]
    dead_rep.shutdown(graceful=False)
    for _ in range(5):
        router.pool.refresh(0.0)
    assert router.pool.state("decode1") == "dead"
    res = scaler.poll_once()           # reap + floor-heal the pool
    assert [r for r, _ in launched] == ["decode"]
    assert any(a.startswith("scale_up:") for a in res["actions"])
    for _role, rep in launched:
        rep.shutdown(graceful=False)


def test_dedicated_pool_never_floor_heals_with_roleless_launcher(
        role_fleet):
    """A zero-arg launcher cannot boot a dedicated-role replica: the
    floor-heal must FAIL LOUDLY (scale_error, no launch) instead of
    booting a "both" node that leaves the pool empty and buying
    hardware every pass forever."""
    router, replicas = role_fleet(["prefill", "decode"])
    launched = []

    def launcher():                     # roleless: colocated-era shape
        launched.append(1)
        return "http://127.0.0.1:1"

    scaler = ReplicaAutoscaler(
        router, launcher,
        policy=ScalePolicy(min_replicas=1, max_replicas=2, up_after=99,
                           cooldown_s=0.0))
    scaler.poll_once()
    dead_rep, _fake = replicas["decode1"]
    dead_rep.shutdown(graceful=False)
    for _ in range(5):
        router.pool.refresh(0.0)
    res = scaler.poll_once()            # reap + attempt to heal decode
    assert launched == []               # never launched the wrong kind
    assert res["actions"] == []
    errs = [e for e in router.events.events()
            if e["kind"] == "scale_error"]
    assert any("takes no role" in str(e.get("error")) for e in errs)


def test_router_metrics_and_slo_and_trace_surfaces(fleet):
    router, _replicas = fleet(
        n=2, router_kw={"slos": _ALWAYS_BURNING, "slo_interval_s": 0.0})
    request_json(router.address + "/generate",
                 {"prompt": [8] * 40},
                 idempotency_key="t-surf")
    # evaluation rides the background signals loop; force one so the
    # scrape below deterministically carries the kubetpu_slo_* gauges
    router.evaluate_slos(0.0)
    text = request_text(router.address + "/metrics")
    assert validate_prometheus_text(text) == []
    assert 'kubetpu_router_requests_total{outcome="routed"} 1' in text
    assert 'replica="rep0"' in text and 'replica="rep1"' in text
    assert 'kubetpu_slo_burn_rate{slo="always_bad",window="fast"}' in text
    slo = request_json(router.address + "/slo")
    assert slo["burning"] is True
    listing = request_json(router.address + "/replicas")
    assert {r["name"] for r in listing["replicas"]} == {"rep0", "rep1"}
    events = request_text(router.address + "/events")
    assert '"kind": "route"' in events


def test_cli_summary_router_section_and_trace_hop(fleet):
    """``kubetpu.cli.obs`` grows the router section (routed/shed
    counts, replica breaker states, per-replica load) and ``--trace``
    renders the router hop above the replica leg."""
    from kubetpu.cli.obs import render_summary, render_trace
    from kubetpu.obs import span

    router, _replicas = fleet(n=2)
    with span("cli-router-test") as root:
        request_json(router.address + "/generate", {"prompt": [4] * 40},
                     idempotency_key="t-cli-1")
        tid = root.trace_id
    text = request_text(router.address + "/metrics")
    out = render_summary(text, "router")
    assert "router    routed=1" in out
    assert "replicas healthy=2" in out
    assert "replica   rep0:" in out and "replica   rep1:" in out
    rendered = render_trace(router.trace(tid))
    assert "[router]" in rendered
    assert "[replica:rep0]" in rendered or "[replica:rep1]" in rendered
    # the router span indents ABOVE its replica leg
    lines = rendered.splitlines()
    r_i = next(i for i, ln in enumerate(lines) if "[router]" in ln)
    rep_i = next(i for i, ln in enumerate(lines) if "[replica:" in ln)
    assert r_i < rep_i
