"""Draft distillation (kubetpu/jobs/distill.py): a TRAINED draft pair
must make speculation actually win — mean tokens/round >= 2 (VERDICT r4:
the random-draft measurement records speculation losing at 1.0)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_state, make_mesh, make_train_step
from kubetpu.jobs.data import SyntheticCorpus
from kubetpu.jobs.distill import (
    agreement_rate,
    init_draft_state,
    make_distill_step,
    truncated_draft,
)
from kubetpu.jobs.speculative import make_speculative_generate

TCFG = ModelConfig(vocab=64, d_model=64, n_layers=2, n_heads=4, d_ff=128,
                   max_seq=128)
DCFG = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=4, d_ff=64,
                   max_seq=128)


@pytest.fixture(scope="module")
def trained_pair():
    """Target trained on the skewed synthetic corpus (a learnable argmax,
    like natural text); draft distilled against it. Module-scoped: the
    tests share the (CPU-cheap) pair."""
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1})
    corpus = SyntheticCorpus(TCFG.vocab, seed=3,
                             skew=[0.85, 0.05, 0.05, 0.05])
    batches = corpus.batches(8, 32, seed=5)

    state, opt = init_state(jax.random.PRNGKey(0), TCFG, mesh)
    step = make_train_step(TCFG, mesh, optimizer=opt, use_ring=False)
    data = [next(batches) for _ in range(8)]
    for i in range(250):
        tokens, targets = data[i % len(data)]
        state, t_loss = step(state, tokens, targets)
    t_params = state.params

    dstep, dopt = make_distill_step(TCFG, DCFG, temperature=2.0)
    dstate = init_draft_state(jax.random.PRNGKey(1), DCFG, dopt)
    for i in range(300):
        tokens, targets = data[i % len(data)]
        dstate, d_loss = dstep(dstate, t_params, tokens, targets)
    return t_params, dstate.params, data, float(t_loss), float(d_loss)


def test_distilled_draft_agrees(trained_pair):
    t_params, d_params, data, t_loss, d_loss = trained_pair
    assert np.isfinite(t_loss) and np.isfinite(d_loss)
    tokens, _ = data[0]
    a = agreement_rate(TCFG, DCFG, t_params, d_params, tokens)
    assert a >= 0.7, f"agreement {a} too low for speculation to win"


def test_trained_pair_speculation_wins(trained_pair):
    """The VERDICT r4 bar: mean tokens/round >= 2 with a trained pair —
    and the output is still EXACTLY target-only greedy."""
    from kubetpu.jobs.decode import make_generate

    t_params, d_params, data, _t, _d = trained_pair
    prompt = data[0][0][:4, :8]
    gen = make_speculative_generate(TCFG, DCFG, gamma=4)
    spec_tokens, tokens_per_round = gen(t_params, d_params, prompt, 24)
    plain = make_generate(TCFG)(t_params, prompt, jax.random.PRNGKey(0), 24)
    np.testing.assert_array_equal(np.asarray(spec_tokens), np.asarray(plain))
    assert float(tokens_per_round) >= 2.0, (
        f"trained pair yields only {float(tokens_per_round)} tokens/round"
    )


def test_truncated_self_draft(trained_pair):
    """The zero-training draft: first-layer slice of the trained target
    shares its arrays, forwards at the right shapes, and beats a random
    draft's agreement."""
    t_params, _d, data, _t, _dl = trained_pair
    dcfg, dparams = truncated_draft(TCFG, t_params, 1)
    assert dcfg.n_layers == 1
    assert dparams["blocks"]["wq"].shape[0] == 1
    assert dparams["embed"] is t_params["embed"]  # shared, not copied
    tokens, _ = data[0]
    a_trunc = agreement_rate(TCFG, dcfg, t_params, dparams, tokens)
    from kubetpu.jobs.model import init_params

    rand = init_params(jax.random.PRNGKey(9), DCFG)
    a_rand = agreement_rate(TCFG, DCFG, t_params, rand, tokens)
    assert a_trunc > a_rand
    with pytest.raises(ValueError):
        truncated_draft(TCFG, t_params, 3)


def test_distill_refuses_vocab_mismatch():
    bad = dataclasses.replace(DCFG, vocab=32)
    with pytest.raises(ValueError):
        make_distill_step(TCFG, bad)
