"""Round-22 adapter control plane over the wire: registry push/evict
round-trips against a REAL packed replica, idempotency-window replay of
a hot-load, the router's tenant-affine routing + per-tenant SLO
classes, and the non-LoRA-replica refusals.

The fault-injected contract (parity under drop/503/partial on the
hot-load leg) runs in ``make lora-check``."""

import urllib.error

import pytest

jax = pytest.importorskip("jax")

from kubetpu.jobs import ModelConfig, init_params  # noqa: E402
from kubetpu.jobs.lora import (  # noqa: E402
    LoraConfig, init_lora_params, merge_lora)
from kubetpu.jobs.multi_lora import (  # noqa: E402
    PagedMultiLoraDecodeServer, adapter_fingerprint)
from kubetpu.jobs.paged import PagedDecodeServer  # noqa: E402
from kubetpu.router import ReplicaServer, RouterServer  # noqa: E402
from kubetpu.router.adapters import (  # noqa: E402
    AdapterRegistry, decode_adapter, encode_adapter)
from kubetpu.wire.httpcommon import request_json  # noqa: E402

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
LCFG = LoraConfig(rank=4, alpha=8.0)
PS = 8
MAX_NEW = 4


def _adapter(seed):
    a = init_lora_params(jax.random.PRNGKey(seed), CFG, LCFG)
    keys = jax.random.split(jax.random.PRNGKey(seed + 100), len(a["blocks"]))
    for i, (k, v) in enumerate(sorted(a["blocks"].items())):
        if k.endswith("_b"):
            a["blocks"][k] = jax.random.normal(keys[i], v.shape, v.dtype) * 0.05
    return a


def test_adapter_codec_round_trip():
    a = _adapter(5)
    back = decode_adapter(encode_adapter(a))
    assert adapter_fingerprint(back) == adapter_fingerprint(a)
    with pytest.raises(ValueError):
        decode_adapter({"blocks": {}})
    wire = encode_adapter(a)
    wire["blocks"]["wq_a"] = {"dtype": "float32", "shape": [3], "data": "!!"}
    with pytest.raises(ValueError):
        decode_adapter(wire)


def test_registry_content_identity():
    reg = AdapterRegistry()
    a, b = _adapter(1), _adapter(2)
    n = reg.register(a)
    assert n == adapter_fingerprint(a)
    assert reg.register(a) == n                  # same bytes: no-op
    reg.register(b, name="tenant-b")
    with pytest.raises(ValueError):
        reg.register(a, name="tenant-b")         # alias never retargets
    assert reg.names() == sorted([n, "tenant-b"])
    assert reg.encoded("tenant-b") is reg.encoded("tenant-b")  # cached


@pytest.fixture(scope="module")
def fleet():
    """Router (registry attached, per-tenant SLO classes) + one packed
    multi-LoRA replica + one plain paged replica."""
    base = init_params(jax.random.PRNGKey(0), CFG)
    adapters = [_adapter(1), _adapter(2)]
    packed = PagedMultiLoraDecodeServer(
        CFG, base, LCFG, adapters, max_adapters=3, n_slots=2, max_seq=64,
        max_new_tokens=MAX_NEW, page_size=PS, prefill_budget=PS,
        prefix_cache_pages=16)
    plain = PagedDecodeServer(
        CFG, base, n_slots=2, max_seq=64, max_new_tokens=MAX_NEW,
        page_size=PS, prefill_budget=PS)
    reps = [ReplicaServer(packed, "packed0", idle_wait=0.002),
            ReplicaServer(plain, "plain0", idle_wait=0.002)]
    for rep in reps:
        rep.start()
    registry = AdapterRegistry()
    names = [registry.register(a) for a in adapters]
    extra = _adapter(3)
    registry.register(extra, name="tenant-extra")
    router = RouterServer(
        load_refresh_s=0.05, adapters=registry,
        tenant_slo_classes={"tenant-extra": "standard"})
    router.start()
    for rep in reps:
        router.register_replica(rep.address)
    yield {"router": router, "reps": reps, "registry": registry,
           "base": base, "adapters": adapters, "extra": extra,
           "names": names}
    router.shutdown()
    for rep in reps:
        rep.shutdown(graceful=False)


def test_wire_hot_load_replay_and_routed_parity(fleet):
    """POST /adapters load round-trip; a replay under the SAME
    idempotency key returns the committed answer without re-executing;
    a routed generate naming the tenant is token-exact vs merged."""
    router, (packed_rep, _), reg = (fleet["router"], fleet["reps"],
                                    fleet["registry"])
    srv = packed_rep.server
    loads0 = int(srv.obs.counter("kubetpu_adapter_loads_total").value)
    payload = {"action": "load", "name": "tenant-extra",
               "adapter": reg.encoded("tenant-extra")}
    out1 = request_json(packed_rep.address + "/adapters", payload,
                        idempotency_key="wire-load-1", timeout=30.0)
    out2 = request_json(packed_rep.address + "/adapters", payload,
                        idempotency_key="wire-load-1", timeout=30.0)
    assert out1 == out2                       # the replay window answered
    assert "tenant-extra" in out1["resident"]
    assert int(srv.obs.counter(
        "kubetpu_adapter_loads_total").value) == loads0 + 1
    # ...and a FRESH key re-executes but is content/name-idempotent
    out3 = request_json(packed_rep.address + "/adapters", payload,
                        idempotency_key="wire-load-2", timeout=30.0)
    assert "tenant-extra" in out3["resident"]
    assert int(srv.obs.counter(
        "kubetpu_adapter_loads_total").value) == loads0 + 1
    srv.check_invariants()

    import time
    time.sleep(0.15)  # the router's /load poll picks up residency
    body = request_json(router.address + "/generate",
                        {"prompt": [5, 6, 7], "adapter": "tenant-extra",
                         "timeout": 30.0},
                        idempotency_key="wire-gen-1", timeout=30.0)
    assert body["replica"] == "packed0"       # tenant-affine routing
    ref = PagedDecodeServer(
        CFG, merge_lora(fleet["base"], fleet["extra"], LCFG), n_slots=1,
        max_seq=64, max_new_tokens=MAX_NEW, page_size=PS,
        prefill_budget=PS)
    rid = ref.enqueue([5, 6, 7])
    ref.drain()
    assert body["tokens"] == ref.pop_result(rid)


def test_wire_evict_and_stale_refusal(fleet):
    """Evict round-trip; an evicted tenant refuses at the replica (400
    through the router, never a stale index)."""
    router, (packed_rep, _), reg = (fleet["router"], fleet["reps"],
                                    fleet["registry"])
    reg.push_adapter(packed_rep.address, "tenant-extra", timeout=30.0)
    out = reg.evict_adapter(packed_rep.address, "tenant-extra",
                            timeout=30.0)
    assert out["evicted"] is True
    assert "tenant-extra" not in packed_rep.server.resident_adapters()
    out2 = reg.evict_adapter(packed_rep.address, "tenant-extra",
                             timeout=30.0)
    assert out2["evicted"] is False           # replayed evict: no-op
    with pytest.raises(urllib.error.HTTPError) as e:
        request_json(packed_rep.address + "/generate",
                     {"prompt": [1, 2], "adapter": "tenant-extra",
                      "timeout": 10.0},
                     idempotency_key="wire-stale-1", timeout=10.0)
    assert e.value.code == 400
    packed_rep.server.check_invariants()


def test_non_lora_replica_refuses_adapter_legs(fleet):
    """A plain paged replica 404s the hot-load leg and 400s a generate
    that names an adapter — the router's distribute skips it."""
    router, (_, plain_rep), reg = (fleet["router"], fleet["reps"],
                                   fleet["registry"])
    with pytest.raises(urllib.error.HTTPError) as e:
        reg.push_adapter(plain_rep.address, "tenant-extra", timeout=10.0)
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        request_json(plain_rep.address + "/generate",
                     {"prompt": [1, 2], "adapter": 0, "timeout": 10.0},
                     idempotency_key="wire-plain-1", timeout=10.0)
    assert e.value.code == 400


def test_router_distribute_and_summary(fleet):
    """POST /adapters on the ROUTER fans the registered adapter out to
    every capable replica (the plain one is skipped, not failed) and
    the summary reflects registry + residency."""
    router, reps, _ = fleet["router"], fleet["reps"], fleet["registry"]
    out = request_json(router.address + "/adapters",
                       {"action": "load", "name": "tenant-extra"},
                       idempotency_key="wire-dist-1", timeout=30.0)
    assert out["results"]["packed0"]["ok"] is True
    assert "packed0" in out["results"]
    assert "tenant-extra" in reps[0].server.resident_adapters()
    summ = request_json(router.address + "/adapters", None, timeout=10.0)
    assert "tenant-extra" in summ["registered"]
    with pytest.raises(urllib.error.HTTPError) as e:
        request_json(router.address + "/adapters",
                     {"action": "load", "name": "no-such"},
                     idempotency_key="wire-dist-2", timeout=10.0)
    assert e.value.code == 404
