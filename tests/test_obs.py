"""The Round-8 observability spine (`kubetpu.obs`).

Four layers under test:

- instruments: typed Counter/Gauge/bounded-reservoir Histogram in a
  thread-safe Registry, Prometheus text exposition + parse/validate;
- the LatencyRecorder facade: bounded memory, registry binding;
- tracing: span nesting, context propagation over the REAL wire
  (controller -> agent), retries visible as child spans under injected
  faults with counter deltas matching the fault policy's script
  (ISSUE 3 satellite);
- fleet federation: controller GET /metrics merges its registry, Cluster
  gauges, and scraped agent registries into ONE valid exposition; GET
  /trace/<id> returns the stitched trace (ISSUE 3 acceptance).
"""

import json
import urllib.request

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core.metrics import LatencyRecorder
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.obs import registry as obs_registry
from kubetpu.obs import trace as obs_trace
from kubetpu.obs.registry import (
    Histogram,
    Registry,
    federate,
    parse_prometheus_text,
    validate_prometheus_text,
)
from kubetpu.plugintypes import ResourceTPU
from kubetpu.wire import (
    ControllerServer,
    FaultInjector,
    NodeAgentServer,
    RoutePolicy,
)
from kubetpu.wire.controller import pod_to_json
from kubetpu.wire.httpcommon import request_json


def tpu_pod(name, chips):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})},
    )


# -- instruments + exposition ------------------------------------------------


def test_registry_render_counters_gauges():
    reg = Registry()
    reg.counter("kubetpu_x_total").inc()
    reg.counter("kubetpu_x_total").inc(2)
    reg.gauge("kubetpu_g", resource="kubedevice/tpu", node="n0").set(8)
    reg.gauge_fn("kubetpu_dyn", lambda: 3.5)
    text = reg.render()
    # integers render bare; label ORDER is preserved (not sorted)
    assert "kubetpu_x_total 3" in text
    assert 'kubetpu_g{resource="kubedevice/tpu",node="n0"} 8' in text
    assert "kubetpu_dyn 3.5" in text
    assert "# TYPE kubetpu_x_total counter" in text
    assert validate_prometheus_text(text) == []


def test_registry_type_conflict_raises():
    reg = Registry()
    reg.counter("kubetpu_thing")
    with pytest.raises(ValueError):
        reg.gauge("kubetpu_thing")


def test_histogram_exact_below_cap_bounded_above():
    h = Histogram(cap=100)
    for i in range(100):
        h.observe(float(i))
    # exact while the reservoir holds everything
    assert h.percentile(50) == pytest.approx(50.0, abs=1)
    assert h.percentile(99) == pytest.approx(99.0, abs=1)
    # 100x the cap: memory stays bounded, count/sum exact, quantile sane
    for i in range(10_000):
        h.observe(1000.0)
    assert len(h._buf) == 100
    assert h.count == 10_100
    assert h.sum == pytest.approx(100 * 99 / 2 + 10_000 * 1000.0)
    # the reservoir is now dominated by the late mass
    assert h.percentile(50) == 1000.0


def test_histogram_renders_as_summary():
    reg = Registry()
    hist = reg.histogram("kubetpu_lat_seconds", op="x")
    for v in (0.1, 0.2, 0.3):
        hist.observe(v)
    text = reg.render()
    assert "# TYPE kubetpu_lat_seconds summary" in text
    assert 'kubetpu_lat_seconds{op="x",quantile="0.5"} 0.2' in text
    assert 'kubetpu_lat_seconds_count{op="x"} 3' in text
    assert validate_prometheus_text(text) == []


def test_parse_round_trip_and_validate_rejects_garbage():
    reg = Registry()
    reg.counter("kubetpu_a_total", node="n0").inc(4)
    reg.gauge("kubetpu_b").set(1.5)
    samples = parse_prometheus_text(reg.render())
    assert ("kubetpu_a_total", {"node": "n0"}, 4.0) in samples
    assert ("kubetpu_b", {}, 1.5) in samples
    assert validate_prometheus_text("not a metric line!!!")
    assert validate_prometheus_text("kubetpu_x not_a_number")
    # duplicate series are flagged
    assert validate_prometheus_text("kubetpu_x 1\nkubetpu_x 2")


def test_federate_relabels_and_dedups_types():
    own = Registry()
    own.gauge("kubetpu_pending_pods").set(2)
    a0, a1 = Registry(), Registry()
    a0.counter("kubetpu_agent_errors_total").inc()
    a1.counter("kubetpu_agent_errors_total").inc(3)
    text = federate(own.render(), {"h0": a0.render(), "h1": a1.render()})
    assert 'kubetpu_agent_errors_total{node="h0"} 1' in text
    assert 'kubetpu_agent_errors_total{node="h1"} 3' in text
    assert text.count("# TYPE kubetpu_agent_errors_total counter") == 1
    assert validate_prometheus_text(text) == []
    # an unparseable peer is skipped wholesale, not fatal
    text2 = federate(own.render(), {"bad": "}{ garbage", "h0": a0.render()})
    assert 'kubetpu_agent_errors_total{node="h0"} 1' in text2


# -- Round-11 exposition round-trip edge cases (ISSUE 6 satellite) -----------


def test_label_value_escaping_round_trip():
    """Backslashes, newlines and quotes in label values must survive
    render -> validate -> parse byte-exactly — adjacent escapes are the
    trap (``\\\\"`` must decode to ``\\"``, not ``"``), and an unescaped
    newline would split the series line and corrupt the exposition."""
    nasty = [
        'C:\\tmp\\x',            # backslashes
        'line1\nline2',          # raw newline
        'say "hi"',              # quotes
        'mix\\"q\\\\n',          # adjacent escape soup
        'trail\\',               # trailing backslash
    ]
    reg = Registry()
    for i, v in enumerate(nasty):
        reg.counter("kubetpu_esc_total", path=v, i=str(i)).inc(i + 1)
    text = reg.render()
    assert validate_prometheus_text(text) == []
    got = {labels["path"]: value
           for name, labels, value in parse_prometheus_text(text)}
    assert got == {v: float(i + 1) for i, v in enumerate(nasty)}
    # and through federation (parse -> relabel -> re-render -> re-parse)
    fed = federate("", {"n0": text})
    assert validate_prometheus_text(fed) == []
    got2 = {labels["path"]: labels["node"]
            for _n, labels, _v in parse_prometheus_text(fed)}
    assert set(got2) == set(nasty)
    assert set(got2.values()) == {"n0"}


def test_empty_reservoir_histogram_round_trips_without_nan():
    """A histogram with count == 0 (created, never observed — every
    serving server pre-creates its latency families) must render, parse
    and federate as zeros: a NaN percentile would poison any fleet
    aggregation downstream."""
    reg = Registry()
    reg.histogram("kubetpu_lat_seconds", op="empty")
    text = reg.render()
    assert validate_prometheus_text(text) == []
    assert "nan" not in text.lower()
    samples = parse_prometheus_text(text)
    assert ("kubetpu_lat_seconds_count", {"op": "empty"}, 0.0) in samples
    for _n, labels, value in samples:
        assert value == 0.0
    fed = federate("", {"n0": text})
    assert validate_prometheus_text(fed) == []
    assert "nan" not in fed.lower()


def test_install_process_gauges():
    """The standard identification trio (ISSUE 6 satellite): build info
    with version+component labels, uptime, RSS — idempotent, valid, and
    distinct per component under federation."""
    from kubetpu.obs.registry import install_process_gauges

    reg = Registry()
    install_process_gauges(reg, "controller")
    install_process_gauges(reg, "controller")     # idempotent
    text = reg.render()
    assert validate_prometheus_text(text) == []
    assert 'component="controller"' in text
    assert "kubetpu_build_info{" in text
    samples = {name: value
               for name, _l, value in parse_prometheus_text(text)}
    assert samples["kubetpu_build_info"] == 1.0
    assert samples["kubetpu_process_uptime_seconds"] >= 0.0
    # RSS is best-effort (nan off-unix) but on Linux it is real bytes
    assert samples["kubetpu_process_rss_bytes"] > 1e6
    other = Registry()
    install_process_gauges(other, "agent:h0")
    fed = federate(text, {"h0": other.render()})
    assert validate_prometheus_text(fed) == []
    assert 'component="agent:h0"' in fed


# -- LatencyRecorder over obs histograms -------------------------------------


def test_latency_recorder_bounded_and_bindable():
    rec = LatencyRecorder(cap=64)
    for i in range(1000):
        rec.record("op", i / 1000.0)
    assert rec.count("op") == 1000            # count exact
    assert len(rec._hists["op"]._buf) == 64   # memory bounded at the cap
    summary = rec.summary()["op"]
    assert {"count", "p50_ms", "p90_ms", "p99_ms"} <= set(summary)
    # bind AFTER recording: the existing histogram (samples intact) is
    # attached into the registry and renders with op labels
    reg = Registry()
    rec.bind(reg, "kubetpu_sched_seconds")
    text = reg.render()
    assert 'kubetpu_sched_seconds_count{op="op"} 1000' in text
    rec.record("op2", 0.5)  # future ops land in the registry too
    assert 'op="op2"' in reg.render()


# -- tracing -----------------------------------------------------------------


def test_span_nesting_and_error_status():
    tr = obs_trace.Tracer()
    with obs_trace.span("outer", tracer_=tr) as outer:
        with obs_trace.span("inner", tracer_=tr) as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        with pytest.raises(RuntimeError):
            with obs_trace.span("boom", tracer_=tr):
                raise RuntimeError("kaput")
    spans = {s["op"]: s for s in tr.spans(outer.trace_id)}
    assert set(spans) == {"outer", "inner", "boom"}
    assert spans["inner"]["dur"] >= 0
    assert spans["boom"]["status"] == "error"
    assert "kaput" in spans["boom"]["tags"]["error"]


def test_trace_jsonl_sink(tmp_path):
    tr = obs_trace.Tracer()
    sink = tmp_path / "spans.jsonl"
    tr.set_sink(str(sink))
    with obs_trace.span("sunk", tracer_=tr, tag1="v"):
        pass
    tr.set_sink(None)
    lines = [json.loads(l) for l in sink.read_text().splitlines()]
    assert len(lines) == 1
    assert lines[0]["op"] == "sunk"
    assert lines[0]["tags"] == {"tag1": "v"}


def test_wire_headers_attach_round_trip():
    with obs_trace.span("root") as root:
        headers = obs_trace.wire_headers()
    assert headers[obs_trace.TRACE_HEADER] == root.trace_id
    assert headers[obs_trace.PARENT_HEADER] == root.span_id
    with obs_trace.attach_wire_context(headers):
        with obs_trace.span("child") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
    assert obs_trace.current_trace_id() is None  # context restored


# -- the wire stack: stitched traces, retries under faults, federation -------


@pytest.fixture
def fleet():
    """Controller + 2 fake v5e-64 agents over the real HTTP wire."""
    agents = []
    for h in range(2):
        a = NodeAgentServer(
            new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h)),
            f"obs-h{h}", faults=FaultInjector(seed=h),
        )
        a.start()
        agents.append(a)
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    for a in agents:
        request_json(controller.address + "/nodes", {"url": a.address})
    yield controller, agents
    controller.shutdown()
    for a in agents:
        a.shutdown()


def test_trace_propagation_under_faults(fleet):
    """ISSUE 3 satellite: with the agent injecting 503s on /allocate, the
    retried request keeps ONE trace_id, gains retry child spans, and the
    ``requests_retried_total`` delta matches the fault policy's scripted
    ``times`` count."""
    controller, agents = fleet
    scripted = 2
    for a in agents:
        a.faults.set_route(
            "/allocate", RoutePolicy(error=1.0, error_code=503,
                                     times=scripted))
    retried = obs_registry.default_registry().counter(
        "kubetpu_wire_requests_retried_total")
    before = retried.value
    with obs_trace.span("test.submit") as root:
        out = request_json(
            controller.address + "/pods",
            {"pod": pod_to_json(tpu_pod("traced", 4))},
            idempotency_key="k-traced",
        )
        trace_id = root.trace_id
    assert out["placements"][0]["pod"] == "traced"
    # each scripted 503 consumed exactly one client retry
    assert retried.value - before == scripted
    spans = obs_trace.tracer().spans(trace_id)
    comps = {s.get("component", "") for s in spans}
    assert "controller" in comps
    assert any(c.startswith("agent:") for c in comps)  # stitched
    retry_spans = [s for s in spans if s["op"] == "http.retry"]
    assert len(retry_spans) == scripted
    assert all(s["tags"]["path"] == "/allocate" for s in retry_spans)
    # the injected-fault server spans are visible too
    faulted = [s for s in spans
               if s.get("tags", {}).get("fault") == "injected"]
    assert len(faulted) == scripted
    # a retry span PARENTS the agent server span that answered it: the
    # wire headers are rebuilt per attempt
    retry_ids = {s["span_id"] for s in retry_spans}
    assert any(s.get("parent_id") in retry_ids for s in spans
               if s.get("component", "").startswith("agent:"))


def test_gang_submit_yields_single_stitched_trace(fleet):
    """ISSUE 3 acceptance: one gang submit against a FAULT-INJECTED
    controller + agents produces ONE trace — shared trace_id across
    controller and agent spans, retries visible as child spans —
    retrievable at the controller's GET /trace/<id>."""
    controller, agents = fleet
    for a in agents:
        a.faults.set_route("/allocate", RoutePolicy(
            error=1.0, error_code=503, times=1))
    with obs_trace.span("test.gang") as root:
        out = request_json(
            controller.address + "/pods",
            {"gang": [pod_to_json(tpu_pod(f"g{i}", 8)) for i in range(2)]},
            idempotency_key="k-gang",
        )
        trace_id = root.trace_id
    nodes = {p["node"] for p in out["placements"]}
    assert len(nodes) == 2
    body = request_json(controller.address + f"/trace/{trace_id}")
    assert body["trace"] == trace_id
    spans = body["spans"]
    assert all(s["trace_id"] == trace_id for s in spans)
    comps = {s.get("component", "") for s in spans}
    # spans from the controller AND every placed agent share the trace
    assert "controller" in comps
    assert {f"agent:{n}" for n in nodes} <= comps
    ops = {s["op"] for s in spans}
    assert "controller.submit" in ops
    assert "cluster.schedule_gang" in ops
    assert "POST /allocate" in ops
    # the injected 503 on each agent's allocate leg surfaces as retry
    # child spans INSIDE the same trace (one per scripted fault)
    retries = [s for s in spans if s["op"] == "http.retry"]
    assert len(retries) == len(agents)
    assert all(s["trace_id"] == trace_id for s in retries)


def test_federated_metrics_endpoint(fleet):
    """ISSUE 3 acceptance: controller GET /metrics serves VALID Prometheus
    text federating agent counters (node-relabeled), Cluster gauges, and
    the scheduler latency histograms."""
    controller, agents = fleet
    request_json(controller.address + "/pods",
                 {"pod": pod_to_json(tpu_pod("m0", 4))},
                 idempotency_key="k-m0")
    controller.poll_once()
    req = urllib.request.Request(controller.address + "/metrics")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    assert validate_prometheus_text(text) == []
    # scheduler latency histograms
    assert 'kubetpu_schedule_latency_seconds{op="schedule_pod",quantile="0.5"}' in text
    assert 'kubetpu_schedule_latency_seconds_count{op="schedule_pod"}' in text
    # breaker-state gauge over the fleet
    assert 'kubetpu_nodes{state="healthy"} 2' in text
    assert 'kubetpu_nodes{state="suspect"} 0' in text
    # cluster capacity + queue gauges
    assert 'kubetpu_chips_free{device="kubedevice/tpu"} 12' in text
    assert 'kubetpu_chips_held{device="kubedevice/tpu"} 4' in text
    assert "kubetpu_pending_pods 0" in text
    # federated agent counters, node-relabeled; capacity keeps its own node
    assert 'kubetpu_agent_allocate_requests_total{node="obs-h0"}' in text
    assert 'kubetpu_agent_allocate_requests_total{node="obs-h1"}' in text
    assert 'kubetpu_agent_capacity{resource="kubedevice/tpu",node="obs-h0"} 8' in text
    # controller's own counters
    assert "kubetpu_controller_submits_total 1" in text
    assert "kubetpu_controller_reconcile_passes_total 1" in text


def test_federation_degrades_when_agent_dark(fleet):
    """A dead agent loses its series (and counts a scrape error) — the
    fleet scrape itself keeps answering valid text."""
    controller, agents = fleet
    agents[1].shutdown()
    text = controller._metrics_text()
    assert validate_prometheus_text(text) == []
    assert 'node="obs-h0"' in text
    assert 'kubetpu_agent_nodeinfo_requests_total{node="obs-h1"}' not in text
    assert "kubetpu_controller_federation_scrape_errors_total 1" in text


def test_agent_counters_compat_property(fleet):
    """The old ``agent.counters`` dict surface survives as a registry
    snapshot (the resilience tests read it)."""
    controller, agents = fleet
    c = agents[0].counters
    assert set(c) == {"nodeinfo_requests", "allocate_requests",
                      "allocate_replays", "releases", "errors"}
    assert c["nodeinfo_requests"] >= 1  # the registration probe


def test_metrics_exporter_serves_registries():
    """obs.exporter.MetricsServer: the slot-server wire path — any
    registry set over HTTP, plus /trace/<id> from the process tracer."""
    from kubetpu.obs.exporter import MetricsServer

    reg = Registry()
    reg.histogram("kubetpu_serving_latency_seconds", op="ttft").observe(0.05)
    reg.gauge("kubetpu_serving_active_slots").set(3)
    server = MetricsServer({"replica0": reg})
    server.start()
    try:
        with urllib.request.urlopen(server.address + "/metrics",
                                    timeout=5) as r:
            text = r.read().decode()
        assert validate_prometheus_text(text) == []
        assert 'kubetpu_serving_latency_seconds{op="ttft",quantile="0.5"} 0.05' in text
        assert "kubetpu_serving_active_slots 3" in text
        with obs_trace.span("exported") as sp:
            tid = sp.trace_id
        with urllib.request.urlopen(
                server.address + f"/trace/{tid}", timeout=5) as r:
            body = json.loads(r.read())
        assert [s["op"] for s in body["spans"]] == ["exported"]
    finally:
        server.shutdown()


@pytest.mark.chaos
@pytest.mark.slow
def test_obs_check_script_passes():
    """`make obs-check` (wired into the chaos path, and slow-marked: the
    ISSUE's contract is that tier-1 stays fast — the same assertions
    already run in-process above): the standalone oracle must pass
    against a live controller + 2 agents."""
    import subprocess
    import sys
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "scripts/obs_check.py"],
        cwd=repo, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "obs-check OK" in proc.stdout
