"""Property-based (hypothesis) equivalence test for the Round-21 fit
index: under RANDOMIZED churn the index-pruned schedule path and the
reference full-sweep pick must agree on every placement — same node,
same score — with the books and the index audit staying clean."""

import pytest

# hypothesis is an optional dev dependency: where it isn't installed the
# module must SKIP, not collection-error (tier-1 runs with
# --continue-on-collection-errors, but an error still hides every test
# in this file from the pass/fail accounting)
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st  # noqa: E402

from kubetpu.api.types import ContainerInfo, PodInfo  # noqa: E402
from kubetpu.core import Cluster, SchedulingError  # noqa: E402
from kubetpu.device import (  # noqa: E402
    make_fake_tpus_info,
    new_fake_tpu_dev_manager,
)
from kubetpu.plugintypes import ResourceTPU  # noqa: E402
from kubetpu.scheduler.meshstate import FracKey  # noqa: E402

# one churn op: (release_pick | whole chips | frac milli | cordon_pick)
OP = st.one_of(
    st.tuples(st.just("release"), st.floats(min_value=0.0, max_value=0.999)),
    st.tuples(st.just("whole"), st.sampled_from([1, 2, 4, 8])),
    st.tuples(st.just("frac"), st.sampled_from([125, 250, 333, 500, 750])),
    st.tuples(st.just("cordon"), st.integers(min_value=0, max_value=7)),
)


@settings(max_examples=25, deadline=None)
@given(ops=st.lists(OP, min_size=10, max_size=80))
def test_index_and_sweep_place_identically_under_random_churn(ops):
    """index_cross_check arms the in-band oracle (divergence raises
    RuntimeError inside schedule); a pure-sweep twin cluster replays the
    stream and must match (pod, node) for every op; check_invariants
    audits the index against the books at the end."""
    indexed = Cluster()
    indexed.index_cross_check = True
    plain = Cluster(use_fit_index=False)
    for c in (indexed, plain):
        for i in range(8):
            c.register_node(
                f"n{i:03d}",
                device=new_fake_tpu_dev_manager(
                    make_fake_tpus_info("v5e-8", slice_uid=f"s{i}")))
    logs = {id(indexed): [], id(plain): []}
    for c in (indexed, plain):
        placed = []
        seq = 0
        for kind, arg in ops:
            seq += 1
            if kind == "release":
                if placed:
                    j = int(arg * len(placed))
                    placed[j], placed[-1] = placed[-1], placed[j]
                    c.release(placed.pop())
                continue
            if kind == "cordon":
                name = f"n{arg:03d}"
                if name in c.nodes:
                    c.cordon(name, on=name not in c.cordoned)
                continue
            if kind == "frac":
                pod = PodInfo(
                    name=f"p{seq}", requests={FracKey: arg},
                    running_containers={"main": ContainerInfo()})
            else:
                pod = PodInfo(
                    name=f"p{seq}", requests={},
                    running_containers={
                        "main": ContainerInfo(
                            requests={ResourceTPU: arg})})
            try:
                got = c.schedule(pod)  # oracle raises on divergence
            except SchedulingError:
                logs[id(c)].append((pod.name, None))
                continue
            placed.append(got.name)
            logs[id(c)].append((got.name, got.node_name))
    assert logs[id(indexed)] == logs[id(plain)]
    assert indexed.check_invariants() == []
    assert plain.check_invariants() == []
