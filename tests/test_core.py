"""End-to-end tests of the core harness over the five BASELINE evaluation
configs (BASELINE.md): fake-device managers -> advertisement -> scheduling ->
group-scheduler fill -> accounting -> device allocation."""

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster, SchedulingError
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceGPU, ResourceTPU


def tpu_pod(name, chips, **extra_requests):
    return PodInfo(
        name=name,
        requests=dict(extra_requests),
        running_containers={"main": ContainerInfo(requests={ResourceTPU: chips})},
    )


def v5e8_cluster(num_nodes=1):
    cluster = Cluster()
    for i in range(num_nodes):
        mgr = new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
        cluster.register_node(f"v5e8-n{i}", device=mgr)
    return cluster


# -- config 1: single-pod 1-device request, fake-device mode ----------------


def test_config1_single_device():
    cluster = v5e8_cluster()
    placed = cluster.schedule(tpu_pod("p1", 1))
    assert placed.node_name == "v5e8-n0"
    af = placed.running_containers["main"].allocate_from
    assert len(af) == 1
    results = cluster.allocate("p1")
    mounts, devices, env = results["main"]
    assert len(devices) == 1 and devices[0].startswith("/dev/accel")
    assert env["TPU_VISIBLE_DEVICES"] == devices[0].removeprefix("/dev/accel")


# -- config 2: 4-chip ICI-contiguous placement on one v5e-8 host ------------


def test_config2_contiguous_quad():
    cluster = v5e8_cluster()
    cluster.schedule(tpu_pod("quad", 4))
    _, devices, env = cluster.allocate("quad")["main"]
    assert len(devices) == 4
    # a 2x2 sub-slice, not a 1x4 line: bounding box 2,2,1
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
    node = cluster.nodes["v5e8-n0"].info
    assert node.allocatable[ResourceTPU] == 4  # accounting took 4 chips


def test_config2_flat_topology_knob():
    # tpu/tpu-generate-topology=0 forces the flat (no auto-topology) path
    # (reference knob semantics, gpu_scheduler.go:12-15).
    cluster = v5e8_cluster()
    pod = tpu_pod("flat", 4, **{"tpu/tpu-generate-topology": 0})
    placed = cluster.schedule(pod)
    assert len(placed.running_containers["main"].allocate_from) == 4


def test_invalid_topology_knob_rejected():
    cluster = v5e8_cluster()
    pod = tpu_pod("bad", 2, **{"tpu/tpu-generate-topology": 7})
    with pytest.raises(SchedulingError):
        cluster.schedule(pod)


# -- config 3: multi-pod bin-packing on one v5e-8 host ----------------------


def test_config3_bin_packing():
    cluster = v5e8_cluster()
    for name, chips in [("a", 4), ("b", 2), ("c", 1), ("d", 1)]:
        cluster.schedule(tpu_pod(name, chips))
    node = cluster.nodes["v5e8-n0"].info
    assert node.allocatable[ResourceTPU] == 0
    # distinct chips across pods
    used = set()
    for pod in cluster.nodes["v5e8-n0"].pods.values():
        for cont in pod.running_containers.values():
            for to in cont.allocate_from.values():
                assert to not in used
                used.add(to)
    assert len(used) == 8

    with pytest.raises(SchedulingError):
        cluster.schedule(tpu_pod("overflow", 1))

    cluster.release("b")
    assert cluster.nodes["v5e8-n0"].info.allocatable[ResourceTPU] == 2
    cluster.schedule(tpu_pod("after-release", 2))


def test_config3_two_nodes_prefers_contiguous():
    cluster = v5e8_cluster(num_nodes=2)
    cluster.schedule(tpu_pod("warm", 4))          # fills a 2x2 on n0
    placed = cluster.schedule(tpu_pod("fresh", 8))  # whole host only fits n1
    assert placed.node_name == "v5e8-n1"


# -- config 4: gang-scheduled multi-host job (v5e-64, 8 hosts) --------------


def v5e64_cluster():
    cluster = Cluster()
    for host in range(8):
        mgr = new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=host))
        cluster.register_node(f"v5e64-h{host}", device=mgr)
    return cluster


def test_config4_gang_all_hosts():
    cluster = v5e64_cluster()
    pods = [tpu_pod(f"w{i}", 8) for i in range(8)]
    placed = cluster.schedule_gang(pods)
    assert sorted(p.node_name for p in placed) == sorted(f"v5e64-h{i}" for i in range(8))
    assert cluster.gang_contiguity(placed) == 1.0
    # every worker got its own host's env
    for p in placed:
        _, devices, env = cluster.allocate(p.name)["main"]
        assert len(devices) == 8
        assert env["TPU_WORKER_ID"] == p.node_name.removeprefix("v5e64-h")


def test_config4_two_host_gang_is_square():
    # 2 hosts out of 8: geometric host selection must give a 4x4 square
    # (two vertically-adjacent 2x4 blocks), not a 2x8 strip.
    cluster = v5e64_cluster()
    placed = cluster.schedule_gang([tpu_pod("w0", 8), tpu_pod("w1", 8)])
    assert cluster.gang_contiguity(placed) == 1.0


def test_config4_gang_all_or_nothing():
    cluster = v5e64_cluster()
    pods = [tpu_pod(f"w{i}", 8) for i in range(9)]  # 9 > 8 hosts
    with pytest.raises(SchedulingError):
        cluster.schedule_gang(pods)
    # rollback left no residue
    for node in cluster.nodes.values():
        assert node.info.allocatable[ResourceTPU] == 8
        assert not node.pods


# -- config 5: heterogeneous GPU + TPU cluster ------------------------------


def gpu_pod(name, gpus):
    return PodInfo(
        name=name,
        running_containers={"main": ContainerInfo(requests={ResourceGPU: gpus})},
    )


def test_config5_heterogeneous():
    from tests.test_device_nvidia import titan_box
    from kubetpu.device.nvidia import new_fake_nvidia_gpu_manager

    cluster = Cluster()
    cluster.register_node(
        "tpu-node", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
    )
    cluster.register_node(
        "gpu-node", device=new_fake_nvidia_gpu_manager(titan_box(), "vol", "drv")
    )

    t = cluster.schedule(tpu_pod("tpujob", 4))
    g = cluster.schedule(gpu_pod("gpujob", 4))
    assert t.node_name == "tpu-node"
    assert g.node_name == "gpu-node"

    _, _, tenv = cluster.allocate("tpujob")["main"]
    assert "TPU_VISIBLE_DEVICES" in tenv
    _, _, genv = cluster.allocate("gpujob")["main"]
    assert len(genv["NVIDIA_VISIBLE_DEVICES"].split(",")) == 4
    # GPU fill respected NVLink grouping: 4 GPUs from one socket's groups
    got = sorted(genv["NVIDIA_VISIBLE_DEVICES"].split(","))
    assert got == [f"GPU{i:02d}" for i in range(4)] or got == [
        f"GPU{i:02d}" for i in range(4, 8)
    ]

    assert cluster.nodes["gpu-node"].info.allocatable[ResourceGPU] == 4
    assert cluster.nodes["tpu-node"].info.allocatable[ResourceTPU] == 4


def test_init_containers_reuse_pool():
    cluster = v5e8_cluster()
    pod = PodInfo(
        name="with-init",
        init_containers={"init": ContainerInfo(requests={ResourceTPU: 2})},
        running_containers={"main": ContainerInfo(requests={ResourceTPU: 4})},
    )
    placed = cluster.schedule(pod)
    main_chips = set(placed.running_containers["main"].allocate_from.values())
    init_chips = set(placed.init_containers["init"].allocate_from.values())
    assert len(main_chips) == 4
    assert init_chips <= main_chips  # init reuses the pod's pool
    assert cluster.nodes["v5e8-n0"].info.allocatable[ResourceTPU] == 4


def test_two_physical_slices_not_conflated():
    """Two distinct v5e-64 slices (different slice uids): a gang must land
    entirely within ONE physical slice — chips across slices are DCN, not
    ICI, and must never count as adjacent."""
    cluster = Cluster()
    for h in range(4):
        cluster.register_node(
            f"a{h}",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h, slice_uid="podA")
            ),
        )
        cluster.register_node(
            f"b{h}",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-64", host_index=h, slice_uid="podB")
            ),
        )
    placed = cluster.schedule_gang([tpu_pod(f"w{i}", 8) for i in range(4)])
    slices = {p.node_name[0] for p in placed}
    assert len(slices) == 1  # all four workers in one physical slice
    assert cluster.gang_contiguity(placed) == 1.0

    # a 5-host gang cannot fit either 4-host slice: all-or-nothing fails
    # rather than silently straddling DCN
    for p in placed:
        cluster.release(p.name)
    with pytest.raises(SchedulingError):
        cluster.schedule_gang([tpu_pod(f"x{i}", 8) for i in range(5)])


def test_gpu_pool_spills_across_groups():
    """A 6-GPU pod on an 8-GPU two-socket box: the structural fill must
    spill across NVLink groups (no single group holds 6) without failing."""
    from tests.test_device_nvidia import titan_box
    from kubetpu.device.nvidia import new_fake_nvidia_gpu_manager

    cluster = Cluster()
    cluster.register_node(
        "gpu-node", device=new_fake_nvidia_gpu_manager(titan_box(), "v", "d")
    )
    placed = cluster.schedule(gpu_pod("big", 6))
    af = placed.running_containers["main"].allocate_from
    assert len(af) == 6
    assert len(set(af.values())) == 6
    assert cluster.nodes["gpu-node"].info.allocatable[ResourceGPU] == 2


def test_mesh_state_memo_survives_net_zero_churn():
    """Regression: take+return netting zero chips must NOT serve a stale
    memoized mesh state (the (len, scalar) fingerprint aliases; explicit
    invalidation from the accounting path is load-bearing)."""
    cluster = v5e8_cluster()
    a = cluster.schedule(tpu_pod("a", 4))
    a_chips = set(a.running_containers["main"].allocate_from.values())
    b = cluster.schedule(tpu_pod("b", 4))  # parses at scalar 4
    cluster.release("a")                   # scalar back to 4: aliases b's parse
    c = cluster.schedule(tpu_pod("c", 4))  # must get a's freed chips, not b's
    c_chips = set(c.running_containers["main"].allocate_from.values())
    b_chips = set(b.running_containers["main"].allocate_from.values())
    assert c_chips == a_chips
    assert c_chips.isdisjoint(b_chips)
    # no negative card values anywhere
    assert all(v >= 0 for v in cluster.nodes["v5e8-n0"].info.allocatable.values())


def test_gang_kube_only_requests_single_slice_guard():
    """A gang whose chip counts ride ONLY kube-native requests is still a
    TPU gang: when no single slice can host it, schedule_gang must raise
    rather than silently straddle slices over DCN (ADVICE r1 medium)."""
    cluster = Cluster()
    for uid in ("podA", "podB"):
        cluster.register_node(
            f"{uid}-h0",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-8", slice_uid=uid)
            ),
        )

    def kube_pod(name):
        return PodInfo(
            name=name,
            running_containers={
                "main": ContainerInfo(kube_requests={ResourceTPU: 8})
            },
        )

    with pytest.raises(SchedulingError):
        cluster.schedule_gang([kube_pod("w0"), kube_pod("w1")])
    for node in cluster.nodes.values():  # all-or-nothing left no residue
        assert not node.pods


def test_early_exit_resumes_when_fill_disagrees(monkeypatch):
    """The predicate sweep stops at the first bound-reaching node; if the
    group-scheduler fill rejects it (stale scalar vs real free cards), the
    sweep must RESUME and land on the NEXT bound-reaching node — never fail
    the pod, and never settle for a sub-bound candidate."""
    from kubetpu.core import group_scheduler

    cluster = Cluster()
    for i in range(3):
        cluster.register_node(
            f"n{i}", device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-8"))
        )
    real_fill = group_scheduler.fill_allocate_from
    attempts = []

    def flaky_fill(node_info, pod_info):
        attempts.append(node_info.name)
        if node_info.name == "n0":
            return False  # the disagreement the fallback path exists for
        return real_fill(node_info, pod_info)

    monkeypatch.setattr(group_scheduler, "fill_allocate_from", flaky_fill)
    placed = cluster.schedule(tpu_pod("p", 4))
    # sweep broke at n0 (perfect score), fill failed there, sweep resumed
    # and the next perfect node n1 won — n2 was never needed
    assert placed.node_name == "n1"
    assert attempts == ["n0", "n1"]
    assert not cluster.nodes["n0"].pods and "p" in cluster.nodes["n1"].pods


# -- cordon / drain -----------------------------------------------------------


def _fresh_two_hosts():
    from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager

    c = Cluster()
    for h in (0, 2):
        c.register_node(f"h{h}", device=new_fake_tpu_dev_manager(
            make_fake_tpus_info("v5e-64", host_index=h)))
    return c


def test_cordon_excludes_every_placement_path():
    from kubetpu.core.cluster import PriorityKey

    c = _fresh_two_hosts()
    c.cordon("h0")
    # plain scheduling avoids the cordoned node
    for i in range(2):
        p = c.schedule(tpu_pod(f"p{i}", 4))
        assert p.node_name == "h2"
    # preemption must not force onto it either
    high = tpu_pod("vip", 8)
    high.requests[PriorityKey] = 10
    placed, evicted = c.schedule_preempting(high)
    assert placed.node_name == "h2" and evicted
    # gangs cannot use the cordoned host: a 2-host gang no longer fits
    c2 = _fresh_two_hosts()
    c2.cordon("h0")
    with pytest.raises(SchedulingError):
        c2.schedule_gang([tpu_pod("g0", 8), tpu_pod("g1", 8)])
    # uncordon restores it
    c2.cordon("h0", on=False)
    assert len(c2.schedule_gang([tpu_pod("g0", 8), tpu_pod("g1", 8)])) == 2


def test_drain_migrates_and_reports_unplaced():
    c = _fresh_two_hosts()
    a = c.schedule(tpu_pod("a", 4), lambda n: n == "h0")
    b = c.schedule(tpu_pod("b", 8), lambda n: n == "h2")
    assert a.node_name == "h0" and b.node_name == "h2"
    migrated, unplaced = c.drain("h0")
    # "a" cannot move (h2 is full): evicted, reported unplaced
    assert [p.name for p in unplaced] == ["a"] and migrated == []
    assert "h0" in c.cordoned and not c.nodes["h0"].pods
    # free h2 and the next drain-style migration works
    c.release("b")
    c.cordon("h0", on=False)
    a2 = c.schedule(tpu_pod("a2", 4), lambda n: n == "h0")
    migrated, unplaced = c.drain("h0")
    assert [p.name for p in migrated] == ["a2"] and not unplaced
    assert migrated[0].node_name == "h2"


def test_drain_keeps_gang_member_in_slice():
    """A drained gang member may only land inside its mates' slice — if
    that slice has no room, it is unplaced, never straddled elsewhere."""
    from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager

    c = Cluster()
    for h in (0, 2):
        c.register_node(f"s1h{h}", device=new_fake_tpu_dev_manager(
            make_fake_tpus_info("v5e-64", host_index=h, slice_uid="sliceA")))
    c.register_node("other", device=new_fake_tpu_dev_manager(
        make_fake_tpus_info("v5e-8", slice_uid="sliceB")))
    placed = c.schedule_gang([tpu_pod("g0", 8), tpu_pod("g1", 8)])
    victim = placed[0].node_name
    migrated, unplaced = c.drain(victim)
    # mates' slice is full (the surviving member holds its host whole) and
    # the other slice is out of bounds for a gang member
    assert [p.name for p in unplaced] == [placed[0].name]
    assert migrated == []
    assert not any(p.name == placed[0].name for n in c.nodes.values()
                   for p in n.pods.values())


def test_defrag_ignores_cordoned_nodes():
    """A cordoned node's free chips must not count as 'already fits'
    (schedule would refuse to place there), nor serve as a migration
    destination."""
    c = Cluster()
    for i in range(2):
        c.register_node(f"n{i}", device=new_fake_tpu_dev_manager(
            make_fake_tpus_info("v5e-8")))
    # fragment n1: hold chips so no contiguous 4-block remains
    held = {}
    for i in range(8):
        p = c.schedule(tpu_pod(f"s{i}", 1), lambda n: n == "n1")
        _t, coords = c.pod_chip_coords(p)
        held[coords[0]] = p.name
    for coord, pname in held.items():
        if coord not in {(0, 1), (1, 2)}:
            c.release(pname)
    # n0 pristine but cordoned: WITHOUT the fix defrag_plan returns []
    # ("already fits") and the follow-up schedule fails
    c.cordon("n0")
    plan = c.defrag_plan(4)
    assert plan != []  # cordoned free space is not a fit
    if plan is not None:
        moved, pending = c.execute_defrag(plan, pending=tpu_pod("big", 4))
        assert pending is not None and pending.node_name == "n1"
        assert all(m.to_node != "n0" for m in plan)
    c.cordon("n0", on=False)
    assert c.defrag_plan(4) == []  # uncordoned pristine node fits plainly


# -- multislice gangs (DCN-spanning, opt-in) --------------------------------


def two_slice_cluster(hosts_per_slice=4):
    """Two distinct v5e-64 slices (podA/podB), *hosts_per_slice* hosts each."""
    cluster = Cluster()
    for uid, prefix in (("podA", "a"), ("podB", "b")):
        for h in range(hosts_per_slice):
            cluster.register_node(
                f"{prefix}{h}",
                device=new_fake_tpu_dev_manager(
                    make_fake_tpus_info("v5e-64", host_index=h, slice_uid=uid)
                ),
            )
    return cluster


def multislice_pod(name, chips, k=2):
    from kubetpu.scheduler.meshstate import MultisliceKey

    return tpu_pod(name, chips, **{MultisliceKey: k})


def test_multislice_gang_spans_two_slices():
    """A 64-chip gang over two 32-chip slice remnants: with the multislice
    knob it places 4+4 pods, per-slice contiguity 1.0, and every member is
    stamped with its slice membership."""
    from kubetpu.scheduler.meshstate import GangSliceIdKey, GangSlicesKey

    cluster = two_slice_cluster()
    placed = cluster.schedule_gang(
        [multislice_pod(f"w{i}", 8) for i in range(8)]
    )
    assert len(placed) == 8
    per = cluster.gang_slice_contiguity(placed)
    assert len(per) == 2
    assert all(v == 1.0 for v in per.values())
    assert cluster.gang_contiguity(placed) == 1.0
    by_sid = {}
    for p in placed:
        assert p.requests[GangSlicesKey] == 2
        by_sid.setdefault(p.requests[GangSliceIdKey], set()).add(
            p.node_name[0]
        )
    # each sub-gang confined to exactly one slice
    assert sorted(by_sid) == [0, 1]
    assert all(len(prefixes) == 1 for prefixes in by_sid.values())
    # allocate exports the libtpu multislice identity
    for p in placed:
        _, _, env = cluster.allocate(p.name)["main"]
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == str(p.requests[GangSliceIdKey])


def test_multislice_prefers_single_slice_when_it_fits():
    """The knob is an escape hatch, not a preference: a gang that fits one
    slice stays there (no DCN hop, no membership stamps)."""
    from kubetpu.scheduler.meshstate import GangSlicesKey

    cluster = two_slice_cluster()
    placed = cluster.schedule_gang(
        [multislice_pod(f"w{i}", 8) for i in range(4)]
    )
    assert len({p.node_name[0] for p in placed}) == 1
    assert all(GangSlicesKey not in p.requests for p in placed)


def test_multislice_respects_max_slices_and_rolls_back():
    """k=2 cannot make 3 slices' worth of chips appear: all-or-nothing
    failure leaves zero residue."""
    cluster = two_slice_cluster()
    with pytest.raises(SchedulingError):
        cluster.schedule_gang([multislice_pod(f"w{i}", 8) for i in range(9)])
    for node in cluster.nodes.values():
        assert node.info.allocatable[ResourceTPU] == 8
        assert not node.pods


def test_multislice_knob_value_one_keeps_single_slice_guard():
    cluster = two_slice_cluster()
    with pytest.raises(SchedulingError):
        cluster.schedule_gang(
            [multislice_pod(f"w{i}", 8, k=1) for i in range(8)]
        )
    for node in cluster.nodes.values():
        assert not node.pods


def test_multislice_replacement_pins_own_subgang_slice():
    """An evicted multislice member re-places only within ITS sub-gang's
    slice — rejoining the other sub-gang's slice would silently change the
    job's DCN topology."""
    cluster = two_slice_cluster()
    placed = cluster.schedule_gang(
        [multislice_pod(f"w{i}", 8) for i in range(8)]
    )
    victim = placed[-1]
    home = victim.node_name[0]  # 'a' or 'b'
    cluster.release(victim.name)
    filt = cluster.gang_slice_filter(victim)
    assert filt is not None
    for node in cluster.nodes:
        assert filt(node) == (node[0] == home)
    # and the re-place through the filter lands back on the home slice
    replaced = cluster.schedule(victim.copy(), filt)
    assert replaced.node_name[0] == home


def test_multislice_subgangs_are_equal_sized():
    """The dcn mesh axis needs the same device count per slice: with
    unequal slice headroom (5 free hosts vs 7) a 10-pod gang must still
    split 5+5, not 7+3 — and an odd gang that cannot split equally at
    k=2 refuses rather than placing a mesh-incompatible gang."""
    cluster = two_slice_cluster(hosts_per_slice=7)
    # shrink podA's headroom to 5 hosts
    for h in (5, 6):
        cluster.schedule(
            tpu_pod(f"hold{h}", 8), lambda n, t=f"a{h}": n == t
        )
    placed = cluster.schedule_gang(
        [multislice_pod(f"w{i}", 8) for i in range(10)]
    )
    from kubetpu.scheduler.meshstate import GangSliceIdKey

    sizes = {}
    for p in placed:
        sizes[p.requests[GangSliceIdKey]] = sizes.get(
            p.requests[GangSliceIdKey], 0) + 1
    assert sorted(sizes.values()) == [5, 5]
    for p in placed:
        cluster.release(p.name)
    # 9 pods: k=2 does not divide, max_slices=2 -> refuse, no residue
    with pytest.raises(SchedulingError):
        cluster.schedule_gang([multislice_pod(f"x{i}", 8) for i in range(9)])
    assert all(
        not node.pods or all(p.startswith("hold") for p in node.pods)
        for node in cluster.nodes.values()
    )


def test_multislice_evicted_subgang_avoids_other_subgang_slices():
    """When a WHOLE sub-gang is evicted, its members re-place anywhere
    EXCEPT the slices of still-placed sub-gangs — landing there would put
    two MEGASCALE "slices" on one physical slice."""
    cluster = two_slice_cluster()
    placed = cluster.schedule_gang(
        [multislice_pod(f"w{i}", 8) for i in range(8)]
    )
    # evict one complete sub-gang
    from kubetpu.scheduler.meshstate import GangSliceIdKey

    sub1 = [p for p in placed if p.requests[GangSliceIdKey] == 1]
    survivor_prefix = next(
        p.node_name[0] for p in placed if p.requests[GangSliceIdKey] == 0
    )
    for p in sub1:
        cluster.release(p.name)
    filt = cluster.gang_slice_filter(sub1[0])
    assert filt is not None
    for node in cluster.nodes:
        # allowed anywhere but the surviving sub-gang's slice
        assert filt(node) == (node[0] != survivor_prefix)


def test_pod_device_need_counts_kube_native_pre_merge():
    """The gang capacity pre-filter runs on UN-translated templates:
    pod_device_need must apply the kube/device max-merge inline, so a
    kube-native-only pod counts its real chips, not 0 (review r5)."""
    from kubetpu.scheduler.deviceclass import TPU
    from kubetpu.scheduler.translate import pod_device_count, pod_device_need

    kube_pod = PodInfo(
        name="k",
        running_containers={
            "main": ContainerInfo(kube_requests={ResourceTPU: 4})
        },
        init_containers={
            "init": ContainerInfo(kube_requests={ResourceTPU: 6})
        },
    )
    assert pod_device_need(TPU, kube_pod) == 6  # max(sum=4, init max=6)
    assert pod_device_count(TPU, kube_pod) == 0  # pre-merge: blind
    # and a kube-native multislice gang still places end to end
    from kubetpu.scheduler.meshstate import MultisliceKey

    cluster = two_slice_cluster()

    def kpod(name):
        return PodInfo(
            name=name, requests={MultisliceKey: 2},
            running_containers={
                "main": ContainerInfo(kube_requests={ResourceTPU: 8})
            },
        )

    placed = cluster.schedule_gang([kpod(f"w{i}") for i in range(8)])
    per = cluster.gang_slice_contiguity(placed)
    assert len(per) == 2 and all(v == 1.0 for v in per.values())
