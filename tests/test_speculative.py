"""Speculative decoding: draft-propose + chunk-verify must be EXACTLY
equivalent to target-only greedy decoding (the greedy acceptance rule's
defining invariant), for good and bad drafts, GQA targets, and bf16."""

import dataclasses

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.decode import forward_chunk, init_kv_cache, make_generate, prefill
from kubetpu.jobs.speculative import make_speculative_generate

TARGET = ModelConfig(vocab=64, d_model=32, n_layers=3, n_heads=4, d_ff=64)
DRAFT = ModelConfig(vocab=64, d_model=16, n_layers=1, n_heads=2, d_ff=32)


@pytest.mark.slow
def test_forward_chunk_matches_sequential_decode():
    """The T-token chunk forward through the cache must equal T sequential
    single-token steps (same cache, same logits at the last position).
    Slow: compiles a fresh step per sequential position; the greedy
    equivalence tests keep the chunk path pinned in tier-1."""
    from kubetpu.jobs.speculative import _forward_chunk_at

    params = init_params(jax.random.PRNGKey(0), TARGET)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, TARGET.vocab)
    extra = jax.random.randint(jax.random.PRNGKey(2), (2, 3), 0, TARGET.vocab)

    k1, v1 = init_kv_cache(TARGET, 2, 16)
    _, k1, v1 = prefill(TARGET, params, prompt, k1, v1)
    logits_chunk, k1, v1 = forward_chunk(TARGET, params, extra, k1, v1, 6)

    k2, v2 = init_kv_cache(TARGET, 2, 16)
    _, k2, v2 = prefill(TARGET, params, prompt, k2, v2)
    pos = jnp.full((2,), 6, jnp.int32)
    seq_logits = []
    for t in range(3):
        lg, k2, v2 = _forward_chunk_at(
            TARGET, params, extra[:, t][:, None], k2, v2, pos + t
        )
        seq_logits.append(lg[:, 0])
    np.testing.assert_allclose(
        np.asarray(logits_chunk), np.stack([np.asarray(x) for x in seq_logits], 1),
        rtol=2e-4, atol=2e-5,
    )
    np.testing.assert_allclose(np.asarray(k1), np.asarray(k2), rtol=1e-5, atol=1e-6)


def _assert_matches_plain_greedy(target_cfg, draft_cfg, gamma, steps=9):
    t_params = init_params(jax.random.PRNGKey(0), target_cfg)
    d_params = init_params(jax.random.PRNGKey(7), draft_cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, target_cfg.vocab)

    plain = make_generate(target_cfg)(t_params, prompt, jax.random.PRNGKey(2), steps)
    spec, mean_accept = make_speculative_generate(target_cfg, draft_cfg, gamma)(
        t_params, d_params, prompt, steps
    )
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(plain))
    return float(mean_accept)


def test_speculative_equals_greedy_random_draft():
    """Even a draft that almost never agrees must yield the exact greedy
    output (just with ~1 token per round)."""
    accept = _assert_matches_plain_greedy(TARGET, DRAFT, gamma=4)
    assert accept >= 1.0  # every round emits at least the correction token


def test_speculative_equals_greedy_perfect_draft():
    """Draft == target: every draft token is accepted, rounds emit gamma
    tokens each, and the output is still exactly the greedy sequence."""
    t_params = init_params(jax.random.PRNGKey(0), TARGET)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, TARGET.vocab)
    steps, gamma = 8, 4

    plain = make_generate(TARGET)(t_params, prompt, jax.random.PRNGKey(2), steps)
    spec, mean_accept = make_speculative_generate(TARGET, TARGET, gamma)(
        t_params, t_params, prompt, steps
    )
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(plain))
    # High acceptance — not exactly gamma+1: the draft decodes in T=1 steps
    # while verification is one chunk, so reduction order differs and a
    # random-init model's near-uniform logits flip argmax on near-ties.
    # Real (trained) models have separated logits; here > 1.8 WRITTEN
    # tokens/round (the stat excludes clipped final-round tokens)
    # demonstrates multi-token acceptance.
    assert float(mean_accept) > 1.8


def test_speculative_with_gqa_target():
    cfg = dataclasses.replace(TARGET, n_kv_heads=2)
    _assert_matches_plain_greedy(cfg, DRAFT, gamma=3)


def test_speculative_gamma_one():
    _assert_matches_plain_greedy(TARGET, DRAFT, gamma=1)


def test_speculative_bf16_runs():
    cfg_t = dataclasses.replace(TARGET, dtype=jnp.bfloat16)
    cfg_d = dataclasses.replace(DRAFT, dtype=jnp.bfloat16)
    t_params = init_params(jax.random.PRNGKey(0), cfg_t)
    d_params = init_params(jax.random.PRNGKey(7), cfg_d)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, cfg_t.vocab)
    out, _ = make_speculative_generate(cfg_t, cfg_d, 3)(t_params, d_params, prompt, 6)
    assert out.shape == (2, 10)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < cfg_t.vocab).all()
