"""CLI smoke tests (tpudevs, schedsim) + multi-host launch wiring +
cluster status observability."""

import json
import subprocess
import sys

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU


def _run(args):
    return subprocess.run(
        [sys.executable, "-m"] + args, capture_output=True, text=True, timeout=120
    )


def test_tpudevs_plugin_fake():
    proc = _run(["kubetpu.cli.tpudevs", "--plugin", "--fake", "v5e-8"])
    assert proc.returncode == 0
    assert "Using plugin" in proc.stdout
    body = proc.stdout[proc.stdout.index("{"):]
    node = json.loads(body)
    assert node["capacity"]["kubedevice/tpu"] == 8
    assert "resource/group/tpu-slice/v5e-8/slice0/0" in node["capacity"]


def test_tpudevs_direct_fake():
    proc = _run(["kubetpu.cli.tpudevs", "--fake", "v5e-4"])
    assert proc.returncode == 0
    info = json.loads(proc.stdout[proc.stdout.index("{"):])
    assert len(info["Devices"]) == 4


def test_schedsim_all_configs():
    proc = _run(["kubetpu.cli.schedsim", "--rounds", "2"])
    assert proc.returncode == 0
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()]
    assert [l["config"] for l in lines] == [1, 2, 3, 4, 5, 6, 7]
    by_cfg = {l["config"]: l for l in lines}
    assert by_cfg[2]["contiguity"] == 1.0
    assert by_cfg[3]["packed"] is True
    assert by_cfg[4]["all_or_nothing"] is True
    assert by_cfg[5]["co_scheduled"] is True


def _gang_cluster():
    cluster = Cluster()
    for h in range(4):
        cluster.register_node(
            f"host{h}",
            device=new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-64", host_index=h)),
        )
    return cluster


def test_gang_launch_configs():
    from kubetpu.jobs.launch import gang_launch_configs

    cluster = _gang_cluster()
    pods = [
        PodInfo(name=f"w{i}", running_containers={"m": ContainerInfo(requests={ResourceTPU: 8})})
        for i in range(2)
    ]
    placed = cluster.schedule_gang(pods)
    configs = gang_launch_configs(cluster, placed)
    assert len(configs) == 2
    assert configs[0].num_processes == 2
    # coordinator = rank-0 worker's host; every config agrees
    assert {c.coordinator_address for c in configs} == {placed[0].node_name + ":8476"}
    # process ids are gang ranks in [0, n) — NOT host indices (a 2-host gang
    # may land on hosts {0, 2} for a square chip region)
    assert [c.process_id for c in configs] == [0, 1]
    assert all(c.local_device_ids == list(range(8)) for c in configs)


def test_initialize_distributed_noop_single():
    from kubetpu.jobs.launch import LaunchConfig, initialize_distributed

    initialize_distributed(None)
    initialize_distributed(
        LaunchConfig("x:1", num_processes=1, process_id=0, local_device_ids=[0])
    )  # must not try to contact a coordinator


def test_cluster_status_snapshot():
    cluster = _gang_cluster()
    cluster.schedule(
        PodInfo(name="p", running_containers={"m": ContainerInfo(requests={ResourceTPU: 4})})
    )
    status = cluster.status()
    assert set(status["nodes"]) == {f"host{h}" for h in range(4)}
    n0 = status["nodes"]["host0"]
    assert n0["kubedevice/tpu"] == {"free": 4, "total": 8}
    assert n0["pods"] == ["p"]
    assert status["slices_free_chips"]["v5e-64/slice0"] == 28
    assert status["latency"]["schedule_pod"]["count"] == 1


def test_agent_emits_advertisement():
    proc = _run(["kubetpu.cli.agent", "--fake", "v5e-8", "--interval", "0.1",
                 "--iterations", "2"])
    assert proc.returncode == 0
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()]
    # advertisement unchanged -> emitted once despite 2 iterations
    assert len(lines) == 1
    assert lines[0]["capacity"]["kubedevice/tpu"] == 8


def test_refresh_node_preserves_allocations():
    from kubetpu.device.tpu_plugin import FakeTpuPlugin

    cluster = _gang_cluster()
    placed = cluster.schedule(
        PodInfo(name="p", running_containers={"m": ContainerInfo(requests={ResourceTPU: 4})})
    )
    name = placed.node_name
    assert cluster.nodes[name].info.allocatable[ResourceTPU] == 4

    # plain refresh: held chips stay subtracted
    cluster.refresh_node(name)
    assert cluster.nodes[name].info.allocatable[ResourceTPU] == 4
    held = set(placed.running_containers["m"].allocate_from.values())
    for key in held:
        assert cluster.nodes[name].info.allocatable[key] == 0

    # a chip the pod does NOT hold disappears from the probe
    from kubetpu.device import make_fake_tpus_info

    mgr = cluster.nodes[name].device
    free_locals = [
        i for i in range(8)
        if not any(f"/tpu/{i}/cards" in k for k in held)
    ]
    mgr._plugin = FakeTpuPlugin(
        make_fake_tpus_info("v5e-64", host_index=int(name.removeprefix("host")),
                            missing_chips=(free_locals[0],))
    )
    cluster.refresh_node(name)
    info = cluster.nodes[name].info
    assert info.capacity[ResourceTPU] == 7
    assert info.allocatable[ResourceTPU] == 3  # 7 found - 4 held
    assert not any(f"/tpu/{free_locals[0]}/cards" in k for k in info.capacity)


def test_event_log_records_lifecycle():
    cluster = _gang_cluster()
    p = cluster.schedule(
        PodInfo(name="e1", running_containers={"m": ContainerInfo(requests={ResourceTPU: 2})})
    )
    cluster.release("e1")
    cluster.fail_node(p.node_name)
    kinds = [e["kind"] for e in cluster.events]
    assert kinds == ["schedule", "release", "node_failed"]
    assert cluster.status()["recent_events"][-1]["kind"] == "node_failed"


def test_gang_launch_configs_multislice():
    """The launch layer closes the multislice loop: a DCN-spanning gang
    yields ONE jax.distributed process group (ranks = gang order across
    both sub-gangs, one coordinator), and each worker's env still carries
    its MEGASCALE identity for the dcn-axis mesh build."""
    from kubetpu.core import Cluster
    from kubetpu.jobs.launch import gang_launch_configs, select_device_env
    from kubetpu.scheduler.meshstate import MultisliceKey

    cluster = Cluster()
    for uid, pre in (("podA", "a"), ("podB", "b")):
        for h in range(2):
            cluster.register_node(
                f"{pre}{h}",
                device=new_fake_tpu_dev_manager(
                    make_fake_tpus_info("v5e-64", host_index=h,
                                        slice_uid=uid)
                ),
            )
    pods = [
        PodInfo(name=f"w{i}", requests={MultisliceKey: 2},
                running_containers={
                    "m": ContainerInfo(requests={ResourceTPU: 8})})
        for i in range(4)  # 32 chips > 16 per (2-host) slice: spans both
    ]
    placed = cluster.schedule_gang(pods)
    configs = gang_launch_configs(cluster, placed)
    assert len(configs) == 4
    assert all(c.num_processes == 4 for c in configs)
    assert [c.process_id for c in configs] == [0, 1, 2, 3]
    assert {c.coordinator_address for c in configs} == {
        placed[0].node_name + ":8476"
    }
    # MEGASCALE env per worker, both slice ids represented
    sids = set()
    for pod in placed:
        env = select_device_env(
            [e for _, _, e in cluster.allocate(pod.name).values()]
        )
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        sids.add(env["MEGASCALE_SLICE_ID"])
    assert sids == {"0", "1"}
