"""Encoder-decoder family: causality on the target side, genuine cross
dependence on the source side, sharded training that learns, and greedy
generation — the same contract bar the other families pin."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, make_mesh
from kubetpu.jobs.seq2seq import (
    decoder_forward,
    encode,
    init_seq2seq_params,
    init_seq2seq_state,
    make_seq2seq_generate,
    make_seq2seq_train_step,
    seq2seq_loss,
)

CFG = ModelConfig(vocab=32, d_model=32, n_layers=2, n_heads=4, d_ff=64)


def _setup(seed=0):
    params = init_seq2seq_params(jax.random.PRNGKey(seed), CFG)
    src = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, CFG.vocab)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, CFG.vocab)
    return params, src, tgt


def test_decoder_is_causal_and_cross_attends():
    params, src, tgt = _setup()
    memory = encode(params, src, CFG)
    logits = decoder_forward(params, tgt, memory, CFG)
    assert logits.shape == (2, 8, CFG.vocab)

    # causality: perturbing a LATE target token must not change EARLY logits
    tgt2 = tgt.at[:, -1].set((tgt[:, -1] + 1) % CFG.vocab)
    logits2 = decoder_forward(params, tgt2, memory, CFG)
    np.testing.assert_allclose(np.asarray(logits[:, :-1]),
                               np.asarray(logits2[:, :-1]), atol=1e-5)

    # cross dependence: perturbing the SOURCE must change decoder logits
    src2 = src.at[:, 0].set((src[:, 0] + 1) % CFG.vocab)
    logits3 = decoder_forward(params, tgt, encode(params, src2, CFG), CFG)
    assert float(jnp.max(jnp.abs(logits3 - logits))) > 1e-4


@pytest.mark.slow
def test_seq2seq_trains_on_copy_task():
    """Loss falls markedly on 'output = the source sequence' — only
    solvable through cross-attention (target inputs alone don't determine
    the output)."""
    from kubetpu.jobs.train import make_optimizer

    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    opt = make_optimizer(lr=3e-3)
    state, _opt = init_seq2seq_state(jax.random.PRNGKey(0), CFG, mesh,
                                     optimizer=opt)
    step = make_seq2seq_train_step(CFG, mesh, optimizer=opt)

    rng = np.random.RandomState(0)
    src = jnp.asarray(rng.randint(2, CFG.vocab, size=(8, 8)), jnp.int32)
    tgt_in = jnp.concatenate(
        [jnp.ones((8, 1), jnp.int32), src[:, :-1]], axis=1)  # BOS + shift
    first = None
    for _ in range(25):
        state, loss = step(state, src, tgt_in, src)
        if first is None:
            first = float(loss)
    assert np.isfinite(float(loss))
    assert float(loss) < first * 0.5, (first, float(loss))


def test_greedy_generate_emits_and_respects_source():
    params, src, _ = _setup()
    gen = make_seq2seq_generate(CFG, bos_id=1)
    out = gen(params, src, 6)
    assert out.shape == (2, 6)
    assert int(jnp.min(out)) >= 0 and int(jnp.max(out)) < CFG.vocab
    # different sources must be able to produce different outputs
    src2 = (src + 7) % CFG.vocab
    out2 = gen(params, src2, 6)
    assert not np.array_equal(np.asarray(out), np.asarray(out2))


def test_param_specs_match_param_tree():
    from kubetpu.jobs.seq2seq import seq2seq_param_specs

    params = init_seq2seq_params(jax.random.PRNGKey(0), CFG)
    specs = seq2seq_param_specs(CFG)
    jax.tree.map(lambda p, s: None, params, specs)  # structure must match
    assert "head" not in specs["encoder"]
    assert "wq_x" in specs["decoder"]["blocks"]


def test_moe_seq2seq_loss_includes_aux():
    """MoE configs must carry the load-balance aux from BOTH stacks —
    same moe_aux_coeff contract as the other families."""
    cfg0 = ModelConfig(vocab=32, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                       n_experts=2, moe_aux_coeff=0.0)
    cfg1 = ModelConfig(vocab=32, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                       n_experts=2, moe_aux_coeff=0.5)
    params = init_seq2seq_params(jax.random.PRNGKey(0), cfg0)
    src = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 32)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 32)
    plain = float(seq2seq_loss(params, src, tgt, tgt, cfg0))
    with_aux = float(seq2seq_loss(params, src, tgt, tgt, cfg1))
    assert np.isfinite(plain) and np.isfinite(with_aux)
    assert with_aux > plain  # the aux term is strictly positive here


def test_generate_eos_pins_finished_sequences():
    params, src, _ = _setup()
    gen = make_seq2seq_generate(CFG, bos_id=1, eos_id=0)
    out = np.asarray(gen(params, src, 8))
    for row in out:
        hits = np.where(row == 0)[0]
        if hits.size:  # everything after the first EOS must stay EOS
            assert (row[hits[0]:] == 0).all()


def test_cached_generate_matches_recompute_reference():
    """The KV-cached decoder (cross K/V precomputed, T=1 steps) must emit
    exactly the recompute-reference path's greedy tokens, with and
    without EOS pinning."""
    params, src, _ = _setup()
    for eos in (None, 0):
        ref = make_seq2seq_generate(CFG, bos_id=1, eos_id=eos, cached=False)
        fast = make_seq2seq_generate(CFG, bos_id=1, eos_id=eos, cached=True)
        np.testing.assert_array_equal(
            np.asarray(ref(params, src, 7)), np.asarray(fast(params, src, 7)),
            err_msg=f"eos={eos}")


@pytest.mark.slow
def test_seq2seq_chunked_loss_matches_unchunked():
    """cfg.loss_chunk streams the decoder CE tail — value and grads must
    match the materialized-logits path (tgt len 8, chunk 4)."""
    import dataclasses

    params, src, tgt = _setup()
    tgt_in, tgt_out = tgt[:, :-1], tgt[:, 1:]  # len 7 -> pad to 8
    tgt_in = jnp.pad(tgt_in, ((0, 0), (0, 1)))
    tgt_out = jnp.pad(tgt_out, ((0, 0), (0, 1)))
    cfgc = dataclasses.replace(CFG, loss_chunk=4)
    l0, g0 = jax.value_and_grad(seq2seq_loss)(params, src, tgt_in, tgt_out, CFG)
    l1, g1 = jax.value_and_grad(seq2seq_loss)(params, src, tgt_in, tgt_out, cfgc)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for p0, p1 in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(p0), np.asarray(p1),
                                   rtol=2e-4, atol=2e-5)


def test_windowed_cached_generate_matches_recompute():
    """cfg.window must band BOTH decoder paths identically: the cached
    (decode._decode_block) and recompute (decoder_forward) generations
    agree past the window boundary."""
    import dataclasses

    cfg = dataclasses.replace(CFG, window=3)
    params = init_seq2seq_params(jax.random.PRNGKey(0), cfg)
    src = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab)
    ref = make_seq2seq_generate(cfg, bos_id=1, cached=False)
    fast = make_seq2seq_generate(cfg, bos_id=1, cached=True)
    np.testing.assert_array_equal(
        np.asarray(ref(params, src, 9)), np.asarray(fast(params, src, 9)))
