"""Paged KV cache: greedy decode through the page pool must match the
dense-cache server EXACTLY (same math, different memory layout), pool
memory must track live tokens, and exhaustion must park — not corrupt —
requests (VERDICT r2 weak #4)."""

import jax
import numpy as np
import pytest

from kubetpu.jobs import ModelConfig, init_params
from kubetpu.jobs.paged import PagedDecodeServer, init_page_pool
from kubetpu.jobs.serving import DecodeServer

CFG = ModelConfig(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def test_paged_greedy_parity_with_dense_server(params):
    """Identical tokens from the paged and dense servers for staggered
    requests crossing page boundaries mid-decode."""
    prompts = [[3, 14, 15, 9, 2, 6], [26, 5], [35, 8, 9, 7, 9, 3, 2, 1, 4]]
    dense = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=12)
    paged = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                              max_new_tokens=12, page_size=8)

    results = {}
    for server, tag in ((dense, "dense"), (paged, "paged")):
        ra = server.submit(prompts[0])
        server.step()
        rb = server.submit(prompts[1])
        server.drain()
        rc = server.submit(prompts[2])
        server.drain()
        results[tag] = [server.result(r) for r in (ra, rb, rc)]
    assert results["paged"] == results["dense"]


def test_page_accounting_tracks_live_tokens(params):
    """pages_in_use == worst-case reservation while live; 0 after retire —
    and the pool is provisioned below the dense equivalent."""
    ps = 8
    server = PagedDecodeServer(CFG, params, n_slots=4, max_seq=64,
                               max_new_tokens=4, page_size=ps)
    dense_equivalent_pages = 4 * (64 // ps)
    assert server.pool_pages < dense_equivalent_pages

    prompt = [1, 2, 3, 4, 5]
    rid = server.submit(prompt)
    worst = len(prompt) + 4 + 1
    expect = (worst + ps - 1) // ps
    assert server.pages_in_use() == expect
    server.drain()
    assert server.finished(rid)
    assert server.pages_in_use() == 0  # retired slot returned its pages


def test_pool_exhaustion_parks_requests_without_corruption(params):
    """When the pool cannot cover a request's worst case, submit returns
    None / the queue parks — and once capacity frees, the parked request
    decodes to exactly the dense-server tokens."""
    ps = 8
    # pool with room for ONE worst-case request only
    server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                               max_new_tokens=8, page_size=ps, n_pages=3)
    pa, pb = [7, 8, 9, 1], [11, 12, 13]
    ra = server.submit(pa)
    assert ra is not None
    assert server.submit(pb) is None          # slots free, pages are not
    rb = server.enqueue(pb)                   # parks in the queue
    out = server.step()
    assert rb not in out                      # still parked: pool full
    server.drain()                            # a finishes -> pages free -> b runs
    assert server.finished(ra) and server.finished(rb)

    dense = DecodeServer(CFG, params, n_slots=2, max_seq=64, max_new_tokens=8)
    for rid, p in ((ra, pa), (rb, pb)):
        d = dense.submit(p)
        dense.drain()
        assert server.result(rid) == dense.result(d)


def test_warmup_and_queue_admission(params):
    server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=32,
                               max_new_tokens=3, page_size=8)
    server.warmup()
    rids = [server.enqueue([i + 1, i + 2]) for i in range(3)]
    server.drain()
    assert all(server.finished(r) for r in rids)
    stats = server.metrics_summary()
    assert stats["admission_stall"]["count"] == 3
    assert server.pages_in_use() == 0


def test_warmup_with_unaligned_max_seq(params):
    """warmup() must pad its dummies with the same page-rounded bucket
    the serve path uses — regression for the reshape crash when max_seq
    is not a page multiple (_bucket caps at max_seq, the pool scatter
    writes whole pages)."""
    server = PagedDecodeServer(CFG, params, n_slots=1, max_seq=24,
                               max_new_tokens=3, page_size=16, n_pages=2)
    server.warmup()
    rid = server.enqueue([5, 6, 7])
    server.drain()
    assert server.finished(rid)
    assert server.pages_in_use() == 0


def test_pool_frac_partitions_pool_honestly(params):
    """Round-18 vChips: ``pool_frac`` SIZES the pool to the replica's
    chip share — N packed replicas on one chip partition the page
    budget, the /load signal reflects it, and greedy tokens are
    unchanged (capacity, never results)."""
    full = PagedDecodeServer(CFG, params, n_slots=2, max_seq=32,
                             max_new_tokens=8, page_size=8, n_pages=64)
    quarter = PagedDecodeServer(CFG, params, n_slots=2, max_seq=32,
                                max_new_tokens=8, page_size=8, n_pages=64,
                                pool_frac=0.25)
    assert quarter.pool_pages == 16
    assert quarter.k_pages.shape[1] == 16    # the arrays ARE smaller
    info = quarter.load_info()
    assert info["pool_pages"] == 16
    assert info["pool_frac"] == 0.25
    assert "pool_frac" not in full.load_info()   # whole-chip: implicit
    assert 'kubetpu_serving_pool_frac 0.25' in quarter.metrics_text()
    prompts = [[3, 14, 15, 9, 2, 6], [26, 5]]
    out = {}
    for tag, server in (("full", full), ("quarter", quarter)):
        rids = [server.enqueue(p) for p in prompts]
        server.drain()
        out[tag] = [server.pop_result(r) for r in rids]
        server.check_invariants()
    assert out["full"] == out["quarter"]
    with pytest.raises(ValueError):
        PagedDecodeServer(CFG, params, pool_frac=0.0)
    with pytest.raises(ValueError):
        PagedDecodeServer(CFG, params, pool_frac=1.5)


def test_pool_smaller_than_worst_case_rejects_up_front(params):
    """A request whose worst case exceeds the WHOLE pool must raise at
    enqueue/submit — accepted-but-never-admittable would park the queue
    head forever and starve everything behind it."""
    server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                               max_new_tokens=8, page_size=8, n_pages=2)
    with pytest.raises(ValueError, match="pool"):
        server.enqueue([1] * 10)   # needs 3 pages worst-case, pool has 2
    with pytest.raises(ValueError, match="pool"):
        server.submit([1] * 10)
    # a coverable request still flows
    rid = server.submit([1, 2])
    server.drain()
    assert server.finished(rid)


def test_pool_shapes():
    k, v = init_page_pool(CFG, n_pages=10, page_size=8)
    assert k.shape == (CFG.n_layers, 10, 8, CFG.kv_heads, CFG.head_dim)
    assert v.shape == k.shape


def test_pallas_kernel_matches_xla_attend(params):
    """The Pallas paged-attention kernel (interpret mode) must match the
    XLA gather reference on random pages/tables/positions."""
    import jax.numpy as jnp

    from kubetpu.jobs.paged import _attend_paged
    from kubetpu.ops.paged_attention import paged_attention

    b, h, h_kv, d, ps, n_pool, max_pages = 3, 4, 2, 8, 4, 10, 4
    key = jax.random.PRNGKey(1)
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (b, h, d), jnp.float32)
    kp = jax.random.normal(k2, (n_pool, ps, h_kv, d), jnp.float32)
    vp = jax.random.normal(k3, (n_pool, ps, h_kv, d), jnp.float32)
    table = np.array([
        [5, 2, 7, -1],
        [0, -1, -1, -1],
        [9, 8, 1, 3],
    ], np.int32)
    pos = np.array([9, 2, 15], np.int32)  # mid-page, first-page, last slot full

    ref = _attend_paged(q, kp, vp, jnp.asarray(table), jnp.asarray(pos))
    out = paged_attention(q, kp, vp, jnp.asarray(table), jnp.asarray(pos),
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def _kernel_fixture(seed=1, b=3, h=4, h_kv=2, d=8, ps=4, n_pool=10,
                    max_pages=4):
    """Random pages + a ragged table/pos set covering mid-page,
    first-page and table-full geometries (the decode-kernel test's
    shapes, shared by the Round-15 variant tests)."""
    import jax.numpy as jnp

    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(k1, (b, h, d), jnp.float32)
    kp = jax.random.normal(k2, (n_pool, ps, h_kv, d), jnp.float32)
    vp = jax.random.normal(k3, (n_pool, ps, h_kv, d), jnp.float32)
    table = jnp.asarray(np.array([
        [5, 2, 7, -1],
        [0, -1, -1, -1],
        [9, 8, 1, 3],
    ], np.int32))
    pos = jnp.asarray(np.array([9, 2, 15], np.int32))
    return q, kp, vp, table, pos, k4


def test_pallas_kernel_int8_matches_gather_core():
    """Round-15 in-kernel int8 dequant: (values, scales) page pairs
    dequantized per-tile in VMEM must match the gather core's
    dequantize-then-attend math on the same quantized pool."""
    from kubetpu.jobs.paged import _attend_paged
    from kubetpu.jobs.quant import quantize_kv_chunk
    from kubetpu.ops.paged_attention import paged_attention

    q, kp, vp, table, pos, _ = _kernel_fixture()
    k8 = quantize_kv_chunk(kp)
    v8 = quantize_kv_chunk(vp)
    ref = _attend_paged(q, k8, v8, table, pos)
    out = paged_attention(q, k8, v8, table, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("window", [3, 6])
def test_pallas_kernel_banded_matches_gather_core(window):
    """Round-15 banded mask: window > 0 through the kernel == the gather
    core's band, including pages skipped entirely below the band."""
    from kubetpu.jobs.paged import _attend_paged
    from kubetpu.ops.paged_attention import paged_attention

    q, kp, vp, table, pos, _ = _kernel_fixture()
    ref = _attend_paged(q, kp, vp, table, pos, window=window)
    out = paged_attention(q, kp, vp, table, pos, window=window,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("pages_per_block", [2, 3])
def test_pallas_kernel_pages_per_block_parity(pages_per_block):
    """The pagedtune VMEM tile knob: any pages_per_block (including one
    that does not divide max_pages — the ragged final block clamps) is
    numerically the shipped default."""
    from kubetpu.jobs.paged import _attend_paged
    from kubetpu.ops.paged_attention import paged_attention

    q, kp, vp, table, pos, _ = _kernel_fixture()
    ref = _attend_paged(q, kp, vp, table, pos)
    out = paged_attention(q, kp, vp, table, pos,
                          pages_per_block=pages_per_block, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_chunk_kernel_matches_gather_core():
    """Round-15 multi-token chunk kernel: causal T-query-per-slot
    attention through the page table == _attend_paged_chunk, f32 and
    int8 pools, one-page-per-step and wider tiles."""
    import jax.numpy as jnp

    from kubetpu.jobs.paged import _attend_paged_chunk
    from kubetpu.jobs.quant import quantize_kv_chunk
    from kubetpu.ops.paged_attention import paged_attention_chunk

    _, kp, vp, table, _, kq = _kernel_fixture()
    t = 3
    qt = jax.random.normal(kq, (3, t, 4, 8), jnp.float32)
    pos = jnp.asarray(np.array([8, 0, 12], np.int32))
    ref = _attend_paged_chunk(qt, kp, vp, table, pos)
    for ppb in (1, 2):
        out = paged_attention_chunk(qt, kp, vp, table, pos,
                                    pages_per_block=ppb, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
    k8 = quantize_kv_chunk(kp)
    v8 = quantize_kv_chunk(vp)
    ref8 = _attend_paged_chunk(qt, k8, v8, table, pos)
    out8 = paged_attention_chunk(qt, k8, v8, table, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(out8), np.asarray(ref8),
                               atol=2e-5)


def test_paged_server_with_pallas_kernel_parity(params):
    """End-to-end: the paged server running the Pallas kernel (interpret)
    produces exactly the dense server's greedy tokens — at the shipped
    tile AND a tuned pages_per_block (the pagedtune knob plumbs through
    the constructor)."""
    prompts = [[3, 14, 15, 9], [26, 5, 1]]
    dense = DecodeServer(CFG, params, n_slots=2, max_seq=32, max_new_tokens=6)
    paged = PagedDecodeServer(CFG, params, n_slots=2, max_seq=32,
                              max_new_tokens=6, page_size=8,
                              use_kernel=True, interpret=True)
    tiled = PagedDecodeServer(CFG, params, n_slots=2, max_seq=32,
                              max_new_tokens=6, page_size=8,
                              use_kernel=True, interpret=True,
                              pages_per_block=2)
    outs = {}
    for server, tag in ((dense, "dense"), (paged, "paged"),
                        (tiled, "tiled")):
        rids = [server.submit(p) for p in prompts]
        server.drain()
        outs[tag] = [server.result(r) for r in rids]
    assert outs["paged"] == outs["dense"]
    assert outs["tiled"] == outs["dense"]


def test_kernel_chunked_prefix_storm_parity_and_counters(params):
    """Round-15 composition storm: use_kernel x chunked prefill x
    prefix-cache hits — greedy token-exact vs the cold gather-core
    server, pool oracle clean per drain, and the kernel adoption
    counters (`kubetpu_paged_kernel_steps_total` + HBM-bytes-saved) on
    the serving registry actually move."""
    fam = [(i * 5) % 60 + 1 for i in range(16)]
    prompts = [fam + [t] for t in (1, 2, 3)] + [[26, 5], [63] * 3]

    def run(server):
        outs = []
        for wave in (prompts[:3], prompts[3:]):
            rids = [server.enqueue(p) for p in wave]
            server.drain()
            outs.extend(server.pop_result(r) for r in rids)
            server.check_invariants()
        return outs

    ref = run(PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                                max_new_tokens=8, page_size=8,
                                prefill_budget=8))
    ker = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=8, page_size=8,
                            prefill_budget=8, prefix_cache_pages=8,
                            use_kernel=True, interpret=True)
    assert run(ker) == ref
    assert ker.prefix_cache_stats()["requests_hit"] >= 1
    steps = int(ker._c_kernel_steps.value)
    saved = int(ker._c_kernel_bytes.value)
    assert steps > 0 and saved == steps * ker._kernel_bytes_saved
    assert "kubetpu_paged_kernel_steps_total" in ker.metrics_text()


def test_mesh_sharded_paged_server_matches_unsharded(params):
    """Multi-chip paged serving over a {dp:2, tp:2} mesh: params tensor-
    parallel, pool kv-heads on tp — tokens identical to the single-chip
    paged server."""
    from kubetpu.jobs import make_mesh

    mesh = make_mesh({"dp": 2, "tp": 2})
    prompts = [[3, 14, 15, 9, 2, 6], [26, 5]]

    def run(server):
        rids = [server.submit(p) for p in prompts]
        server.drain()
        return [server.result(r) for r in rids]

    plain = run(PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                                  max_new_tokens=8, page_size=8))
    sharded_server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                                       max_new_tokens=8, page_size=8,
                                       mesh=mesh)
    assert "tp" in str(sharded_server.k_pages.sharding.spec)
    assert run(sharded_server) == plain


def test_paged_per_request_sampling(params):
    """Per-request sampling flows through the paged legs too: temp=3
    truncated to top_k=1 == greedy."""
    prompt = [3, 14, 15, 9, 2, 6]
    ref = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=6, page_size=8)
    rr = ref.submit(prompt)
    ref.drain()
    srv = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                            max_new_tokens=6, page_size=8)
    rs = srv.submit(prompt, sampling={"temperature": 3.0, "top_k": 1})
    srv.drain()
    assert srv.result(rs) == ref.result(rr)


# -- windowed (banded) paged serving — round 5 ------------------------------


def test_windowed_paged_greedy_parity_with_dense_server(params):
    """cfg.window > 0 composes with the page pool (the paged.py refusal is
    gone): greedy tokens EXACTLY match DecodeServer's banded read, across
    sequences long enough to wrap the physical page ring several times."""
    import dataclasses

    wcfg = dataclasses.replace(CFG, window=8)
    # lengths chosen to hit the dangerous geometries (review r5): 9 makes
    # bucket padding exceed the physical ring with the first band reaching
    # a page the pad writes would have clobbered; 40 (> ring * page_size)
    # keeps only the LAST ring-many prompt pages live at prefill
    prompts = [[3, 14, 15, 9, 2, 6], [26, 5],
               [35, 8, 9, 7, 9, 3, 2, 1, 4, 11, 13, 2],
               [5, 9, 3, 1, 7, 2, 8, 4, 6],
               [(i * 7) % 60 + 1 for i in range(40)]]
    dense = DecodeServer(wcfg, params, n_slots=2, max_seq=96,
                         max_new_tokens=40)
    paged = PagedDecodeServer(wcfg, params, n_slots=2, max_seq=96,
                              max_new_tokens=40, page_size=4)
    results = {}
    for server, tag in ((dense, "dense"), (paged, "paged")):
        ra = server.submit(prompts[0])
        server.step()
        rb = server.submit(prompts[1])
        server.drain()
        rc = server.submit(prompts[2])
        server.drain()
        rd = server.submit(prompts[3])
        re_ = server.submit(prompts[4])
        server.drain()
        results[tag] = [server.result(r) for r in (ra, rb, rc, rd, re_)]
    assert results["paged"] == results["dense"]


def test_windowed_pages_bounded_by_window_not_seq(params):
    """The compounding memory win: a windowed slot maps only
    ceil(window/ps) + 1 physical pages however long max_seq (and the
    sequence) grows — and they return to the pool on retirement."""
    import dataclasses

    ps = 4
    window = 8
    wcfg = dataclasses.replace(CFG, window=window)
    server = PagedDecodeServer(wcfg, params, n_slots=2, max_seq=256,
                               max_new_tokens=60, page_size=ps, n_pages=8)
    ring = window // ps + 1  # 3 pages
    rid = server.submit(list(range(1, 12)))  # 11-token prompt, decodes 60
    assert server.pages_in_use() == ring
    server.drain()
    assert server.finished(rid)
    out = server.pop_result(rid)
    assert len(out) >= 11 + 1
    assert server.pages_in_use() == 0
    # an unwindowed server with the same shapes could not even admit:
    # worst case needs (11 + 60 + 1)/4 = 18 pages > pool 8
    plain = PagedDecodeServer(CFG, params, n_slots=2, max_seq=256,
                              max_new_tokens=60, page_size=ps, n_pages=8)
    with pytest.raises(ValueError):
        plain.submit(list(range(1, 12)))


def test_windowed_paged_kernel_parity(params):
    """Round-15: the banded-mask kernel lifts the old windowed refusal —
    a windowed paged server under ``use_kernel`` emits exactly the
    gather core's greedy tokens, across ring wraps (prompt longer than
    ring * page_size)."""
    import dataclasses

    wcfg = dataclasses.replace(CFG, window=8)
    prompts = [[3, 14, 15, 9, 2, 6], [26, 5],
               [(i * 7) % 60 + 1 for i in range(40)]]

    def run(server):
        rids = [server.enqueue(p) for p in prompts]
        server.drain()
        return [server.pop_result(r) for r in rids]

    ref = run(PagedDecodeServer(wcfg, params, n_slots=2, max_seq=96,
                                max_new_tokens=12, page_size=4))
    ker = PagedDecodeServer(wcfg, params, n_slots=2, max_seq=96,
                            max_new_tokens=12, page_size=4,
                            use_kernel=True, interpret=True)
    assert run(ker) == ref
    assert ker._c_kernel_steps.value > 0


def test_int8_page_pool_parity_and_bytes(trained_small):
    """kv_int8 page pool: greedy tokens EXACTLY match the int8 dense-cache
    server (the apples-to-apples reference: same quantize-on-write scales,
    only the storage layout differs) across a staggered lifecycle with
    page-boundary crossings — and the pool is ~half the resident bytes,
    so the live-token provisioning and the int8 entries COMPOUND. (Versus
    the bf16 pool the contract is agreement, not exactness: int8 rounding
    legitimately flips near-argmax ties on weak continuations.)"""
    import jax as _jax

    tcfg, params, data = trained_small
    row = [int(t) for t in data[0][0][0]]
    prompts = [row[:6], row[:2], row[:9]]

    def run(server):
        ra = server.submit(prompts[0])
        server.step()
        rb = server.submit(prompts[1])
        server.drain()
        rc = server.submit(prompts[2])
        server.drain()
        return [server.result(r) for r in (ra, rb, rc)]

    dense = PagedDecodeServer(tcfg, params, n_slots=2, max_seq=64,
                              max_new_tokens=12, page_size=8)
    q8 = PagedDecodeServer(tcfg, params, n_slots=2, max_seq=64,
                           max_new_tokens=12, page_size=8, kv_int8=True)
    q8_dense_ref = DecodeServer(tcfg, params, n_slots=2, max_seq=64,
                                max_new_tokens=12, kv_int8=True)
    got = run(q8)
    assert got == run(q8_dense_ref)  # exact: same layout semantics
    bf16 = run(dense)
    agree = sum(a == b for g, r in zip(got, bf16) for a, b in zip(g, r))
    total = sum(len(g) for g in got)
    assert agree / total > 0.9, f"int8 vs bf16 agreement {agree/total}"
    dense_b = sum(x.nbytes for x in _jax.tree.leaves(
        (dense.k_pages, dense.v_pages)))
    q8_b = sum(x.nbytes for x in _jax.tree.leaves((q8.k_pages, q8.v_pages)))
    assert q8_b < 0.6 * dense_b  # f32 pool -> int8 + thin scales
    # Round-15: use_kernel now composes with kv_int8 — the in-kernel
    # dequant bit-matches the gather core's, so the trained-model greedy
    # stream is identical to the int8 gather server's
    q8k = PagedDecodeServer(tcfg, params, n_slots=2, max_seq=64,
                            max_new_tokens=12, page_size=8, kv_int8=True,
                            use_kernel=True, interpret=True)
    assert run(q8k) == got
    assert q8k._c_kernel_steps.value > 0


@pytest.mark.slow
def test_int8_windowed_paged_triple_composition(trained_small):
    """window x paged ring x int8 pool all at once: token-exact vs the
    dense banded DecodeServer — every memory feature stacked.
    Slow: the triple composition compiles its own server variant; each
    pairwise composition keeps a tier-1 parity pin."""
    import dataclasses

    tcfg, params, data = trained_small
    wcfg = dataclasses.replace(tcfg, window=8)
    prompt = [int(t) for t in data[1][0][0][:9]]
    # exact reference: the int8 DENSE banded server — same write-time
    # quantization, only the storage layout (pool ring vs contiguous)
    # differs, so the tokens must be identical
    ref = DecodeServer(wcfg, params, n_slots=2, max_seq=96,
                       max_new_tokens=30, kv_int8=True)
    q8 = PagedDecodeServer(wcfg, params, n_slots=2, max_seq=96,
                           max_new_tokens=30, page_size=4, kv_int8=True)
    rr, rq = ref.submit(prompt), q8.submit(prompt)
    ref.drain(); q8.drain()
    assert ref.result(rr) == q8.result(rq)
    # the ring bound still holds with the int8 pool
    assert q8.pages_in_use() == 0  # retired


def test_paged_steady_state_step_uploads_no_slot_state(params, monkeypatch):
    """Round-10 upload cache, paged edition: the page TABLE rides the
    device-resident mirror too — a steady-state decode step issues zero
    ``jnp.asarray`` uploads, and table mutations (admission mapping new
    pages, retirement releasing them) dirty the mirror so the next step
    re-uploads exactly once."""
    import jax.numpy as jnp

    server = PagedDecodeServer(CFG, params, n_slots=2, max_seq=64,
                               max_new_tokens=30, page_size=8)
    server.submit([1, 2, 3, 4])
    server.step()
    calls = []
    real = jnp.asarray

    def counting(x, *a, **k):
        calls.append(np.shape(x))
        return real(x, *a, **k)

    monkeypatch.setattr(jnp, "asarray", counting)
    for _ in range(3):
        server.step()
    monkeypatch.undo()
    assert calls == [], f"steady-state step re-uploaded host state: {calls}"
    # page-boundary crossings mid-decode map new pages host-side; the
    # mirror must follow (parity tests pin the VALUES; this pins that the
    # invalidation actually fires so the device never reads a stale table)
    server.drain()
    rid2 = server.submit([5] * 9)      # fresh admission re-maps the table
    monkeypatch.setattr(jnp, "asarray", counting)
    server.step()
    monkeypatch.undo()
    assert any(s == np.shape(server._table) for s in calls), calls
    server.drain()
    assert server.finished(rid2)
