"""Round-21 incremental fit index: decision equivalence with the full
predicate sweep (cross-check oracle + twin-cluster replay), staleness
fallbacks, the ``check_invariants`` index/accounting audit, the O(1)
pod->node map, and the incremental occupancy-gauge dirty feed."""

import random

import pytest

from kubetpu.api.types import ContainerInfo, PodInfo
from kubetpu.core import Cluster, SchedulingError
from kubetpu.core.cluster import PriorityKey
from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
from kubetpu.plugintypes import ResourceTPU
from kubetpu.scheduler.fitindex import _compute_entry
from kubetpu.scheduler.meshstate import MILLI_PER_CHIP, FracKey
from kubetpu.scheduler.tpu_scheduler import TpuScheduler


def tpu_pod(name, chips, **extra):
    return PodInfo(
        name=name, requests=dict(extra),
        running_containers={
            "main": ContainerInfo(requests={ResourceTPU: chips})})


def frac_pod(name, milli):
    return PodInfo(name=name, requests={FracKey: milli},
                   running_containers={"main": ContainerInfo()})


def fleet(n, use_fit_index=None):
    c = Cluster(use_fit_index=use_fit_index)
    for i in range(n):
        c.register_node(
            f"n{i:03d}",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-8", slice_uid=f"s{i}")))
    return c


def churn_ops(seed, ops):
    """A deterministic mixed op stream: (kind, payload) tuples shared by
    both twin clusters so their placements are comparable op by op."""
    rng = random.Random(seed)
    out = []
    for i in range(ops):
        r = rng.random()
        if r < 0.30:
            out.append(("release", rng.random()))
        elif r < 0.55:
            out.append(("frac", (f"v{i}", rng.choice([125, 250, 500]))))
        elif r < 0.60:
            out.append(("preempt", f"hi{i}"))
        else:
            out.append(("whole", (f"c{i}", rng.choice([1, 1, 2, 2, 4, 8]))))
    return out


def run_ops(cluster, ops):
    """Apply the op stream; returns the (pod, node) placement log."""
    placed, log = [], []
    for kind, payload in ops:
        if kind == "release":
            if placed:
                j = int(payload * len(placed))
                placed[j], placed[-1] = placed[-1], placed[j]
                cluster.release(placed.pop())
            continue
        if kind == "preempt":
            pod = tpu_pod(payload, 8)
            pod.requests[PriorityKey] = 10
            try:
                got, evicted = cluster.schedule_preempting(pod)
            except SchedulingError:
                continue
            for v in evicted:
                if v.name in placed:
                    placed.remove(v.name)
            placed.append(got.name)
            log.append((got.name, got.node_name))
            continue
        name, arg = payload
        pod = frac_pod(name, arg) if kind == "frac" else tpu_pod(name, arg)
        try:
            got = cluster.schedule(pod)
        except SchedulingError:
            log.append((name, None))
            continue
        placed.append(got.name)
        log.append((got.name, got.node_name))
    return log


def test_twin_cluster_equivalence_under_churn():
    """The load-bearing guarantee: index on (cross-checked) and index
    off place the identical op stream identically — same pod, same
    node, same no-fit outcomes — and both books stay clean."""
    ops = churn_ops(seed=99, ops=500)
    indexed = fleet(24)
    indexed.index_cross_check = True
    plain = fleet(24, use_fit_index=False)
    log_indexed = run_ops(indexed, ops)   # raises on oracle divergence
    log_plain = run_ops(plain, ops)
    assert log_indexed == log_plain
    assert indexed.index_stats["pruned_sweeps"] > 0
    assert indexed.index_stats["cross_checks"] > 0
    assert plain.index_stats["pruned_sweeps"] == 0
    assert indexed.check_invariants() == []
    assert plain.check_invariants() == []


def test_frac_fast_path_picks_tightest_fit_first():
    """A vChip pod must land on the node whose best-fit remainder is
    smallest FLEET-WIDE — the index's ordered path must reproduce the
    sweep's best-fit policy even when that node sorts last by name."""
    c = fleet(4)
    c.index_cross_check = True
    # pin a 750m hold onto the name-LAST node: its 250m remainder is
    # now the only sub-pristine chip in the fleet
    c.schedule(frac_pod("a", 750), candidates=["n003"])
    got = c.schedule(frac_pod("tight", 250))  # exact fit on n003
    assert got.node_name == "n003"  # beats the name-first pristine nodes
    loose = c.schedule(frac_pod("loose", 500))  # no sub-pristine fit
    assert loose.node_name == "n000"  # all-equal scores: name tie-break
    assert c.check_invariants() == []


def test_index_registry_drift_falls_back_to_sweep():
    """STALENESS: an entry missing from the index (simulated desync)
    must not break scheduling — the query detects the registry drift
    and the full sweep runs (fallback_sweeps), still placing
    correctly."""
    c = fleet(6)
    c.fit_index.unregister("n002")  # desync behind the cluster's back
    before = c.index_stats["fallback_sweeps"]
    got = c.schedule(tpu_pod("p", 2))
    assert got.node_name  # placed despite the desync
    assert c.index_stats["fallback_sweeps"] == before + 1
    # the audit reports the hole until the node is re-registered
    problems = c.check_invariants()
    assert any("fit index" in p and "n002" in p for p in problems)
    c._index_register("n002")
    assert c.check_invariants() == []


def test_check_invariants_catches_corrupted_entry():
    c = fleet(3)
    got = c.schedule(tpu_pod("p", 4))
    # freshen first: a dirty entry is EXEMPT from the value audit (lazy
    # staleness is the design) — corruption of a CLEAN entry is not
    c.fit_index.ensure_fresh(c._index_alloc)
    entry = c.fit_index.entries[got.node_name]
    entry.free_tpu += 2  # books say 4, index now says 6
    problems = c.check_invariants()
    assert any("drifted" in p for p in problems)
    # the repair path: mark dirty -> next query recomputes lazily
    c.fit_index.mark_dirty(got.node_name)
    c.schedule(tpu_pod("q", 1))
    assert c.check_invariants() == []


def test_kill_switch_env(monkeypatch):
    monkeypatch.setenv("KUBETPU_NO_FIT_INDEX", "1")
    c = Cluster()
    assert c.use_fit_index is False
    monkeypatch.delenv("KUBETPU_NO_FIT_INDEX")
    assert Cluster().use_fit_index is True


def test_custom_scheduler_disables_frac_caps_but_not_pruning():
    """A non-stock scheduler type must disable the exact-cap frac fast
    path (its scores are unknown to the index) while the set prune and
    the placements stay correct."""

    class MyTpu(TpuScheduler):
        pass

    c = Cluster(schedulers=[MyTpu()])
    for i in range(3):
        c.register_node(
            f"n{i:03d}",
            device=new_fake_tpu_dev_manager(
                make_fake_tpus_info("v5e-8", slice_uid=f"s{i}")))
    assert c._caps_ok is False
    c.index_cross_check = True
    c.schedule(frac_pod("a", 750))
    got = c.schedule(frac_pod("b", 250))  # oracle raises on divergence
    assert got.node_name
    assert c.index_stats["pruned_sweeps"] > 0
    assert c.check_invariants() == []


def test_whole_free_prune_skips_fractionalized_nodes():
    """A node whose every chip carries a vChip occupant advertises a
    full TPU scalar but can host no whole-chip gang — the whole-free
    bucket key must reflect that (and the decision must match the
    sweep, which rejects it on geometry)."""
    c = fleet(2)
    c.index_cross_check = True
    for i in range(8):  # one 500m occupant per chip of n000 (best-fit
        c.schedule(frac_pod(f"f{i}", 600))  # 600m can't share a chip)
    assert c.pod_node("f0") == "n000"
    c.fit_index.ensure_fresh(c._index_alloc)
    entry = c.fit_index.entries["n000"]
    assert entry.whole_free == 0 and entry.free_tpu == 8
    got = c.schedule(tpu_pod("gang", 8))  # must go to n001, no divergence
    assert got.node_name == "n001"
    assert c.check_invariants() == []


def test_pod_map_o1_lookup_and_audit():
    c = fleet(3)
    got = c.schedule(tpu_pod("p", 2))
    assert c.pod_node("p") == got.node_name
    assert c.pod_node("ghost") is None
    # corrupt the map: the audit must flag it, the lookup must repair it
    c._pod_node["p"] = "n999"
    problems = c.check_invariants()
    assert any("pod" in p and "p" in p for p in problems)
    assert c.pod_node("p") == got.node_name  # fallback sweep repaired
    assert c.check_invariants() == []
    c.release("p")
    assert c.pod_node("p") is None
    with pytest.raises(KeyError):
        c.release("p")


def test_occupancy_dirty_feed_is_incremental():
    c = fleet(4)
    c.pop_dirty_occupancy()  # drain registration dirt
    got = c.schedule(tpu_pod("p", 1))
    dirty = c.pop_dirty_occupancy()
    assert got.node_name in dirty
    assert len(dirty) == 1  # ONLY the touched node, not the fleet
    assert c.pop_dirty_occupancy() == set()  # drained
    c.release("p")
    assert c.pop_dirty_occupancy() == {got.node_name}
    c.remove_node("n003")
    assert "n003" in c.pop_dirty_occupancy()


def test_entry_recompute_matches_accounting_after_lifecycle():
    """refresh_node / drain replace or rewrite the allocatable dict —
    the re-hooked index must converge to a fresh recompute."""
    c = fleet(3)
    c.schedule(tpu_pod("p", 2))
    c.schedule(frac_pod("v", 250))
    c.refresh_node("n000")
    c.drain("n001")
    c.cordon("n001", on=False)
    c.fit_index.ensure_fresh(c._index_alloc)
    for name, node in c.nodes.items():
        assert c.fit_index.entries[name] == _compute_entry(
            node.info.allocatable), name
    assert c.check_invariants() == []


def test_dropped_cluster_not_pinned_by_dirty_hooks():
    """The meshstate dirty-hook registry holds its OWNER weakly: dropping
    a cluster must let the whole node graph collect even though its
    allocatable dicts were hook-registered and never explicitly
    unregistered (a bench building throwaway 512-node fleets must not
    accrete them in process memory — that pinning once pushed a
    bench_gate record run into GC stalls long enough to blow a 120s
    HTTP timeout downstream)."""
    import gc
    import weakref

    c = fleet(4)
    c.schedule(tpu_pod("p", 2))
    c.schedule(frac_pod("v", 250))
    ref = weakref.ref(c)
    del c
    gc.collect()
    assert ref() is None
