"""Round-11 structured event log: bounded ring semantics, JSONL schema
+ validation, trace cross-linking, the sink tee, multi-log merging, and
the ``GET /events`` wire surface on the exporter and both wire servers."""

import json

import pytest

from kubetpu.obs import span
from kubetpu.obs.events import (
    EventLog,
    event_log,
    merge_events,
    validate_events_jsonl,
)


def test_ring_bounds_and_drop_counter():
    log = EventLog(capacity=4)
    for i in range(7):
        log.emit("tick", i=i)
    assert len(log) == 4
    assert log.dropped == 3
    evs = log.events()
    assert [e["i"] for e in evs] == [3, 4, 5, 6]       # oldest-first tail
    assert [e["seq"] for e in evs] == [3, 4, 5, 6]     # seq keeps counting
    with pytest.raises(ValueError):
        EventLog(capacity=0)


def test_kind_filter_limit_and_counts():
    log = EventLog()
    for i in range(3):
        log.emit("admit", rid=f"r{i}")
    log.emit("retire", rid="r0")
    assert [e["rid"] for e in log.events(kind="admit", limit=2)] == \
        ["r1", "r2"]
    assert log.events(limit=0) == []          # not "[-0:] = everything"
    assert log.counts() == {"admit": 3, "retire": 1}


def test_component_and_field_coercion():
    log = EventLog(component="serving")
    ev = log.emit("admit", rid="r0", obj=object(), none=None, flag=True)
    assert ev["component"] == "serving"
    assert isinstance(ev["obj"], str)       # non-JSON values coerced
    assert ev["none"] is None and ev["flag"] is True
    # a per-call component overrides the log's
    assert log.emit("x", component="agent:h0")["component"] == "agent:h0"


def test_trace_id_cross_link():
    log = EventLog()
    with span("unit.op") as s:
        ev = log.emit("inside")
    outside = log.emit("outside")
    assert ev["trace_id"] == s.trace_id
    assert "trace_id" not in outside


def test_jsonl_roundtrip_and_validation():
    log = EventLog(component="c")
    log.emit("a", x=1)
    log.emit("b", y="two")
    text = log.to_jsonl()
    assert validate_events_jsonl(text) == []
    lines = [json.loads(line) for line in text.splitlines()]
    assert [e["kind"] for e in lines] == ["a", "b"]
    # the validator actually catches breakage
    bad = 'not json\n{"ts": "late", "seq": 1.5, "kind": 3}\n[1, 2]\n'
    problems = validate_events_jsonl(bad)
    assert len(problems) == 5, problems     # not-JSON, ts, seq, kind, not-obj


def test_sink_tee_and_survives_close(tmp_path):
    path = tmp_path / "events.jsonl"
    log = EventLog()
    log.set_sink(str(path))
    log.emit("a", n=1)
    log.set_sink(None)
    log.emit("b", n=2)              # after close: ring only
    text = path.read_text()
    assert validate_events_jsonl(text) == []
    assert '"kind": "a"' in text and '"kind": "b"' not in text
    assert len(log) == 2


def test_merge_events_orders_and_stamps():
    a, b = EventLog(), EventLog(component="b")
    a.emit("first")
    b.emit("second")
    a.emit("third")
    merged = merge_events({"a": a, "b": b})
    assert [e["kind"] for e in merged] == ["first", "second", "third"]
    assert merged[0]["component"] == "a"        # stamped by merge
    assert merged[1]["component"] == "b"        # the log's own wins
    assert merge_events({"a": a, "b": b}, limit=1)[0]["kind"] == "third"


def test_process_default_log_exists():
    assert event_log() is event_log()
    before = len(event_log())
    event_log().emit("unit_test_marker")
    assert len(event_log()) == before + 1


def test_exporter_serves_events_with_filters():
    import urllib.request

    from kubetpu.obs.exporter import MetricsServer
    from kubetpu.obs.registry import Registry

    log = EventLog(component="serving")
    log.emit("admit", rid="r0")
    log.emit("retire", rid="r0")
    log.emit("admit", rid="r1")
    srv = MetricsServer({"replica": Registry()}, events=log)
    srv.start()
    try:
        def get(path):
            with urllib.request.urlopen(srv.address + path, timeout=5) as r:
                return r.read().decode()

        body = get("/events")
        assert validate_events_jsonl(body) == []
        assert len(body.splitlines()) == 3
        only_admits = get("/events?kind=admit")
        assert len(only_admits.splitlines()) == 2
        assert '"retire"' not in only_admits
        tail = get("/events?kind=admit&limit=1")
        assert json.loads(tail)["rid"] == "r1"
    finally:
        srv.shutdown()


def test_agent_and_controller_serve_events():
    """The wire servers' /events: the agent records allocates, the
    controller records registrations — both schema-valid JSONL."""
    import urllib.request

    from kubetpu.api.types import ContainerInfo, PodInfo
    from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
    from kubetpu.plugintypes import ResourceTPU
    from kubetpu.wire import ControllerServer, NodeAgentServer
    from kubetpu.wire.controller import pod_to_json
    from kubetpu.wire.httpcommon import request_json

    agent = NodeAgentServer(
        new_fake_tpu_dev_manager(make_fake_tpus_info("v5e-16")), "ev-h0")
    controller = ControllerServer(poll_interval=3600)
    controller.start()
    agent.start()
    try:
        request_json(controller.address + "/nodes", {"url": agent.address})
        request_json(
            controller.address + "/pods",
            {"pod": pod_to_json(PodInfo(
                name="ev-p0",
                running_containers={"main": ContainerInfo(
                    requests={ResourceTPU: 4})},
            ))},
            idempotency_key="ev-p0")
        controller.poll_once()

        def get(base, path):
            with urllib.request.urlopen(base + path, timeout=5) as r:
                return r.read().decode()

        abody = get(agent.address, "/events")
        assert validate_events_jsonl(abody) == []
        allocates = [json.loads(line) for line in abody.splitlines()
                     if '"allocate"' in line]
        assert allocates and allocates[0]["component"] == "agent:ev-h0"
        # the allocate ran inside the wire-propagated submit span
        assert "trace_id" in allocates[0]
        cbody = get(controller.address, "/events")
        assert validate_events_jsonl(cbody) == []
        assert '"kind": "register"' in cbody
    finally:
        controller.shutdown()
        agent.shutdown()
