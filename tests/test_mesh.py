"""Tests for the ICI torus mesh model (kubetpu/plugintypes/mesh.py) — the
TPU replacement for NVLink tree locality (SURVEY.md §7 step 2)."""

import pytest

from kubetpu.plugintypes import mesh
from kubetpu.plugintypes.mesh import TOPOLOGIES, contiguity_score, find_contiguous_block


def test_registry_shapes():
    v5e8 = TOPOLOGIES["v5e-8"]
    assert v5e8.mesh_shape == (2, 4)
    assert v5e8.num_chips == 8
    assert v5e8.num_hosts == 1
    v5e64 = TOPOLOGIES["v5e-64"]
    assert v5e64.num_chips == 64
    assert v5e64.num_hosts == 8
    v5e256 = TOPOLOGIES["v5e-256"]
    assert v5e256.wrap == (True, True)  # full 16x16 torus wraps


def test_chip_index_roundtrip():
    t = TOPOLOGIES["v5e-64"]
    for i, c in enumerate(t.coords()):
        assert t.chip_index(c) == i
        assert t.index_coord(i) == c


def test_host_blocks_partition_mesh():
    t = TOPOLOGIES["v5e-64"]
    seen = set()
    for h in range(t.num_hosts):
        coords = t.host_coords(h)
        assert len(coords) == 8
        for c in coords:
            assert t.host_of(c) == h
            seen.add(c)
    assert len(seen) == 64


def test_neighbors_wrap_and_edges():
    t = TOPOLOGIES["v5e-8"]  # 2x4, no wrap
    assert set(t.neighbors((0, 0))) == {(1, 0), (0, 1)}
    t256 = TOPOLOGIES["v5e-256"]  # 16x16 torus
    assert (0, 15) in t256.neighbors((0, 0))
    assert (15, 0) in t256.neighbors((0, 0))


def test_contiguity_square_beats_line():
    # The SURVEY §7 "hard part": 2x2 block vs 1x4 line of 4 chips must NOT
    # look identical. 2x2 has 4 internal links, 1x4 has 3.
    t = TOPOLOGIES["v5e-16"]
    square = [(0, 0), (0, 1), (1, 0), (1, 1)]
    line = [(0, 0), (0, 1), (0, 2), (0, 3)]
    assert contiguity_score(square, t) == 1.0
    assert contiguity_score(line, t) == pytest.approx(3 / 4)
    scattered = [(0, 0), (0, 2), (2, 0), (2, 2)]
    assert contiguity_score(scattered, t) == 0.0


def test_contiguity_singletons():
    t = TOPOLOGIES["v5e-8"]
    assert contiguity_score([(0, 0)], t) == 1.0
    assert contiguity_score([], t) == 1.0


def test_find_block_exact_rectangle():
    t = TOPOLOGIES["v5e-8"]
    free = set(t.coords())
    got = find_contiguous_block(free, 4, t)
    assert got is not None
    coords, score = got
    assert len(coords) == 4 and score == 1.0
    assert set(coords) == {(0, 0), (0, 1), (1, 0), (1, 1)}  # 2x2, not 1x4


def test_find_block_avoids_taken_chips():
    t = TOPOLOGIES["v5e-8"]
    free = set(t.coords()) - {(0, 0), (1, 0)}  # left column taken
    got = find_contiguous_block(free, 4, t)
    assert got is not None
    coords, score = got
    assert score == 1.0
    assert set(coords).isdisjoint({(0, 0), (1, 0)})


def test_find_block_fallback_non_rectangular():
    t = TOPOLOGIES["v5e-8"]
    # Free: an L of 3 chips + 1 isolated; ask for 3 -> the connected L wins.
    free = {(0, 0), (0, 1), (1, 0), (1, 3)}
    got = find_contiguous_block(free, 3, t)
    assert got is not None
    coords, score = got
    assert set(coords) == {(0, 0), (0, 1), (1, 0)}
    assert score == pytest.approx(2 / 2)  # ideal 3-chip block in 2x4 = line of 2 links


def test_find_block_insufficient():
    t = TOPOLOGIES["v5e-8"]
    assert find_contiguous_block({(0, 0)}, 2, t) is None
    assert find_contiguous_block(set(), 1, t) is None
    assert find_contiguous_block(set(), 0, t) == ([], 1.0)


def test_find_block_full_pod_gang():
    # The north-star shape: 256 chips on a v5e-256 pod.
    t = TOPOLOGIES["v5e-256"]
    got = find_contiguous_block(set(t.coords()), 256, t)
    assert got is not None
    coords, score = got
    assert len(coords) == 256 and score == 1.0


def test_wraparound_rectangle_placement():
    t = TOPOLOGIES["v5e-256"]
    # Occupy a middle band so only a wrapped block fits in columns.
    free = {c for c in t.coords() if c[1] in (0, 1, 14, 15)}
    got = find_contiguous_block(free, 64, t)
    assert got is not None
    coords, score = got
    assert len(coords) == 64
    assert score == 1.0  # 16x4 wrapped around the column seam


def test_max_internal_links_wrap_bonus():
    t = TOPOLOGIES["v5e-256"]
    # Full torus: every chip has 4 links -> 512 total.
    assert mesh.max_internal_links(256, t) == 512
    assert contiguity_score(set(t.coords()), t) == 1.0
