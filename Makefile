# kubetpu build (analog of the reference Makefile: two plugins + two CLIs;
# here the plugins are Python modules, so the native artifact is tpuinfo).
BUILD_DIR ?= _output
CXX ?= g++
CXXFLAGS ?= -O2 -Wall -Wextra -std=c++17

.PHONY: all
all: tpuinfo gpuinfo dataio

.PHONY: tpuinfo
tpuinfo: $(BUILD_DIR)/tpuinfo

$(BUILD_DIR)/tpuinfo: kubetpu/tpuinfo/tpuinfo.cc kubetpu/native/json_escape.h
	@mkdir -p $(BUILD_DIR)
	$(CXX) $(CXXFLAGS) -o $@ $<

.PHONY: gpuinfo
gpuinfo: $(BUILD_DIR)/gpuinfo

$(BUILD_DIR)/gpuinfo: kubetpu/gpuinfo/gpuinfo.cc kubetpu/native/json_escape.h
	@mkdir -p $(BUILD_DIR)
	$(CXX) $(CXXFLAGS) -o $@ $<

.PHONY: dataio
dataio: $(BUILD_DIR)/libkubetpu_dataio.so

$(BUILD_DIR)/libkubetpu_dataio.so: kubetpu/dataio/loader.cc
	@mkdir -p $(BUILD_DIR)
	$(CXX) $(CXXFLAGS) -shared -fPIC -o $@ $<

.PHONY: test
test: tpuinfo gpuinfo dataio
	python -m pytest tests/ -x -q

# seeded fault-injection soaks + the resilience suite (both race soaks
# are slow-marked for the tier-1 wall budget — this target is where
# they run, short then the 30% long one). lint runs
# FIRST (a chaos run over code that violates the wire/lock invariants
# proves the wrong thing — a raw urlopen is invisible to the very faults
# the soak injects), then obs-check (a chaos run whose faults are
# invisible proves nothing), then prefix-check (a chaos run over a pool
# the prefix tree corrupted proves the wrong thing), then spec-check
# (speculative rounds must be invisible in the output stream before
# chaos means anything), then router-check (the data plane must route
# token-exactly and never double-admit under the same faults), then
# lora-check (every packed tenant must decode token-exactly vs its
# merged model while adapters hot-load and LRU-evict under the same
# faults), then migrate-check (a live slot handoff must resume token-exactly and
# at-most-once under faults on the transfer leg), then crash-check
# (a SIGKILLed controller or replica must recover to the exact
# pre-crash state — journal replay, boot-nonce takeover, crash
# replace), then sched-check (the fit index must never change a
# placement decision — cross-checked churn, a pure-sweep twin replay,
# and a deliberate-desync audit probe), then bench-gate in smoke mode
# (a chaos pass that silently regressed serving throughput still fails
# the round).
.PHONY: chaos
chaos: lint obs-check prefix-check spec-check router-check lora-check \
		migrate-check disagg-check pack-check tier-check crash-check \
		sched-check bench-gate-smoke
	python -m pytest tests/test_chaos.py tests/test_resilience.py \
		tests/test_race_soak.py -q

# static invariant lint (Rounds 12–13, kubetpu/analysis): rules
# KTP001–KTP010 over kubetpu/ + scripts/, exits non-zero on any finding
# not covered by an inline `# ktlint: disable=` or the committed
# lint_baseline.json ratchet — and (CI mode, scripts/lint.py) on a
# STALE baseline whose budget outlived its findings
.PHONY: lint
lint:
	python scripts/lint.py

# diff-scoped lint for the inner loop: the whole tree is still parsed
# (the flow rules need global context) but only findings in files git
# sees as changed fail — the gate's failure surface scales with the
# diff as the repo grows
.PHONY: lint-changed
lint-changed:
	python -m kubetpu.analysis --changed-only

# deliberately regenerate the ratchet from the current tree. The diff of
# lint_baseline.json must only ever SHRINK counts — review enforces it,
# and tests/test_analysis.py asserts the repo lints clean against the
# committed file.
.PHONY: lint-baseline
lint-baseline:
	python -m kubetpu.analysis --write-baseline

# bench regression gate: compare the newest BENCH_r0*.json against its
# predecessor and fail on a >15% regression in any shared storm metric
# (decode tok/s up-is-good; TTFT p50 / ITL p99 down-is-good). Run
# `make bench-gate-record` first in a round to measure + persist the
# round's BENCH_r0N.json.
.PHONY: bench-gate
bench-gate:
	python scripts/bench_gate.py

# smoke mode re-measures a tiny storm in-process and gates it against the
# newest persisted round — fast enough to ride `make chaos`. The wider
# threshold absorbs co-tenant wall-clock noise (uniform ~15-20% swings
# observed on shared machines); the round-to-round file gate above stays
# at the strict 15%.
.PHONY: bench-gate-smoke
bench-gate-smoke:
	python scripts/bench_gate.py --smoke --threshold 0.35

.PHONY: bench-gate-record
bench-gate-record:
	python scripts/bench_gate.py --record

# paged speculative-decoding oracle: greedy parity of draft+verify rounds
# vs plain paged decode (monolithic + chunked + prefix-hit, f32 + int8),
# the pool accounting invariant after every drain, adaptive-gamma
# convergence, and the self-draft tokens/round ceiling
.PHONY: spec-check
spec-check:
	python scripts/spec_check.py

# shared-prefix KV reuse oracle: cold-vs-warm token parity through
# prefix-cache hits on a short shared-system-prompt storm, plus the pool
# accounting invariant (free + slot-owned + tree-owned == n_pages,
# refcounts == live pins) after every drain
.PHONY: prefix-check
prefix-check:
	python scripts/prefix_check.py

# data-plane routing oracle (Round-14): router + 2 paged replicas under
# >=10% injected wire faults — greedy token parity vs direct serving,
# zero double-admissions through the idempotency replay window, a
# stitched router->replica trace, and the pool invariant per replica
.PHONY: router-check
router-check:
	python scripts/router_check.py

# multi-tenant adapter oracle (Round-22): router + 2 packed multi-LoRA
# replicas under >=10% injected drop/503/partial on the adapter
# hot-load leg — per-tenant greedy parity vs merge_lora through
# hot-load churn and LRU eviction under pressure, replays never
# double-resident, evicted names refuse (never serve stale factors),
# and the adapter-directory oracle (check_invariants) per drain
.PHONY: lora-check
lora-check:
	python scripts/lora_check.py

# live-KV-migration oracle (Round-16): router + 2 paged replicas,
# rolling /migrate_out sweeps under >=10% injected faults on the
# /migrate_in leg — migrated tokens byte-equal to a quiet unmigrated
# run, committed handoffs == committed restores (zero double-restores;
# a forged stale epoch must fence 409), admissions == logical requests,
# a stitched source->target handoff trace, pool invariants on BOTH
# replicas
.PHONY: migrate-check
migrate-check:
	python scripts/migrate_check.py

# fractional-packing oracle (Round-18): a mixed vChip + whole-chip
# workload through the real Cluster — the packing invariants
# (Σ fractions <= 1.0 per chip, exact capacity restoration on release
# AND preemption), no whole-chip gang starvation behind fractional
# confetti, and greedy token parity of a pool_frac-packed paged
# replica vs an unpacked one
.PHONY: pack-check
pack-check:
	python scripts/pack_check.py

# tiered-KV-cache oracle (Round-19): HBM -> host spill/fill parity on a
# 3-family storm overflowing the HBM tree budget, cross-replica span
# fetch under >=10% injected drop/503/partial on the /prefix_fetch leg
# (parity always; the fetch ledger accounts for every attempt), and the
# dark-peer / retry-budget degrade probes — tiering may only REMOVE
# prefill work, never change a token
.PHONY: tier-check
tier-check:
	python scripts/tier_check.py

# disaggregated prefill/decode oracle (Round-17): router + 1 prefill +
# 2 decode replicas under >=10% injected faults on the KV-stream leg —
# routed tokens byte-equal a quiet colocated run, committed handoffs ==
# requests == fleet-wide admissions (zero double-admissions), pages
# actually streamed mid-prefill (the pipelining), warm decode-side
# prefix pages never shipped, a stitched prefill->decode handoff trace,
# pool invariants on all three pools
.PHONY: disagg-check
disagg-check:
	python scripts/disagg_check.py

# crash-tolerance oracle (Round-20): controller SIGKILL + cold restart
# (journal replay to the exact pre-crash state, torn WAL tail dropped,
# orphaned agent allocation freed, invariants clean before the wire
# reports ready, idempotent second replay), replica SIGKILL mid-storm
# with a same-name takeover (boot-nonce fencing, stale pins dropped,
# token parity, admissions == logical requests), and the autoscaler's
# crash-replace reap path (replacement booted despite cooldown)
.PHONY: crash-check
crash-check:
	python scripts/crash_check.py

# fit-index equivalence oracle (Round-21): 128-host fake-fleet churn
# (whole-chip + vChip + gangs + preemption + cordon/drain/refresh/
# remove) with the cross-check oracle armed — every index-pruned sweep
# shadowed by the reference full sweep; a pure-sweep twin cluster
# replays the identical op stream and must place identically; a
# deliberately desynced index entry must be caught by
# check_invariants and repaired by the dirty path
.PHONY: sched-check
sched-check:
	python scripts/sched_check.py

# observability smoke oracle: controller + 2 fake agents, scrape the
# federated /metrics, fail on malformed Prometheus text / missing
# required series / an unstitched submit trace
.PHONY: obs-check
obs-check:
	python scripts/obs_check.py

.PHONY: bench
bench: tpuinfo
	python bench.py

.PHONY: schedsim
schedsim:
	python -m kubetpu.cli.schedsim

.PHONY: bench-adversarial
bench-adversarial:
	python -m kubetpu.cli.schedsim --config 8 9 10 11 12 13 14

.PHONY: demo
demo:
	python examples/train_demo.py

.PHONY: multislice-demo
multislice-demo:
	python examples/multislice_demo.py

.PHONY: text-serve-demo
text-serve-demo:
	python examples/text_serve_demo.py

.PHONY: train-demo-wire
train-demo-wire:
	python examples/train_demo.py --wire

.PHONY: wire-demo
wire-demo:
	python examples/wire_demo.py

.PHONY: serve-demo
serve-demo:
	python examples/serve_demo.py

.PHONY: clean
clean:
	rm -rf $(BUILD_DIR)/*
