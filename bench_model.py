#!/usr/bin/env python3
"""Model-performance benchmark: the framework's OWN workload numbers on the
local accelerator (VERDICT r1 #2 — "fast" must be measured, not asserted).

Measures, on whatever chip JAX sees (designed for one TPU v5e):

1. training throughput — full train step (fwd+bwd+adamw) of the flagship
   decoder transformer, bf16 + flash attention + remat, seq >= 2k:
   tokens/sec, step time, and achieved MFU vs the chip's bf16 peak;
2. flash-vs-dense attention speedup — Pallas flash attention core vs the
   XLA dense softmax core at growing sequence lengths;
3. decode throughput — KV-cached autoregressive generation tokens/sec,
   MHA vs grouped-query (n_kv_heads=4) at the same model size.

Prints one JSON line per measurement; --out FILE also writes them to a
checked-in artifact (BENCH_MODEL.json). --smoke runs a tiny config (CI /
CPU-mesh sanity; numbers are meaningless there, structure is identical).

    python bench_model.py [--smoke] [--steps N] [--out BENCH_MODEL.json]
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp


# bf16 peak TFLOP/s per chip by TPU generation (public spec sheets).
# Ordered: device_kind strings are e.g. "TPU v5 lite" (v5e), "TPU v5p",
# "TPU v4" — match the most specific marker first.
PEAK_BF16 = [
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v5p", 459e12), ("v4", 275e12),
]


def flagship_cfg(smoke: bool):
    from kubetpu.jobs import ModelConfig

    if smoke:
        return ModelConfig(vocab=256, d_model=128, n_layers=2, n_heads=4,
                           d_ff=256, max_seq=512, dtype=jnp.bfloat16, remat=True)
    # ~0.75B params: fits one v5e (16 GiB) with adamw + remat at seq 2048
    return ModelConfig(vocab=32000, d_model=2048, n_layers=12, n_heads=16,
                       d_ff=5632, max_seq=4096, dtype=jnp.bfloat16, remat=True)


def param_count(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def chip_peak_flops():
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    for key, peak in PEAK_BF16:
        if key in kind:
            return peak
    return None


def train_throughput(cfg, batch, seq, steps, attention):
    from kubetpu.jobs import init_state, make_mesh, make_train_step

    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1}, devices=jax.devices()[:1])
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    n_params = param_count(state.params)
    step = make_train_step(cfg, mesh, optimizer=opt, attention=attention)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab,
                                jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)

    state, loss = step(state, tokens, targets)  # compile
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step(state, tokens, targets)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / steps

    tokens_per_s = batch * seq / dt
    # FLOPs/token for fwd+bwd: 6*P (matmul params) + 12*L*D*S (causal
    # attention scores+values, fwd 4*L*D*S and bwd 2x) — the PaLM appendix
    # accounting. Remat re-computes the fwd once more: +50% of the fwd
    # third, i.e. x(8/6) on the model term when counting HARDWARE flops;
    # MFU convention counts MODEL flops, so remat overhead shows up as
    # lower MFU, which is what we want to observe.
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq
    peak = chip_peak_flops()
    mfu = tokens_per_s * flops_per_token / peak if peak else None
    del state
    return {
        "metric": "train_tokens_per_s",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s",
        "step_ms": round(dt * 1e3, 2),
        "batch": batch,
        "seq": seq,
        "params": n_params,
        "attention": attention,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "device": getattr(jax.devices()[0], "device_kind", str(jax.devices()[0])),
    }


def flash_vs_dense(cfg, seqs):
    from kubetpu.jobs.model import dense_causal_attention

    if jax.default_backend() == "cpu":
        return []  # Pallas TPU kernels don't run on the CPU backend
    from kubetpu.ops import flash_attention

    out = []
    b, h, d = (2, cfg.n_heads, cfg.head_dim)
    for seq in seqs:
        q, k, v = (
            jax.random.normal(jax.random.PRNGKey(i), (b, seq, h, d), jnp.bfloat16)
            for i in range(3)
        )
        flash = jax.jit(lambda q, k, v: flash_attention(q, k, v))
        dense = jax.jit(dense_causal_attention)

        def timeit(fn):
            r = fn(q, k, v)
            jax.block_until_ready(r)
            t0 = time.perf_counter()
            for _ in range(10):
                r = fn(q, k, v)
            jax.block_until_ready(r)
            return (time.perf_counter() - t0) / 10 * 1e3

        fms = timeit(flash)
        try:
            dms = timeit(dense)
        except Exception:  # noqa: BLE001 — dense OOMs first at long seq
            dms = None
        out.append({
            "metric": "flash_vs_dense_speedup",
            "seq": seq,
            "flash_ms": round(fms, 3),
            "dense_ms": round(dms, 3) if dms else None,
            "value": round(dms / fms, 2) if dms else None,
            "unit": "x",
        })
    return out


def decode_throughput(cfg, batch, prompt_len, gen_steps, n_kv_heads):
    import dataclasses

    from kubetpu.jobs import init_params
    from kubetpu.jobs.decode import make_generate

    dcfg = dataclasses.replace(cfg, n_kv_heads=n_kv_heads, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len), 0,
                                dcfg.vocab, jnp.int32)
    gen = make_generate(dcfg)
    out = gen(params, prompt, jax.random.PRNGKey(2), gen_steps)  # compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = gen(params, prompt, jax.random.PRNGKey(3), gen_steps)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    del params
    return {
        "metric": "decode_tokens_per_s",
        "value": round(batch * gen_steps / dt, 1),
        "unit": "tokens/s",
        "batch": batch,
        "prompt_len": prompt_len,
        "gen_steps": gen_steps,
        "n_kv_heads": n_kv_heads or cfg.n_heads,
    }


def speculative_throughput(cfg, batch, prompt_len, gen_steps, gamma):
    import dataclasses

    from kubetpu.jobs import init_params
    from kubetpu.jobs.speculative import make_speculative_generate

    tcfg = dataclasses.replace(cfg, remat=False)
    # draft: a quarter-depth, quarter-width shrink of the target
    dcfg = dataclasses.replace(
        tcfg,
        d_model=max(64, cfg.d_model // 4),
        n_layers=max(1, cfg.n_layers // 4),
        n_heads=max(1, cfg.n_heads // 4),
        d_ff=max(128, cfg.d_ff // 4),
    )
    t_params = init_params(jax.random.PRNGKey(0), tcfg)
    d_params = init_params(jax.random.PRNGKey(7), dcfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len), 0,
                                tcfg.vocab, jnp.int32)
    gen = make_speculative_generate(tcfg, dcfg, gamma)
    out, accept = gen(t_params, d_params, prompt, gen_steps)  # compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out, accept = gen(t_params, d_params, prompt, gen_steps)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    del t_params, d_params
    return {
        "metric": "speculative_decode_tokens_per_s",
        "value": round(batch * gen_steps / dt, 1),
        "unit": "tokens/s",
        "batch": batch,
        "gen_steps": gen_steps,
        "gamma": gamma,
        "mean_tokens_per_round": round(float(accept), 2),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config (structure check; numbers meaningless)")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--out", default=None, help="also write JSON lines to FILE")
    args = ap.parse_args()

    if args.smoke:
        # Smoke must run where a sitecustomize pins JAX to a hardware
        # platform (tests/conftest.py documents the same workaround).
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — backend already initialized
            pass

    cfg = flagship_cfg(args.smoke)
    results = []

    if args.smoke:
        batch, seq = 2, 256
        seqs = [256]
        dec = (2, 16, 8)
    else:
        batch, seq = 4, 2048
        seqs = [2048, 4096, 8192]
        dec = (8, 128, 128)

    results.append(train_throughput(cfg, batch, seq, args.steps, "flash"
                                    if jax.default_backend() != "cpu" else "dense"))
    results.extend(flash_vs_dense(cfg, seqs))
    results.append(decode_throughput(cfg, *dec, n_kv_heads=0))
    results.append(decode_throughput(cfg, *dec, n_kv_heads=4 if not args.smoke else 2))
    results.append(speculative_throughput(cfg, *dec, gamma=4))

    for r in results:
        print(json.dumps(r), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            for r in results:
                f.write(json.dumps(r) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
